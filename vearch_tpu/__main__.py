"""Role launcher: `python -m vearch_tpu --role master|ps|router|standalone`.

The reference ships one binary that runs any combination of roles by CLI
tag (reference: cmd/vearch/startup.go:87,112-120). Same shape here; each
role blocks until SIGINT.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


ELASTIC_VERBS = ("rebalance", "drain", "split", "migrate", "plan", "jobs")


def main(argv: list[str] | None = None) -> int:
    from vearch_tpu.utils import apply_jax_platform_env

    apply_jax_platform_env()

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] in ELASTIC_VERBS:
        # operator verbs (`vearch_tpu rebalance`, `vearch_tpu drain 3`)
        # delegate to the elasticity CLI — same binary, no role daemon
        from vearch_tpu.tools.elastic_cli import main as elastic_main

        return elastic_main(argv)
    if argv and argv[0] == "doctor":
        # cluster doctor: fan out, collect evidence, check the standing
        # runtime invariants, exit non-zero on any violation
        from vearch_tpu.obs.doctor import main as doctor_main

        return doctor_main(argv[1:])

    ap = argparse.ArgumentParser(prog="vearch_tpu")
    ap.add_argument("--role", default="standalone",
                    choices=["master", "ps", "router", "standalone"])
    ap.add_argument("--conf", default=None,
                    help="TOML config file (reference: -conf config.toml)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--master-addr", default=None,
                    help="host:port of the master (ps/router roles)")
    ap.add_argument("--data-dir", default="./vearch_data")
    ap.add_argument("--auth", action="store_true")
    ap.add_argument("--grpc-port", type=int, default=None,
                    help="router only: serve gRPC next to HTTP "
                         "(reference: router rpc_port)")
    ap.add_argument("--root-password", default="secret")
    ap.add_argument("--n-ps", type=int, default=1,
                    help="partition servers in standalone mode")
    ap.add_argument("--node-id", type=int, default=1,
                    help="master only: this replica's id in a "
                         "multi-master metadata raft")
    ap.add_argument("--peers", default=None,
                    help="master only: multimaster peer map, "
                         "'1=host:port,2=host:port,...' (reference: "
                         "embedded-etcd initial-cluster)")
    args = ap.parse_args(argv)

    from vearch_tpu.utils import log

    if args.conf:
        from vearch_tpu.cluster.config import Config

        cfg = Config.load(args.conf)
        section = getattr(cfg, args.role, {}) if args.role != "standalone" \
            else {}
        args.host = section.get("host", args.host)
        args.port = int(section.get("port", args.port))
        args.master_addr = section.get("master_addr", args.master_addr)
        args.data_dir = cfg.data_dir if args.data_dir == "./vearch_data" \
            else args.data_dir
        args.auth = args.auth or cfg.auth
        args.root_password = cfg.root_password
        # per-role rotating file log + stderr (reference: [global] log
        # dir + level, pkg/log rotating writer)
        log.init(args.role, log_dir=cfg.log_dir_for(args.data_dir),
                 level=cfg.log_level)
    else:
        import os

        log.init(args.role, log_dir=None,
                 level=os.environ.get("VEARCH_LOG_LEVEL", "info"))

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())

    if args.role == "standalone":
        from vearch_tpu.cluster.standalone import StandaloneCluster

        cluster = StandaloneCluster(data_dir=args.data_dir, n_ps=args.n_ps)
        cluster.start()
        print(f"router: http://{cluster.router_addr}  "
              f"master: http://{cluster.master_addr}", flush=True)
        stop.wait()
        cluster.stop()
        return 0

    if args.role == "master":
        from vearch_tpu.cluster.master import MasterServer

        peers = None
        if args.peers:
            peers = {}
            for part in args.peers.split(","):
                nid, _, addr = part.strip().partition("=")
                peers[int(nid)] = addr
        server = MasterServer(
            host=args.host, port=args.port,
            persist_path=f"{args.data_dir}/meta.json",
            auth=args.auth, root_password=args.root_password,
            node_id=args.node_id, peers=peers,
            meta_dir=args.data_dir if peers else None,
        )
        server.start()
        print(f"master: http://{server.addr}", flush=True)
        stop.wait()
        server.stop()
        return 0

    if args.master_addr is None:
        print("--master-addr required for ps/router roles", file=sys.stderr)
        return 2

    if args.role == "ps":
        from vearch_tpu.cluster.ps import PSServer

        cfg_ps = {}
        cfg_tr = {}
        if args.conf:
            from vearch_tpu.cluster.config import Config

            cfg = Config.load(args.conf)
            cfg_ps = getattr(cfg, "ps", {}) or {}
            cfg_tr = getattr(cfg, "tracer", {}) or {}
        server = PSServer(
            data_dir=args.data_dir, host=args.host, port=args.port,
            master_addr=args.master_addr,
            master_auth=("root", args.root_password) if args.auth else None,
            backup_roots=cfg_ps.get("backup_roots"),
            backup_endpoints=cfg_ps.get("backup_endpoints"),
            trace_collector=cfg_tr.get("collector_endpoint"),
            search_cache_entries=int(
                cfg_ps.get("search_cache_entries", 256)),
            # overload shedding bound (0 disables; runtime-tunable via
            # /ps/engine/config)
            admission_queue_limit=int(
                cfg_ps.get("admission_queue_limit", 0)),
        )
        server.start()
        print(f"ps node {server.node_id}: http://{server.addr}", flush=True)
        stop.wait()
        server.stop()
        return 0

    from vearch_tpu.cluster.router import RouterServer

    cfg_rt = {}
    cfg_tr = {}
    if args.conf:
        from vearch_tpu.cluster.config import Config

        cfg = Config.load(args.conf)
        cfg_rt = getattr(cfg, "router", {}) or {}
        cfg_tr = getattr(cfg, "tracer", {}) or {}
    server = RouterServer(
        master_addr=args.master_addr, host=args.host, port=args.port,
        auth=args.auth,
        master_auth=("root", args.root_password) if args.auth else None,
        # reference: [tracer] config block (sampler rate), startup.go:66
        trace_sample=float(cfg_tr.get("sample_rate", 0.0)),
        trace_export=cfg_tr.get("export_path"),
        trace_collector=cfg_tr.get("collector_endpoint"),
        grpc_port=args.grpc_port,
        # fan-out pool size (0 = auto with partition count) and the
        # merged-result cache knobs from the [router] block
        fanout_workers=int(cfg_rt.get("fanout_workers", 0)),
        cache_entries=int(cfg_rt.get("cache_entries", 512)),
        cache_ttl_s=float(cfg_rt.get("cache_ttl_s", 10.0)),
        # tail-latency knobs: adaptive hedged scatter (quantile-derived
        # delay, budget-capped) and least-loaded replica reads
        hedge_quantile=float(cfg_rt.get("hedge_quantile", 0.95)),
        hedge_budget_pct=float(cfg_rt.get("hedge_budget_pct", 10.0)),
        replica_read=bool(cfg_rt.get("replica_read", False)),
    )
    server.start()
    print(f"router: http://{server.addr}", flush=True)
    if server.grpc is not None:
        print(f"router grpc: {server.grpc.addr}", flush=True)
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Device mesh construction for multi-chip partitions.

The reference scales across machines with partition sharding + raft
replication (reference: SURVEY.md §2.3 — murmur3 slot sharding,
client-side scatter/gather). Within one partition server, this module adds
the axis the reference never had: a JAX device mesh over local TPU chips,
with the vector matrix row-sharded ("data" axis) and the query batch
sharded ("query" axis). Collectives ride ICI:

- search: per-shard top-k, then all_gather over "data" + re-top-k — the
  cross-chip merge never leaves the device (SURVEY.md §2.4: TPU-native
  equivalent of the router's host-side merge, pushed down to ICI);
- k-means training: psum of per-shard partial sums ("data" axis) — the
  classic data-parallel reduction.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vearch_tpu.ops import perf_model


def default_mesh() -> Mesh:
    """Process-wide all-devices mesh, rows on "data" (cached: mesh
    identity matters for jit cache hits)."""
    return make_mesh(query_axis=1)


@functools.lru_cache(maxsize=32)
def _mesh_cached(n: int, data_axis: int, query_axis: int) -> Mesh:
    dev_array = np.asarray(jax.devices()[:n]).reshape(data_axis, query_axis)
    return Mesh(dev_array, axis_names=("data", "query"))


def make_mesh(
    n_devices: int | None = None,
    data_axis: int | None = None,
    query_axis: int = 1,
) -> Mesh:
    """2D mesh ("data", "query") over the first n devices.

    Default puts all devices on "data" (row sharding) — the right shape
    for search serving where the DB dwarfs the query batch.

    Meshes are cached per (n, data_axis, query_axis): the shard_map
    program builders in parallel/sharded.py key their lru_caches on mesh
    IDENTITY, so a fresh Mesh per engine publish would retrace every
    sharded program and blow past the zero-new-programs perf gates.
    """
    n = min(n_devices or len(jax.devices()), len(jax.devices()))
    if data_axis is None:
        data_axis = n // query_axis
    assert data_axis * query_axis == n, (
        f"mesh {data_axis}x{query_axis} != {n} devices"
    )
    return _mesh_cached(n, data_axis, query_axis)


def mesh_from_shape(shape) -> Mesh:
    """Resolve a user-facing ``mesh_shape`` knob to a cached mesh.

    Accepts ``"4x2"`` strings, ``(data, query)`` pairs, a bare device
    count (all on "data"), or None/"" for :func:`default_mesh`. This is
    the single parse point for the engine/apply_config and index-params
    surfaces, so every layer lands on the SAME cached Mesh object and
    the shard_map program caches (keyed on mesh identity) stay warm.
    """
    if shape in (None, "", "auto", "default"):
        return default_mesh()
    if isinstance(shape, str):
        parts = shape.lower().split("x")
        if len(parts) == 1:
            return make_mesh(int(parts[0]))
        da, qa = (int(p) for p in parts[:2])
        return make_mesh(da * qa, data_axis=da, query_axis=qa)
    if isinstance(shape, (list, tuple)):
        da, qa = int(shape[0]), int(shape[1])
        return make_mesh(da * qa, data_axis=da, query_axis=qa)
    return make_mesh(int(shape))


def shard_rows(mesh: Mesh, x, pad_value=0):
    """Place a host [N, ...] array row-sharded over the "data" axis,
    padding N up to a multiple of the axis size. Returns (device_array,
    orig_n)."""
    import jax.numpy as jnp

    n_shards = mesh.shape["data"]
    n = x.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad = np.full((rem,) + x.shape[1:], pad_value, dtype=x.dtype)
        x = np.concatenate([np.asarray(x), pad], axis=0)
    sharding = NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
    # .nbytes is metadata on both numpy and jax arrays — no host sync
    perf_model.note_h2d_bytes(int(getattr(x, "nbytes", 0)))
    return jax.device_put(jnp.asarray(x), sharding), n


def shard_queries(mesh: Mesh, q):
    """Place a host [B, d] query batch sharded over the "query" axis
    (replicated over "data")."""
    import jax.numpy as jnp

    n_shards = mesh.shape["query"]
    b = q.shape[0]
    rem = (-b) % n_shards
    if rem:
        q = np.concatenate(
            [np.asarray(q), np.zeros((rem, q.shape[1]), dtype=q.dtype)], axis=0
        )
    sharding = NamedSharding(mesh, P("query", None))
    perf_model.note_h2d_bytes(int(getattr(q, "nbytes", 0)))
    return jax.device_put(jnp.asarray(q), sharding), b


def replicate(mesh: Mesh, x):
    import jax.numpy as jnp

    spec = P(*([None] * np.ndim(x)))
    perf_model.note_h2d_bytes(int(getattr(x, "nbytes", 0)))
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))


@functools.lru_cache(maxsize=16)
def _tail_update_fn(ndim: int, with_sqnorm: bool):
    """Per-device tail writer: dynamic_update_slice of the new rows into
    one shard's slab (NOT donated — a concurrent search may still hold
    the previous buffer; the device-side copy is the price of lock-free
    reads). Traced `off` so append offsets never retrace. The derived
    sqnorm tail arrives pre-computed host-side (ops/distance
    host_sqnorms) so every placement path lands the identical column."""
    from vearch_tpu.ops.perf_model import register_jit

    def upd(dst, tail, off, sq=None, sq_tail=None):
        idx = (off,) + (0,) * (ndim - 1)
        out = jax.lax.dynamic_update_slice(dst, tail, idx)
        if sq is None:
            return out
        return out, jax.lax.dynamic_update_slice(sq, sq_tail, (off,))

    fn = jax.jit(upd)
    return register_jit(
        f"mesh.tail_append[{ndim}d{',sqnorm' if with_sqnorm else ''}]", fn
    )


class ShardedRowCache:
    """Grow-only cache of host row arrays placed row-sharded on a mesh.

    One invalidation point for every sharded device buffer (int8 mirror,
    raw rerank base, ...): `get` rebuilds when capacity changed, and
    TAIL-APPENDS when rows merely grew within the cached capacity and
    the caller supplies `append_host_fn` — one H2D per touched device of
    only the new rows, never a full re-place (realtime absorb on a mesh
    partition). `lower_rows` must be called when rows BELOW the
    high-water mark were overwritten (re-absorb, engine load) so the
    next get re-places instead of serving stale rows; `invalidate` drops
    everything.

    `sqnorm_of=i` maintains a derived [cap] f32 squared-norm column of
    arrays[i] (`self.sqnorm`), kept in lockstep through both rebuilds
    and tail-appends — the rerank base needs it and computing it host-
    side would break bit-equality with the single-device path.

    `stats` counts rebuilds / appends / H2D bytes so the perf gates can
    assert absorb never re-places the full buffer.

    The cache is keyed on mesh IDENTITY, so a runtime ``mesh_shape``
    change (engine apply_config -> index params -> mesh_from_shape)
    re-places every buffer onto the new mesh on the next get() with no
    explicit invalidation — the old mesh's placement is simply dropped.
    """

    def __init__(self, align: int, sqnorm_of: int | None = None):
        self.align = align
        self.sqnorm_of = sqnorm_of
        self._key = None
        self._rows = 0
        self.arrays: tuple | None = None
        self.sqnorm: jax.Array | None = None
        self.stats = {"rebuilds": 0, "appends": 0, "h2d_bytes": 0}

    def capacity(self, mesh: Mesh, n: int) -> int:
        """Sharded capacity for n rows: align*n_shards units, grown
        GEOMETRICALLY past the currently-placed capacity so realtime
        absorb amortizes to tail-appends (a tight capacity would force
        a full re-place every time n crossed a unit boundary)."""
        unit = self.align * mesh.shape["data"]
        need = -(-max(n, 1) // unit) * unit
        if self._key is not None and self._key[0] == id(mesh):
            cur = self._key[1]
            if cur >= need:
                return cur
            return max(need, 2 * cur)
        return need

    def get(self, mesh: Mesh, n: int, build_host_fn, append_host_fn=None):
        """build_host_fn(cap) -> tuple of host arrays with cap rows;
        append_host_fn(lo, hi) -> tuple of host arrays with hi-lo rows
        (rows [lo, hi) of each cached array). Returns (device_arrays,
        rebuilt)."""
        cap = self.capacity(mesh, n)
        key = (id(mesh), cap)
        rebuilt = False
        if self._key == key and self.arrays is not None and self._rows < n \
                and append_host_fn is not None:
            self._append(mesh, n, cap, append_host_fn)
        elif self._key != key or self._rows < n or self.arrays is None:
            hosts = build_host_fn(cap)
            self.arrays = tuple(shard_rows(mesh, h)[0] for h in hosts)
            if self.sqnorm_of is not None:
                from vearch_tpu.ops.distance import host_sqnorms

                self.sqnorm = shard_rows(
                    mesh, host_sqnorms(hosts[self.sqnorm_of])
                )[0]
            self._key = key
            self._rows = n
            rebuilt = True
            self.stats["rebuilds"] += 1
            moved = sum(np.asarray(h).nbytes for h in hosts)
            self.stats["h2d_bytes"] += moved
            perf_model.note_h2d_bytes(moved)
        return self.arrays, rebuilt

    def _append(self, mesh: Mesh, n: int, cap: int, append_host_fn) -> None:
        """Tail-append rows [rows_hw, n) in place: the host window is
        align-rounded so every per-shard slice keeps lane-aligned static
        shapes (bounded retrace), sliced per shard, H2D'd to exactly the
        devices whose slab the window touches, and written with a
        non-donating dynamic_update_slice. Untouched shards keep their
        existing buffers — zero copies, zero traffic."""
        n_shards = mesh.shape["data"]
        local_n = cap // n_shards
        lo = (self._rows // self.align) * self.align
        hi = min(-(-n // self.align) * self.align, cap)
        tails = [np.asarray(t) for t in append_host_fn(lo, hi)]
        sq_tail = None
        if self.sqnorm_of is not None:
            from vearch_tpu.ops.distance import host_sqnorms

            sq_tail = host_sqnorms(tails[self.sqnorm_of])
        new_arrays = []
        new_sq = self.sqnorm
        for ai, arr in enumerate(self.arrays):
            want_sq = self.sqnorm_of == ai
            upd = _tail_update_fn(arr.ndim, want_sq)
            parts = {}
            sq_parts = {}
            for sh in arr.addressable_shards:
                s = (sh.index[0].start or 0) // local_n
                a = max(lo, s * local_n)
                b = min(hi, (s + 1) * local_n)
                if a >= b:
                    parts[s] = sh.data
                    continue
                win = tails[ai][a - lo : b - lo]
                win_dev = jax.device_put(win, sh.device)
                self.stats["h2d_bytes"] += win.nbytes
                perf_model.note_h2d_bytes(win.nbytes)
                off = np.int32(a - s * local_n)
                if want_sq:
                    sq_sh = {
                        (q.index[0].start or 0) // local_n: q
                        for q in new_sq.addressable_shards
                    }[s]
                    sq_win = jax.device_put(
                        sq_tail[a - lo : b - lo], sh.device
                    )
                    self.stats["h2d_bytes"] += sq_win.nbytes
                    perf_model.note_h2d_bytes(sq_win.nbytes)
                    parts[s], sq_parts[s] = upd(
                        sh.data, win_dev, off, sq_sh.data, sq_win
                    )
                else:
                    parts[s] = upd(sh.data, win_dev, off)
            new_arrays.append(jax.make_array_from_single_device_arrays(
                arr.shape, arr.sharding,
                [parts[s] for s in sorted(parts)],
            ))
            if want_sq:
                sq_all = {
                    (q.index[0].start or 0) // local_n: q.data
                    for q in new_sq.addressable_shards
                }
                sq_all.update(sq_parts)
                new_sq = jax.make_array_from_single_device_arrays(
                    new_sq.shape, new_sq.sharding,
                    [sq_all[s] for s in sorted(sq_all)],
                )
        # publish by reference swap: readers see either the old or the
        # new tuple, both internally consistent
        self.arrays = tuple(new_arrays)
        self.sqnorm = new_sq
        self._rows = n
        self.stats["appends"] += 1

    def lower_rows(self, start: int) -> None:
        self._rows = min(self._rows, start)

    def invalidate(self) -> None:
        self._key = None
        self._rows = 0
        self.arrays = None
        self.sqnorm = None

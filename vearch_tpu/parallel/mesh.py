"""Device mesh construction for multi-chip partitions.

The reference scales across machines with partition sharding + raft
replication (reference: SURVEY.md §2.3 — murmur3 slot sharding,
client-side scatter/gather). Within one partition server, this module adds
the axis the reference never had: a JAX device mesh over local TPU chips,
with the vector matrix row-sharded ("data" axis) and the query batch
sharded ("query" axis). Collectives ride ICI:

- search: per-shard top-k, then all_gather over "data" + re-top-k — the
  cross-chip merge never leaves the device (SURVEY.md §2.4: TPU-native
  equivalent of the router's host-side merge, pushed down to ICI);
- k-means training: psum of per-shard partial sums ("data" axis) — the
  classic data-parallel reduction.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: int | None = None,
    data_axis: int | None = None,
    query_axis: int = 1,
) -> Mesh:
    """2D mesh ("data", "query") over the first n devices.

    Default puts all devices on "data" (row sharding) — the right shape
    for search serving where the DB dwarfs the query batch.
    """
    devices = jax.devices()[: (n_devices or len(jax.devices()))]
    n = len(devices)
    if data_axis is None:
        data_axis = n // query_axis
    assert data_axis * query_axis == n, (
        f"mesh {data_axis}x{query_axis} != {n} devices"
    )
    dev_array = np.asarray(devices).reshape(data_axis, query_axis)
    return Mesh(dev_array, axis_names=("data", "query"))


def shard_rows(mesh: Mesh, x, pad_value=0):
    """Place a host [N, ...] array row-sharded over the "data" axis,
    padding N up to a multiple of the axis size. Returns (device_array,
    orig_n)."""
    import jax.numpy as jnp

    n_shards = mesh.shape["data"]
    n = x.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad = np.full((rem,) + x.shape[1:], pad_value, dtype=x.dtype)
        x = np.concatenate([np.asarray(x), pad], axis=0)
    sharding = NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
    return jax.device_put(jnp.asarray(x), sharding), n


def shard_queries(mesh: Mesh, q):
    """Place a host [B, d] query batch sharded over the "query" axis
    (replicated over "data")."""
    import jax.numpy as jnp

    n_shards = mesh.shape["query"]
    b = q.shape[0]
    rem = (-b) % n_shards
    if rem:
        q = np.concatenate(
            [np.asarray(q), np.zeros((rem, q.shape[1]), dtype=q.dtype)], axis=0
        )
    sharding = NamedSharding(mesh, P("query", None))
    return jax.device_put(jnp.asarray(q), sharding), b


def replicate(mesh: Mesh, x):
    import jax.numpy as jnp

    spec = P(*([None] * np.ndim(x)))
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

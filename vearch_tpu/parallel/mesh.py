"""Device mesh construction for multi-chip partitions.

The reference scales across machines with partition sharding + raft
replication (reference: SURVEY.md §2.3 — murmur3 slot sharding,
client-side scatter/gather). Within one partition server, this module adds
the axis the reference never had: a JAX device mesh over local TPU chips,
with the vector matrix row-sharded ("data" axis) and the query batch
sharded ("query" axis). Collectives ride ICI:

- search: per-shard top-k, then all_gather over "data" + re-top-k — the
  cross-chip merge never leaves the device (SURVEY.md §2.4: TPU-native
  equivalent of the router's host-side merge, pushed down to ICI);
- k-means training: psum of per-shard partial sums ("data" axis) — the
  classic data-parallel reduction.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_default_mesh: Mesh | None = None


def default_mesh() -> Mesh:
    """Process-wide all-devices mesh, rows on "data" (cached: mesh
    identity matters for jit cache hits)."""
    global _default_mesh
    if _default_mesh is None or (
        _default_mesh.size != len(jax.devices())
    ):
        _default_mesh = make_mesh(query_axis=1)
    return _default_mesh


def make_mesh(
    n_devices: int | None = None,
    data_axis: int | None = None,
    query_axis: int = 1,
) -> Mesh:
    """2D mesh ("data", "query") over the first n devices.

    Default puts all devices on "data" (row sharding) — the right shape
    for search serving where the DB dwarfs the query batch.
    """
    devices = jax.devices()[: (n_devices or len(jax.devices()))]
    n = len(devices)
    if data_axis is None:
        data_axis = n // query_axis
    assert data_axis * query_axis == n, (
        f"mesh {data_axis}x{query_axis} != {n} devices"
    )
    dev_array = np.asarray(devices).reshape(data_axis, query_axis)
    return Mesh(dev_array, axis_names=("data", "query"))


def shard_rows(mesh: Mesh, x, pad_value=0):
    """Place a host [N, ...] array row-sharded over the "data" axis,
    padding N up to a multiple of the axis size. Returns (device_array,
    orig_n)."""
    import jax.numpy as jnp

    n_shards = mesh.shape["data"]
    n = x.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad = np.full((rem,) + x.shape[1:], pad_value, dtype=x.dtype)
        x = np.concatenate([np.asarray(x), pad], axis=0)
    sharding = NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
    return jax.device_put(jnp.asarray(x), sharding), n


def shard_queries(mesh: Mesh, q):
    """Place a host [B, d] query batch sharded over the "query" axis
    (replicated over "data")."""
    import jax.numpy as jnp

    n_shards = mesh.shape["query"]
    b = q.shape[0]
    rem = (-b) % n_shards
    if rem:
        q = np.concatenate(
            [np.asarray(q), np.zeros((rem, q.shape[1]), dtype=q.dtype)], axis=0
        )
    sharding = NamedSharding(mesh, P("query", None))
    return jax.device_put(jnp.asarray(q), sharding), b


def replicate(mesh: Mesh, x):
    import jax.numpy as jnp

    spec = P(*([None] * np.ndim(x)))
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))


class ShardedRowCache:
    """Grow-only cache of host row arrays placed row-sharded on a mesh.

    One invalidation point for every sharded device buffer (int8 mirror,
    raw rerank base, ...): `get` rebuilds when capacity changed or rows
    grew past the cached high-water mark; `lower_rows` must be called
    when rows BELOW the high-water mark were overwritten (re-absorb,
    engine load) so the next get re-places instead of serving stale
    rows; `invalidate` drops everything.
    """

    def __init__(self, align: int):
        self.align = align
        self._key = None
        self._rows = 0
        self.arrays: tuple | None = None

    def capacity(self, mesh: Mesh, n: int) -> int:
        unit = self.align * mesh.shape["data"]
        return -(-max(n, 1) // unit) * unit

    def get(self, mesh: Mesh, n: int, build_host_fn):
        """build_host_fn(cap) -> tuple of host arrays with cap rows.
        Returns (device_arrays, rebuilt)."""
        cap = self.capacity(mesh, n)
        key = (id(mesh), cap)
        rebuilt = False
        if self._key != key or self._rows < n or self.arrays is None:
            hosts = build_host_fn(cap)
            self.arrays = tuple(shard_rows(mesh, h)[0] for h in hosts)
            self._key = key
            self._rows = n
            rebuilt = True
        return self.arrays, rebuilt

    def lower_rows(self, start: int) -> None:
        self._rows = min(self._rows, start)

    def invalidate(self) -> None:
        self._key = None
        self._rows = 0
        self.arrays = None

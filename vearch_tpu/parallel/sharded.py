"""shard_map'd multi-chip kernels: sharded search + sharded k-means.

The SPMD layer of the engine. All cross-chip traffic is XLA collectives
over ICI (all_gather / psum) — no host round-trips inside a step
(SURVEY.md §2.4: the TPU-native communication backend; the reference's
NCCL-free design maps to pure data-parallel shard scan + on-device merge).

Layouts (built by parallel/mesh.py):
    base    [N_pad, d]  rows sharded over "data"
    queries [B_pad, d]  sharded over "query", replicated over "data"
    outputs [B_pad, k]  sharded over "query" (global docids)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from vearch_tpu.engine.types import MetricType
from vearch_tpu.ops import kmeans as km
from vearch_tpu.ops.distance import brute_force_search, dot_precision, sqnorms
from vearch_tpu.ops.perf_model import register_jit
from vearch_tpu.parallel import mesh as mesh_lib

NEG_INF = float("-inf")


def _mesh_tag(mesh: Mesh) -> str:
    return f"{mesh.shape['data']}x{mesh.shape['query']}"


@functools.lru_cache(maxsize=128)
def _flat_search_fn(mesh: Mesh, k: int, metric: MetricType):
    """Build-once jitted shard_map program. Re-creating the closure per
    call would retrace every search: jit's cache keys on function
    identity, so the callable itself is cached per (mesh, statics)."""

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("data", None), P("data"), P("data"), P("query", None)),
        out_specs=(P("query", None), P("query", None)),
        check_rep=False,
    )
    def run(b, sqn, v, q):
        local_k = min(k, b.shape[0])
        scores, ids = brute_force_search(q, b, v, local_k, metric, sqn)
        shard = jax.lax.axis_index("data")
        gids = jnp.where(ids >= 0, ids + shard * b.shape[0], -1)
        all_s = jax.lax.all_gather(scores, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gids, "data", axis=1, tiled=True)
        kk = min(k, all_s.shape[1])
        top_s, pos = jax.lax.top_k(all_s, kk)
        return top_s, jnp.take_along_axis(all_i, pos, axis=1)

    return register_jit(
        f"sharded.flat[{_mesh_tag(mesh)},k{k},{metric.name}]", run
    )


def sharded_flat_search(
    mesh: Mesh,
    base: jax.Array,      # [N_pad, d] sharded P("data", None)
    base_sqnorm: jax.Array,  # [N_pad] sharded P("data")
    valid: jax.Array,     # [N_pad] bool sharded P("data")
    queries: jax.Array,   # [B_pad, d] sharded P("query", None)
    k: int,
    metric: MetricType = MetricType.L2,
) -> tuple[jax.Array, jax.Array]:
    """Exact search over a row-sharded base: local top-k per shard, then
    all_gather over "data" + global re-top-k, all on device."""
    return _flat_search_fn(mesh, k, metric)(
        base, base_sqnorm, valid, queries
    )


def sharded_int8_search(
    mesh: Mesh,
    approx8: jax.Array,    # [N_pad, d] int8 sharded P("data", None)
    row_scale: jax.Array,  # [N_pad] sharded P("data")
    row_vsq: jax.Array,    # [N_pad] sharded P("data")
    valid: jax.Array,      # [N_pad] bool sharded P("data")
    queries: jax.Array,    # [B_pad, d] f32 sharded P("query", None)
    r: int,
    metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
    storage: str = "int8",
) -> tuple[jax.Array, jax.Array]:
    """Sharded compressed scan (the IVFPQ full-scan path across chips).
    `storage` follows the mirror tier: int8 rows or nibble-packed int4."""
    return _int8_search_fn(mesh, r, metric, topk_mode, storage)(
        approx8, row_scale, row_vsq, valid, queries
    )


@functools.lru_cache(maxsize=128)
def _int8_search_fn(mesh: Mesh, r: int, metric: MetricType,
                    topk_mode: str, storage: str = "int8"):
    from vearch_tpu.ops.ivf import int4_scan_candidates, int8_scan_candidates

    scan = int8_scan_candidates if storage == "int8" else int4_scan_candidates

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("data", None), P("data"), P("data"), P("data"),
            P("query", None),
        ),
        out_specs=(P("query", None), P("query", None)),
        check_rep=False,
    )
    def run(a8, sc, vsq, v, q):
        local_r = min(r, a8.shape[0])
        scores, ids = scan(q, a8, sc, vsq, v, local_r, metric, topk_mode)
        shard = jax.lax.axis_index("data")
        # masked candidates come back as id=-1; keep them -1 globally
        # (a bare shard offset would turn them into real foreign docids)
        gids = jnp.where(ids >= 0, ids + shard * a8.shape[0], -1)
        all_s = jax.lax.all_gather(scores, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gids, "data", axis=1, tiled=True)
        rr = min(r, all_s.shape[1])
        top_s, pos = jax.lax.top_k(all_s, rr)
        return top_s, jnp.take_along_axis(all_i, pos, axis=1)

    return register_jit(
        f"sharded.int8[{_mesh_tag(mesh)},r{r},{metric.name},"
        f"{topk_mode},{storage}]", run,
    )


def sharded_exact_rerank(
    mesh: Mesh,
    queries: jax.Array,     # [B_pad, d] sharded P("query", None)
    cand_ids: jax.Array,    # [B_pad, r] i32 global docids, P("query", None)
    base: jax.Array,        # [N_pad, d] sharded P("data", None)
    base_sqnorm: jax.Array,  # [N_pad] sharded P("data")
    k: int,
    metric: MetricType = MetricType.L2,
) -> tuple[jax.Array, jax.Array]:
    """Exact re-scoring against a row-sharded raw buffer: every shard
    scores the candidates it owns (others -inf), pmax over "data" merges
    without leaving the device, then one small top-k. The mesh analogue
    of ops/ivf.py exact_rerank. Every step is per-query-row, so the
    query batch shards over "query" (positional PartitionSpecs — the
    program stays mesh-shape agnostic; a 1-wide query axis degenerates
    to the replicated layout)."""
    return _exact_rerank_fn(mesh, k, metric)(
        queries, cand_ids, base, base_sqnorm
    )


@functools.lru_cache(maxsize=128)
def _exact_rerank_fn(mesh: Mesh, k: int, metric: MetricType):
    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("query", None), P("query", None), P("data", None), P("data"),
        ),
        out_specs=(P("query", None), P("query", None)),
        check_rep=False,
    )
    def run(q, cids, b, sqn):
        shard = jax.lax.axis_index("data")
        local_n = b.shape[0]
        local = cids - shard * local_n
        mine = (cids >= 0) & (local >= 0) & (local < local_n)
        safe = jnp.clip(local, 0, local_n - 1)
        vecs = b[safe]  # [B, r, d]
        vsq = sqn[safe]
        qf = q.astype(b.dtype)
        dots = jax.lax.dot_general(
            qf, vecs, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
            precision=dot_precision(qf, vecs),
        )
        if metric is MetricType.L2:
            scores = -(sqnorms(qf)[:, None] - 2.0 * dots + vsq)
        elif metric is MetricType.COSINE:
            qn = jnp.sqrt(jnp.maximum(sqnorms(qf), 1e-30))[:, None]
            vn = jnp.sqrt(jnp.maximum(vsq, 1e-30))
            scores = dots / (qn * vn)
        else:
            scores = dots
        scores = jnp.where(mine, scores, NEG_INF)
        scores = jax.lax.pmax(scores, "data")  # replicated merge
        kk = min(k, scores.shape[1])
        top_s, pos = jax.lax.top_k(scores, kk)
        ids = jnp.take_along_axis(cids, pos, axis=1)
        return top_s, jnp.where(jnp.isfinite(top_s), ids, -1)

    return register_jit(
        f"sharded.rerank[{_mesh_tag(mesh)},k{k},{metric.name}]", run
    )


def sharded_ivf_search(
    mesh: Mesh,
    centroids: jax.Array | None,  # [nlist, d] f32 replicated (None: no probe)
    assign: jax.Array | None,     # [N_pad] i32 row->cluster, sharded P("data")
    approx8: jax.Array,           # [N_pad, d] int8 / [N_pad, d/2] packed int4
    row_scale: jax.Array,         # [N_pad] f32 sharded P("data")
    row_vsq: jax.Array,           # [N_pad] f32 sharded P("data")
    valid: jax.Array,             # [N_pad] bool sharded P("data")
    base: jax.Array,              # [cap, d] raw rows sharded P("data", None)
    base_sqnorm: jax.Array,       # [cap] f32 sharded P("data")
    queries: jax.Array,           # [B_pad, d] f32 sharded P("query", None)
    r: int,
    k: int,
    scan_metric: MetricType = MetricType.L2,
    rerank_metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
    storage: str = "int8",
    nprobe: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """The pod-slice IVF serving program: coarse probe -> per-shard
    compressed scan -> all_gather top-r merge -> exact rerank against
    the sharded raw base -> pmax merge + final top-k, as ONE jitted
    shard_map program. Nothing touches the host between the query
    replicate and the final [B, k] device_get.

    nprobe=0 disables the coarse gate (docid-ordered full scan — the
    IVFPQ "full" mode); nprobe>0 masks every shard's rows to the probed
    cells using the REPLICATED coarse quantizer, so probe selection is
    computed redundantly per shard instead of paying a collective."""
    fn = _ivf_search_fn(
        mesh, r, k, scan_metric, rerank_metric, topk_mode, storage, nprobe
    )
    if nprobe > 0:
        return fn(centroids, assign, approx8, row_scale, row_vsq, valid,
                  base, base_sqnorm, queries)
    return fn(approx8, row_scale, row_vsq, valid, base, base_sqnorm, queries)


@functools.lru_cache(maxsize=128)
def _ivf_search_fn(
    mesh: Mesh, r: int, k: int, scan_metric: MetricType,
    rerank_metric: MetricType, topk_mode: str, storage: str, nprobe: int,
):
    from vearch_tpu.ops.ivf import _coarse_probes, _select_topk, unpack_int4

    probed = nprobe > 0
    # queries ride the "query" axis (last in_spec / both out_specs) —
    # every stage of the program is per-query-row except the "data"
    # collectives, so a query_axis>1 mesh splits the batch across its
    # query shards for free; centroids stay replicated (every query
    # shard recomputes its own probes, same as every data shard does)
    mirror_specs = (P("data", None), P("data"), P("data"), P("data"))
    rerank_specs = (P("data", None), P("data"), P("query", None))
    if probed:
        in_specs = (P(None, None), P("data")) + mirror_specs + rerank_specs
    else:
        in_specs = mirror_specs + rerank_specs

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P("query", None), P("query", None)),
        check_rep=False,
    )
    def run(*args):
        if probed:
            cents, assign, a8, sc, vsq, v, b, bsqn, q = args
        else:
            a8, sc, vsq, v, b, bsqn, q = args
        local_n = sc.shape[0]
        ok = v[None, :]
        if probed:
            # every shard holds the full coarse quantizer, so probe
            # selection is recomputed identically per shard — cheaper
            # than a collective for any realistic nlist. The per-row
            # gate is a [B, nlist] cell mask gathered by the shard's own
            # row->cluster assignment.
            probes = _coarse_probes(q, cents, min(nprobe, cents.shape[0]))
            cell = jnp.zeros(
                (q.shape[0], cents.shape[0]), dtype=bool
            ).at[jnp.arange(q.shape[0])[:, None], probes].set(True)
            ok = ok & cell[:, jnp.maximum(assign, 0)]
        rows = a8.astype(jnp.bfloat16) if storage == "int8" \
            else unpack_int4(a8)
        dots = jax.lax.dot_general(
            q.astype(jnp.bfloat16), rows, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sc[None, :]
        if scan_metric is MetricType.L2:
            scores = -(sqnorms(q)[:, None] - 2.0 * dots + vsq[None, :])
        else:
            scores = dots
        scores = jnp.where(ok, scores, NEG_INF)
        top_s, top_i = _select_topk(scores, min(r, local_n), topk_mode)
        shard = jax.lax.axis_index("data")
        gids = jnp.where(top_i >= 0, top_i + shard * local_n, -1)
        all_s = jax.lax.all_gather(top_s, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gids, "data", axis=1, tiled=True)
        rr = min(r, all_s.shape[1])
        cand_s, pos = jax.lax.top_k(all_s, rr)
        cand_i = jnp.take_along_axis(all_i, pos, axis=1)
        # exact rerank against the shard's raw slab: candidates this
        # shard does not own score -inf and the pmax merge recovers the
        # owner's exact score everywhere (same ownership math as
        # _exact_rerank_fn, with the BASE slab size — the mirror and the
        # raw buffer are padded to different alignments)
        local_nb = b.shape[0]
        local = cand_i - shard * local_nb
        mine = (cand_i >= 0) & (local >= 0) & (local < local_nb)
        safe = jnp.clip(local, 0, local_nb - 1)
        vecs = b[safe]  # [B, rr, d]
        bvsq = bsqn[safe]
        qf = q.astype(b.dtype)
        rdots = jax.lax.dot_general(
            qf, vecs, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
            precision=dot_precision(qf, vecs),
        )
        if rerank_metric is MetricType.L2:
            rscores = -(sqnorms(qf)[:, None] - 2.0 * rdots + bvsq)
        elif rerank_metric is MetricType.COSINE:
            qn = jnp.sqrt(jnp.maximum(sqnorms(qf), 1e-30))[:, None]
            vn = jnp.sqrt(jnp.maximum(bvsq, 1e-30))
            rscores = rdots / (qn * vn)
        else:
            rscores = rdots
        rscores = jnp.where(mine, rscores, NEG_INF)
        rscores = jax.lax.pmax(rscores, "data")
        kk = min(k, rscores.shape[1])
        out_s, out_pos = jax.lax.top_k(rscores, kk)
        out_i = jnp.take_along_axis(cand_i, out_pos, axis=1)
        return out_s, jnp.where(jnp.isfinite(out_s), out_i, -1)

    return register_jit(
        f"sharded.ivf_fused[{_mesh_tag(mesh)},r{r},k{k},"
        f"{scan_metric.name},{rerank_metric.name},{topk_mode},{storage},"
        f"p{nprobe}]", run,
    )


def sharded_binary_refine(
    mesh: Mesh,
    planes: jax.Array,       # [N_pad, d/8] uint8 sharded P("data", None)
    p_scale: jax.Array,      # [N_pad] f32 sharded P("data")
    p_vsq: jax.Array,        # [N_pad] f32 sharded P("data")
    approx8: jax.Array,      # [N_pad, d] int8 / [N_pad, d/2] int4-packed
    m_scale: jax.Array,      # [N_pad] f32 sharded P("data")
    m_vsq: jax.Array,        # [N_pad] f32 sharded P("data")
    valid: jax.Array,        # [N_pad] bool sharded P("data")
    base: jax.Array,         # [cap, d] raw rows sharded P("data", None)
    base_sqnorm: jax.Array,  # [cap] f32 sharded P("data")
    queries: jax.Array,      # [B_pad, d] f32 sharded P("query", None)
    r0: int,
    r1: int,
    k: int,
    scan_metric: MetricType = MetricType.L2,
    rerank_metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
    storage: str = "int8",
) -> tuple[jax.Array, jax.Array]:
    """The pod-slice three-stage refinement program: bit planes, int8
    mirror, and raw base all row-sharded in lockstep over "data"
    (identical ShardedRowCache alignment, so local row offsets agree);
    stages 0-1 run entirely shard-local — a shard's stage-0 survivors
    are by construction rows it owns, so the int8 rescore needs no
    collective — then ONE all_gather merges the per-shard top-r1 sets
    and the exact rerank + pmax merge finishes exactly like
    sharded_ivf_search. ONE jitted shard_map program end to end."""
    return _binary_refine_fn(
        mesh, r0, r1, k, scan_metric, rerank_metric, topk_mode, storage
    )(planes, p_scale, p_vsq, approx8, m_scale, m_vsq, valid,
      base, base_sqnorm, queries)


@functools.lru_cache(maxsize=128)
def _binary_refine_fn(
    mesh: Mesh, r0: int, r1: int, k: int, scan_metric: MetricType,
    rerank_metric: MetricType, topk_mode: str, storage: str,
):
    from vearch_tpu.ops.binary_scan import _binary_scores, _mirror_rescore
    from vearch_tpu.ops.ivf import _select_topk

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P("data", None), P("data"), P("data"),
            P("data", None), P("data"), P("data"), P("data"),
            P("data", None), P("data"), P("query", None),
        ),
        out_specs=(P("query", None), P("query", None)),
        check_rep=False,
    )
    def run(pl, psc, pvsq, a8, msc, mvsq, v, b, bsqn, q):
        local_n = psc.shape[0]
        # stage 0: local binary scan over this shard's bit planes
        scores = _binary_scores(q, pl, psc, pvsq, v, scan_metric)
        _, c0 = _select_topk(scores, min(r0, local_n), topk_mode)
        # stage 1: rescore this shard's own survivors against its
        # int8/int4 mirror slab — ids are still shard-local
        top_s, top_i = _mirror_rescore(
            q, c0, a8, msc, mvsq, min(r1, local_n), scan_metric, storage
        )
        shard = jax.lax.axis_index("data")
        gids = jnp.where(top_i >= 0, top_i + shard * local_n, -1)
        all_s = jax.lax.all_gather(top_s, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gids, "data", axis=1, tiled=True)
        rr = min(r1, all_s.shape[1])
        cand_s, pos = jax.lax.top_k(all_s, rr)
        cand_i = jnp.take_along_axis(all_i, pos, axis=1)
        # stage 2: exact rerank against the shard's raw slab, pmax
        # ownership merge (same math as _ivf_search_fn's tail)
        local_nb = b.shape[0]
        local = cand_i - shard * local_nb
        mine = (cand_i >= 0) & (local >= 0) & (local < local_nb)
        safe = jnp.clip(local, 0, local_nb - 1)
        vecs = b[safe]  # [B, rr, d]
        bvsq = bsqn[safe]
        qf = q.astype(b.dtype)
        rdots = jax.lax.dot_general(
            qf, vecs, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
            precision=dot_precision(qf, vecs),
        )
        if rerank_metric is MetricType.L2:
            rscores = -(sqnorms(qf)[:, None] - 2.0 * rdots + bvsq)
        elif rerank_metric is MetricType.COSINE:
            qn = jnp.sqrt(jnp.maximum(sqnorms(qf), 1e-30))[:, None]
            vn = jnp.sqrt(jnp.maximum(bvsq, 1e-30))
            rscores = rdots / (qn * vn)
        else:
            rscores = rdots
        rscores = jnp.where(mine, rscores, NEG_INF)
        rscores = jax.lax.pmax(rscores, "data")
        kk = min(k, rscores.shape[1])
        out_s, out_pos = jax.lax.top_k(rscores, kk)
        out_i = jnp.take_along_axis(cand_i, out_pos, axis=1)
        return out_s, jnp.where(jnp.isfinite(out_s), out_i, -1)

    return register_jit(
        f"sharded.binary_refine[{_mesh_tag(mesh)},r0_{r0},r1_{r1},k{k},"
        f"{scan_metric.name},{rerank_metric.name},{topk_mode},{storage}]",
        run,
    )


def sharded_kmeans_step(
    mesh: Mesh,
    x: jax.Array,        # [N_pad, d] sharded P("data", None)
    valid: jax.Array,    # [N_pad] bool sharded P("data")
    centroids: jax.Array,  # [k, d] replicated
    reseed: jax.Array,   # [k, d] replicated
    chunk: int = 16384,
) -> jax.Array:
    """One Lloyd round over sharded data: per-shard partial stats, psum
    over "data", identical centroid update everywhere (the distributed
    training step of the coarse quantizer / PQ codebooks)."""
    return _kmeans_step_fn(mesh, chunk)(x, valid, centroids, reseed)


@functools.lru_cache(maxsize=32)
def _kmeans_step_fn(mesh: Mesh, chunk: int):
    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("data", None), P("data"), P(None, None), P(None, None)),
        out_specs=P(None, None),
        check_rep=False,
    )
    def step(xs, vs, c, rs):
        local_chunk = min(chunk, max(256, xs.shape[0]))
        rem = (-xs.shape[0]) % local_chunk
        if rem:
            xs = jnp.pad(xs, ((0, rem), (0, 0)))
            vs = jnp.pad(vs, (0, rem))
        sums, counts = km.kmeans_partials(xs, vs, c, chunk=local_chunk)
        # inputs are replicated over "query", so reducing over "data" alone
        # leaves every device with identical full stats
        sums = jax.lax.psum(sums, "data")
        counts = jax.lax.psum(counts, "data")
        return km.centroids_from_partials(sums, counts, rs)

    return step


def train_kmeans_sharded(
    mesh: Mesh, x_host: np.ndarray, k: int, iters: int = 10, seed: int = 0
) -> jax.Array:
    """Full multi-chip k-means: k-means++ init on a host sample, then
    `iters` sharded Lloyd rounds."""
    n = x_host.shape[0]
    x_host = np.asarray(x_host, dtype=np.float32)
    sample = x_host[
        np.random.default_rng(seed).choice(n, min(n, 65_536), replace=False)
    ]
    init = km.kmeanspp_init(jax.random.PRNGKey(seed), jnp.asarray(sample), k)
    reseed_rows = x_host[
        np.random.default_rng(seed + 1).choice(n, k, replace=n < k)
    ]

    x_dev, n_orig = mesh_lib.shard_rows(mesh, x_host)
    valid_host = np.arange(x_dev.shape[0]) < n_orig
    valid_dev, _ = mesh_lib.shard_rows(mesh, valid_host)
    cents = mesh_lib.replicate(mesh, init)
    reseed = mesh_lib.replicate(mesh, reseed_rows)
    for _ in range(iters):
        cents = sharded_kmeans_step(mesh, x_dev, valid_dev, cents, reseed)
    return cents


class ShardedFlatSearcher:
    """Holds a row-sharded database on a mesh and serves exact search —
    the multi-chip deployment of a FLAT partition (one partition spanning
    a TPU slice; the cluster layer still shards *across* partitions)."""

    def __init__(
        self,
        mesh: Mesh,
        base: np.ndarray,
        metric: MetricType = MetricType.L2,
        store_dtype: str = "bfloat16",
    ):
        from vearch_tpu.ops.distance import sqnorms

        self.mesh = mesh
        self.metric = metric
        self.n = base.shape[0]
        self.store_dtype = jnp.dtype(store_dtype)
        base = np.asarray(base, dtype=np.float32)
        self.base, _ = mesh_lib.shard_rows(mesh, base.astype(self.store_dtype))
        self.sqnorm = sqnorms(self.base)
        valid = np.arange(self.base.shape[0]) < self.n
        self.valid, _ = mesh_lib.shard_rows(mesh, valid)

    def search(self, queries: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
        q, b = mesh_lib.shard_queries(
            self.mesh, np.asarray(queries, np.float32).astype(self.store_dtype)
        )
        scores, ids = sharded_flat_search(
            self.mesh, self.base, self.sqnorm, self.valid, q, k, self.metric
        )
        scores, ids = jax.device_get((scores, ids))
        return scores[:b], ids[:b]

"""Per-partition engine: orchestrates table + vector stores + indexes +
deletion bitmap.

TPU-native re-design of the reference's gamma Engine (reference:
internal/engine/search/engine.h:35 `vearch::Engine`; search entry
engine.cc:242, upsert engine.cc:691, brute-force fallback engine.cc:280-302,
background build engine.cc:966/1106). One Engine instance per partition;
the cluster layer (ps) holds a registry of them.

Write model (TPU-first): everything is append-only. An update soft-deletes
the old docid and appends a new row, so device vector buffers never mutate
rows — deletions are masked inside the top-k kernel. Compaction is an
offline rebuild (rebuild_index), as in the reference.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from vearch_tpu.cluster import metrics as cluster_metrics
from vearch_tpu.engine.bitmap import BitmapManager
from vearch_tpu.obs import accounting as _acct
from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.table import Table
from vearch_tpu.engine.types import (
    DataType,
    IndexParams,
    IndexStatus,
    ScalarIndexType,
    SearchResult,
    SearchResultItem,
    TableSchema,
)
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.registry import create_index
from vearch_tpu.utils import log

_log = log.get("engine")


@dataclass
class SearchRequest:
    """One batched vector search (reference: api_data/request.h:18).

    vectors: field name -> [B, d] query matrix. Multiple fields are merged
    with `field_weights` (reference: WeightedRanker, doc_query.go:202).
    filters: a scalar-filter AST (vearch_tpu.scalar.filter) or None.
    """

    vectors: dict[str, np.ndarray]
    k: int = 10
    filters: Any = None
    include_fields: list[str] | None = None
    brute_force: bool = False  # force exact scan even when indexed
    field_weights: dict[str, float] = field(default_factory=dict)
    index_params: dict[str, Any] = field(default_factory=dict)  # nprobe etc.
    # {field: (min_score, max_score)} — per-field windows on each
    # field's OWN metric-oriented score, applied inside the rank merge
    # for multi-field requests and on the final score for single-field
    # ones (reference: min_score/max_score per vector query,
    # test_document_search.py test_..._with_score_filter)
    score_bounds: dict[str, tuple] | None = None
    # normalized scalar-field sort specs (engine/sort.py parse_sort):
    # hits get per-spec sort values attached and each query's items are
    # returned ordered by them (reference: SortFields on the request,
    # doc_query.go:1543; sortorder value compare)
    sort: list[dict] | None = None
    # fields-free fast path: return ColumnarSearchResults (key lists +
    # one flat score buffer) instead of per-item objects — the serving
    # shape of the columnar wire; skips the microbatcher
    raw_results: bool = False
    # when not None, the engine records per-phase wall times into it
    # (reference: per-request trace:true timing breakdown,
    # client/client.go:521-565 + PerfTool, index_model.h:24)
    trace: dict[str, float] | None = None
    # cooperative cancellation (reference: RequestContext kill status,
    # api_data/request_context.h + Set/DeleteKillStatus c_api): checked
    # at phase boundaries — a killed request aborts before its next
    # device dispatch rather than mid-kernel
    ctx: "RequestContext | None" = None


class RequestKilled(Exception):
    pass


class RequestContext:
    """Kill flag for one in-flight request (reference:
    api_data/request_context.h; the PS slow-request killer and the
    /ps/kill admin both flip it).

    `deadline` (absolute `time.monotonic()` seconds — NOT wall epoch:
    an NTP step must not expire or immortalize a live request) arms
    check() itself: a request past its deadline self-kills at the next
    phase boundary — between device dispatches, never mid-kernel —
    without waiting on the PS killer loop's tick. `reason_code` is the
    bounded label the PS exports on vearch_requests_killed_total."""

    def __init__(self, request_id: str = "",
                 deadline: float | None = None):
        self.request_id = request_id
        self.deadline = deadline
        self.killed = False
        self.reason = ""
        self.reason_code = ""

    def kill(self, reason: str = "killed", code: str = "operator") -> None:
        self.killed = True
        self.reason = reason
        self.reason_code = code

    def check(self) -> None:
        if (not self.killed and self.deadline is not None
                and time.monotonic() > self.deadline):
            self.kill("deadline exceeded", code="deadline")
        if self.killed:
            raise RequestKilled(self.reason or "request killed")


class _FieldBuild:
    """In-flight scalar field-index build: target type, completion
    event, and the build's error (read by sync joiners)."""

    __slots__ = ("value", "done", "error")

    def __init__(self, value: str):
        self.value = value
        self.done = threading.Event()
        self.error: BaseException | None = None


class Engine:
    def __init__(self, schema: TableSchema, data_dir: str | None = None):
        from vearch_tpu.utils import enable_compilation_cache

        # opt-in via VEARCH_COMPILE_CACHE: compiled search programs
        # survive restarts, so warmup after a restart is a disk read
        enable_compilation_cache()
        self.schema = schema
        self.data_dir = data_dir
        self.table = Table(schema)
        self.bitmap = BitmapManager()
        self.vector_stores: dict[str, RawVectorStore] = {}
        self.indexes: dict[str, VectorIndex] = {}
        self.status = IndexStatus.UNINDEXED
        self.last_build_error: BaseException | None = None
        # current/last index-build job record (build_index fills it) —
        # the PS serves these at GET /ps/jobs and rides the terminal
        # status on heartbeats for the master's /cluster/health rollup
        self.build_job: dict | None = None
        # optional terminal-state sink (PS wires build-duration
        # histograms through it; covers background auto-builds too)
        self.build_observer = None
        # optional staleness sink for the search-quality layer (lint
        # VL105): fired on every wholesale index replacement — retrain
        # rebuilds the compressed serving tiers (int8 mirror AND the
        # stage-0 bit planes) in place, so queued shadow samples must
        # not be scored against the pre-rebuild snapshot. The PS resets
        # its QualityMonitor through build_observer; embedded users
        # (bench, SDK-local engines) wire this directly.
        self.mutation_observer = None
        self._write_lock = threading.Lock()
        # monotone data version: bumped under _write_lock by every
        # mutation that can change search results (upsert, delete,
        # schema/scalar-index changes). The serving caches key on it
        # for exact invalidation — stale entries are unreachable the
        # instant a write lands, and simply age out of their LRUs.
        self.data_version = 0
        # scalar-filter bitmap cache: (filter-json, data_version, n) ->
        # combined alive∧filter mask, so repeated filtered searches
        # skip both bitmap reconstruction and the columnar filter scan.
        # Cached masks are served without a copy — callers treat
        # `valid` as read-only (they already do).
        self._filter_cache: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._filter_cache_lock = threading.Lock()
        self._filter_cache_max = 128
        self.filter_cache_hits = 0
        self.filter_cache_misses = 0
        # field -> in-flight build marker; stops the heartbeat reconcile
        # loop re-spawning a build every 2s while a long background build
        # has yet to publish (flags only flip at publish time), lets sync
        # callers join an identical in-flight build, and gates publish on
        # the marker still being current (supersede/remove cancels it)
        self._field_builds: dict[str, _FieldBuild] = {}
        # continuous batching (engine/batching.py): lazily started on
        # the first qualifying search so idle engines spawn no thread
        self.micro_batch = True
        self.micro_batch_max_rows = 1024
        # age bound on a partially-filled shape bucket (ms); 0 = dispatch
        # the moment the dispatcher is free (zero added idle latency)
        self.batch_delay_ms = 0.0
        self._microbatcher = None
        # padded shape buckets (ops/perf_model.ROW_BUCKETS /
        # FETCH_K_TIERS): every serving dispatch is quantized to the
        # declared grid so the warmed program set is finite and
        # mixed-k traffic co-batches. Off reverts to free-form shapes
        # (the pre-bucket baseline, kept for A/B).
        self.shape_buckets = True
        # padding-waste accounting (best-effort counters; the doctor
        # flags sustained waste, /ps/stats surfaces them)
        self.pad_real_rows = 0
        self.pad_padded_rows = 0
        self.pad_waste_bytes = 0
        self._scalar_manager = None
        if schema.composite_indexes or any(
            f.scalar_index.value != "NONE" for f in schema.scalar_fields()
        ):
            from vearch_tpu.scalar.manager import ScalarIndexManager

            self._scalar_manager = ScalarIndexManager(schema)

        for f in schema.vector_fields():
            params = f.index or IndexParams()
            dtype = params.get("store_dtype", "float32")
            store_type = str(params.get("store_type", "MemoryOnly"))
            disk_index = params.index_type.upper() in (
                "DISKANN", "DISKANN_STATIC"
            )
            if store_type in ("Disk", "RocksDB") or disk_index:
                # disk tier (reference: RocksDBRawVector + DiskANN static
                # raw data): rows live in an mmap, not host RAM
                from vearch_tpu.engine.disk_vector import DiskRawVectorStore

                base = data_dir or tempfile.mkdtemp(prefix="vearch_disk_")
                store: RawVectorStore = DiskRawVectorStore(
                    f.dimension,
                    directory=os.path.join(base, f"disk_{f.name}"),
                    store_dtype=dtype,
                    row_cache_mb=int(params.get("row_cache_mb", 64)),
                )
            else:
                store = RawVectorStore(f.dimension, store_dtype=dtype)
            self.vector_stores[f.name] = store
            self.indexes[f.name] = create_index(params, store)

    # -- writes --------------------------------------------------------------

    def upsert(self, docs: list[dict[str, Any]]) -> list[str]:
        """Add-or-update a batch; returns assigned doc keys.

        Mirrors reference engine.cc:691 AddOrUpdate: existing key ==
        update -> old docid soft-deleted, new row appended everywhere.
        Partial updates carry omitted fields forward from the replaced
        row — an upsert without the vector updates scalars only
        (reference: test_document_upsert.py update() add(has_vector=
        False)); a NEW document must bring every vector field."""
        vf = self.schema.vector_fields()
        keys: list[str] = []
        with self._write_lock:
            # batch the vector appends: one host copy per field per call;
            # decode wire format (e.g. packed binary) via the index hook.
            # A doc whose vector is absent OR null inherits the row it
            # replaces — the latest provider of the same _id earlier in
            # THIS batch, else the stored row. All resolution and
            # validation happens BEFORE any mutation (a bad batch fails
            # whole; a mid-batch failure would desync the docid==row-id
            # invariant between table and vector stores forever), and
            # deterministically from engine state so raft replicas
            # resolve identically.
            for doc in docs:
                self.table.validate(
                    {k: v for k, v in doc.items() if k != "_id"}
                )
            mats = {}
            for f in vf:
                idx = self.indexes[f.name]
                store = self.vector_stores[f.name]
                have = [i for i, d in enumerate(docs)
                        if d.get(f.name) is not None]
                if len(have) == len(docs):
                    raw = np.asarray([d[f.name] for d in docs]).reshape(
                        len(docs), idx.input_dim
                    )
                    mats[f.name] = idx.decode_input(raw)
                    continue
                out = np.zeros((len(docs), store.dimension), np.float32)
                if have:
                    raw = np.asarray(
                        [docs[i][f.name] for i in have]
                    ).reshape(len(have), idx.input_dim)
                    out[have] = idx.decode_input(raw)
                latest: dict[str, int] = {}  # key -> out row in this batch
                for i, d in enumerate(docs):
                    key = str(d["_id"]) if "_id" in d else None
                    if d.get(f.name) is not None:
                        if key is not None:
                            latest[key] = i
                        continue
                    src = latest.get(key) if key is not None else None
                    if src is not None:
                        out[i] = out[src]
                        latest[key] = i
                        continue
                    old = (self.table.docid_of(key)
                           if key is not None else None)
                    if old is None:
                        raise ValueError(
                            f"document {key!r} omits vector field "
                            f"{f.name!r} and has no existing row to "
                            f"inherit it from"
                        )
                    out[i] = np.asarray(store.get(old), dtype=np.float32)
                    latest[key] = i
                mats[f.name] = out
            merged_docs = []
            for i, doc in enumerate(docs):
                key = str(doc["_id"]) if "_id" in doc else uuid.uuid4().hex
                fields = {k: v for k, v in doc.items() if k != "_id"}
                prev_id = self.table.docid_of(key)
                if prev_id is not None:
                    # partial scalar update: omitted fields keep their
                    # previous values — but only fields the previous doc
                    # actually SET (fixed columns materialize 0-defaults;
                    # carrying those forward would index phantom values)
                    prev_set = self.table.set_fields_of(prev_id)
                    for name, val in self.table.get_fields(
                            prev_id, list(prev_set)).items():
                        fields.setdefault(name, val)
                docid, old = self.table.add(key, fields)
                if old is not None:
                    self.bitmap.set_deleted(old)
                keys.append(key)
                merged_docs.append(fields)
            for f in vf:
                self.vector_stores[f.name].add(mats[f.name])
            if self._scalar_manager is not None:
                self._scalar_manager.add_docs(
                    merged_docs, len(self.table._keys) - len(docs)
                )
            self.data_version += 1
        self._maybe_start_build()
        return keys

    def delete(self, keys: list[str]) -> int:
        n = 0
        with self._write_lock:
            for key in keys:
                docid = self.table.delete(key)
                if docid is not None:
                    self.bitmap.set_deleted(docid)
                    n += 1
            if n:
                self.data_version += 1
        return n

    def get(
        self,
        keys: list[str],
        fields: list[str] | None = None,
        vector_value: bool = False,
    ) -> list[dict]:
        """Fetch docs by key. Vector payloads ride only when
        `vector_value` is set or a vector field is named in `fields`
        (reference: the `vector_value` request flag)."""
        out = []
        for key in keys:
            docid = self.table.docid_of(key)
            if docid is None or self.bitmap.is_deleted(docid):
                continue
            doc = {"_id": key, **self.table.get_fields(docid, fields)}
            for name, store in self.vector_stores.items():
                if vector_value or (fields is not None and name in fields):
                    doc[name] = store.get(docid).tolist()
            out.append(doc)
        return out

    @property
    def doc_count(self) -> int:
        """Alive docs (reference: engine status doc_num minus deletes)."""
        return self.table.doc_count - self.bitmap.deleted_count

    def memory_usage_bytes(self) -> int:
        """Host-side memory of the durable structures (raw vectors +
        quantized mirrors + codes). Drives the resource-limit write guard
        (reference: store_writer.go:82-95 resource check every 50k docs;
        memory/memoryManager.cc accounting)."""
        total = 0
        for store in self.vector_stores.values():
            if getattr(store, "durable_on_disk", False):
                total += store.memory_usage_bytes()  # page cache, not RSS
            else:
                total += store.host_view().nbytes  # used rows, not capacity
        for index in self.indexes.values():
            mirror = getattr(index, "_mirror", None)
            if mirror is not None:
                n = mirror.count
                total += n * (mirror.dimension + 8)  # int8 row + scale + vsq
            codes = getattr(index, "_codes", None)
            if codes is not None:
                total += codes.nbytes
        return total

    def quality_info(self) -> dict[str, Any]:
        """Index-health raw numbers for the quality monitor's drift
        gauges (obs/quality.py collect_health): deleted/unindexed
        fractions plus per-field quantization reconstruction error and
        cell-population imbalance. Host work only — no device dispatch
        (the monitor samples this on a background cadence)."""
        total = int(self.table.doc_count)
        deleted = int(self.bitmap.deleted_count)
        info: dict[str, Any] = {
            "doc_count": total - deleted,
            "deleted_count": deleted,
            "deleted_frac": deleted / total if total else 0.0,
            "data_version": int(self.data_version),
            "fields": {},
        }
        for name, index in self.indexes.items():
            n = int(index.store.count)
            if index.needs_training and n:
                unindexed = (n - min(int(index.indexed_count), n)) / n
            else:
                # FLAT-family indexes scan the raw store directly: the
                # tail is always searched, never "unindexed"
                unindexed = 0.0
            f: dict[str, Any] = {
                "index_type": index.params.index_type,
                "trained": bool(index.trained),
                "indexed_count": int(index.indexed_count),
                "unindexed_frac": unindexed,
            }
            try:
                f["recon_error"] = index.reconstruction_error()
            except Exception as e:
                cluster_metrics.internal_error("engine.quality_info", e)
                f["recon_error"] = None
            pops = index.cell_populations()
            if pops:
                arr = np.asarray(pops, dtype=np.float64)
                mean = float(arr.mean())
                f["ncells"] = len(pops)
                f["cell_min"] = int(arr.min())
                f["cell_max"] = int(arr.max())
                f["cell_imbalance_cv"] = (
                    float(arr.std() / mean) if mean > 0 else 0.0
                )
            info["fields"][name] = f
        return info

    def query(
        self,
        filters: Any = None,
        limit: int = 50,
        offset: int = 0,
        include_fields: list[str] | None = None,
        vector_value: bool = False,
        order_by_key: bool = True,
        sort: list[dict] | None = None,
    ) -> list[dict]:
        """Scalar-only query: filter docs without vector search
        (reference: engine.cc:404 ScalarIndexQuery-only path +
        /document/query). Vector payload rules match get().

        Matches are returned in _id order by default so the router's
        merge-then-slice global pagination is correct regardless of
        insertion order; pass order_by_key=False for drain-style callers
        (delete-by-filter) that don't care and shouldn't pay the sort.
        With `sort` (normalized specs, engine/sort.py), matches order by
        the scalar sort keys instead — _id tie-break — and each returned
        doc carries "_sort" values for the router's cross-partition
        merge (reference: QueryFieldSortExecute, client.go:1062).
        """
        n = self.table.doc_count
        valid = self.bitmap.valid_mask(n)
        if filters is not None:
            from vearch_tpu.scalar.filter import evaluate_filter

            valid = valid & evaluate_filter(filters, self, n)
        matched = np.nonzero(valid)[0]
        sort_rows: list[list] | None = None
        if sort and matched.size:
            matched, sort_rows = self._sorted_matches(matched, sort)
        elif order_by_key and matched.size:
            keys = np.array(
                [self.table.key_of(int(i)) for i in matched], dtype=object
            )
            matched = matched[np.argsort(keys, kind="stable")]
        hits = matched[offset : offset + limit]
        out = []
        for pos, docid in enumerate(hits):
            docid = int(docid)
            doc = {"_id": self.table.key_of(docid)}
            doc.update(self.table.get_fields(docid, include_fields))
            for name, store in self.vector_stores.items():
                if vector_value or (
                    include_fields is not None and name in include_fields
                ):
                    doc[name] = store.get(docid).tolist()
            if sort_rows is not None:
                doc["_sort"] = sort_rows[offset + pos]
            out.append(doc)
        return out

    def _sorted_matches(
        self, matched: np.ndarray, specs: list[dict]
    ) -> tuple[np.ndarray, list[list]]:
        """Order matched docids by the sort specs (stable, _id
        tie-break). Returns (ordered docids, per-docid sort values in
        the SAME order). Fixed numeric columns ride a vectorised
        np.lexsort; string/missing-capable fields fall back to a cmp
        sort."""
        from vearch_tpu.engine.sort import ID_FIELD, SCORE_FIELD, row_sort_key

        ids = matched.tolist()
        keys = [self.table.key_of(int(i)) for i in ids]
        value_cols: list[list] = []
        all_fixed = True
        for s in specs:
            f = s["field"]
            if f == ID_FIELD:
                value_cols.append(keys)
                all_fixed = False
                continue
            if f == SCORE_FIELD:
                # no vector score in a scalar query; router rejects this
                # upstream, a direct caller gets None values (sort last)
                value_cols.append([None] * len(ids))
                all_fixed = False
                continue
            try:
                col = self.table.column(f)
                value_cols.append(col[matched].tolist())
            except KeyError:
                # string (or unknown) field: per-doc lookup, None when
                # the doc lacks it
                all_fixed = False
                try:
                    scol = self.table.string_column(f)
                    value_cols.append([scol[i] for i in ids])
                except KeyError:
                    value_cols.append([None] * len(ids))
        if all_fixed and value_cols:
            # numeric fast path: lexsort with least-significant key
            # first -> feed (tie-break key, reversed spec columns)
            import numpy as _np

            # least-significant key first: (_id tie-break, then spec
            # columns in reverse). Keys are str -> unicode dtype
            # (np.lexsort rejects object arrays).
            lex_keys = [_np.asarray(keys)]
            for s, col in zip(reversed(specs), reversed(value_cols)):
                arr = _np.asarray(col)
                if arr.dtype == bool or arr.dtype.kind == "u":
                    arr = arr.astype(_np.int64)  # negate-safe
                lex_keys.append(-arr if s["desc"] else arr)
            order = _np.lexsort(lex_keys)
        else:
            rows = list(range(len(ids)))
            rows.sort(key=row_sort_key(
                specs,
                lambda r: [value_cols[c][r] for c in range(len(specs))],
                tie_key=lambda r: keys[r],
            ))
            order = rows
        ordered = matched[np.asarray(order, dtype=np.int64)]
        sort_rows = [
            [value_cols[c][r] for c in range(len(specs))] for r in order
        ]
        return ordered, sort_rows

    # -- index lifecycle -----------------------------------------------------

    def _maybe_start_build(self) -> None:
        """Kick off a background train+absorb once the training threshold is
        crossed (reference: the Indexing thread trains when doc volume
        passes training_threshold, engine.cc:1106). CAS-style guard mirrors
        the reference's IDLE->STARTING state machine (engine.cc:967)."""
        needs = [
            (name, idx)
            for name, idx in self.indexes.items()
            if idx.needs_training
            and not idx.trained
            and self.vector_stores[name].count >= self._training_threshold(idx)
        ]
        if not needs or self.status != IndexStatus.UNINDEXED:
            return
        self.status = IndexStatus.TRAINING
        t = threading.Thread(target=self.build_index, daemon=True,
                             name="engine-build")
        t.start()
        self._build_thread = t

    def wait_for_index(self, timeout: float | None = None) -> None:
        """Join an in-flight background build (tests / explicit flush)."""
        t = getattr(self, "_build_thread", None)
        if t is not None:
            t.join(timeout)

    def start_refresh_loop(self) -> None:
        """Background realtime pump: absorb new rows into every trained
        index at refresh_interval cadence so searches never pay the
        absorb cost inline (reference: engine.cc:1106-1158 Indexing loop
        sleeping refresh_interval_ between AddRTVecsToIndex passes)."""
        with self._write_lock:  # ordered against close()'s _closed write
            if getattr(self, "_refresh_thread", None) is not None:
                return
            if (getattr(self, "_closed", None) is not None
                    and self._closed.is_set()):
                return  # closed engines stay closed
            self._closed = threading.Event()

        def loop():
            while not self._closed.wait(
                max(self.schema.refresh_interval_ms, 50) / 1e3
            ):
                for name, index in self.indexes.items():
                    if index.trained:
                        try:
                            index.absorb(self.vector_stores[name].count)
                        except Exception as e:
                            self.last_build_error = e

        self._refresh_thread = threading.Thread(target=loop, daemon=True,
                                                name="engine-refresh")
        self._refresh_thread.start()

    def close(self) -> None:
        # under _write_lock, mirroring the lazy creation in search() and
        # the _closed creation in start_refresh_loop(): otherwise a
        # concurrent search could construct a fresh batcher after this
        # stop (or a racing start_refresh_loop could clobber the set
        # event with a fresh one), leaking threads bound to a closed
        # engine
        with self._write_lock:
            if getattr(self, "_closed", None) is None:
                # no refresh loop ever started; still record closedness
                # so apply_config can't re-enable micro-batching later
                self._closed = threading.Event()
            self._closed.set()
            self.micro_batch = False
            if self._microbatcher is not None:
                self._microbatcher.stop()
                self._microbatcher = None
        # outside _write_lock: index close only stops background tier
        # workers (prefetchers) and must not order under the write path
        for index in self.indexes.values():
            try:
                index.close()
            except Exception as e:
                log.warn("index close failed: %s", e)

    def apply_config(self, cfg: dict[str, Any]) -> dict[str, Any]:
        """Runtime-mutable engine config (reference: master /config API ->
        etcd -> PS watch, cluster_api.go:294-307; engine cache / limits).
        Supported: refresh_interval_ms, training_threshold, plus default
        index params merged per vector field."""
        if "refresh_interval_ms" in cfg:
            self.schema.refresh_interval_ms = int(cfg["refresh_interval_ms"])
        if "training_threshold" in cfg:
            self.schema.training_threshold = int(cfg["training_threshold"])
        if "micro_batch" in cfg:
            # under _write_lock to order against close(): an unlocked
            # check could pass just before close() completes and then
            # re-enable batching on the closed engine — search() would
            # lazily spawn a dispatcher thread bound to a dead engine
            with self._write_lock:
                closed = getattr(self, "_closed", None)
                if closed is None or not closed.is_set():
                    self.micro_batch = bool(cfg["micro_batch"])
        if "micro_batch_max_rows" in cfg:
            self.micro_batch_max_rows = int(cfg["micro_batch_max_rows"])
            mb = self._microbatcher
            if mb is not None:  # propagate to a live batcher
                mb.max_rows = self.micro_batch_max_rows
        if "batch_delay_ms" in cfg:
            self.batch_delay_ms = float(cfg["batch_delay_ms"])
            mb = self._microbatcher
            if mb is not None:  # propagate to a live scheduler
                mb.max_delay_ms = self.batch_delay_ms
        if "shape_buckets" in cfg:
            # A/B escape hatch: free-form dispatch shapes (the
            # pre-bucket baseline). The scheduler reads this per submit,
            # so flipping it also reverts co-batching to exact-k keys.
            self.shape_buckets = bool(cfg["shape_buckets"])
        if "mesh_shape" in cfg:
            # serving-mesh shape ("DxQ", [data, query], or device
            # count): fans into every vector field's index params, same
            # pattern as mesh_serving; parallel/mesh.mesh_from_shape
            # resolves it to one cached Mesh so the program caches and
            # the sharded row caches key consistently
            for index in self.indexes.values():
                index.params.params["mesh_shape"] = cfg["mesh_shape"]
        if "mesh_serving" in cfg:
            # space-level toggle for the multi-chip data plane: fan the
            # mode into every vector field's index params (per-field
            # overrides still win via index_params below)
            for index in self.indexes.values():
                index.params.params["mesh_serving"] = cfg["mesh_serving"]
        for name, params in (cfg.get("index_params") or {}).items():
            if name in self.indexes:
                self.indexes[name].params.params.update(params)
        if cfg.get("warmup"):
            # re-trace after changing warmup_batches / index params at
            # runtime without waiting for the next build
            self.warmup()
        return {
            "refresh_interval_ms": self.schema.refresh_interval_ms,
            "training_threshold": self.schema.training_threshold,
        }

    # -- online scalar field indexes (reference: AddFieldIndexWithParams /
    #    RemoveFieldIndex, c_api/gamma_api.h:166,181; Go seam
    #    gammacb/gamma.go:538,591 — dedicated add-field/remove-field
    #    threads build while searches keep serving) -------------------------

    def add_field_index(
        self, field: str, index_type: str = "INVERTED",
        background: bool = True,
    ) -> None:
        """Build a scalar index on a live field. The build runs over a
        snapshot of the column WITHOUT the write lock (searches keep
        scanning meanwhile), then catches up and publishes atomically
        under the lock — from that moment filters use the index."""
        f = self.schema.field(field)
        if f.data_type is DataType.VECTOR:
            raise ValueError(f"{field} is a vector field")
        itype = ScalarIndexType(index_type.upper())
        if itype is ScalarIndexType.NONE:
            return self.remove_field_index(field)
        with self._write_lock:
            cur = self._field_builds.get(field)
            if cur is not None and cur.value == itype.value:
                if not background:
                    # sync contract: the index must be live on return,
                    # even when an identical build is already in flight
                    pending = cur
                else:
                    return  # identical background build already in flight
            else:
                pending = None
                marker = _FieldBuild(itype.value)
                self._field_builds[field] = marker
        if pending is not None:
            pending.done.wait()
            if pending.error is not None:
                # joining must not report success for a failed build
                raise pending.error
            return

        def build() -> None:
            from vearch_tpu.scalar.manager import _NUMERIC
            from vearch_tpu.scalar.indexes import (
                BitmapScalarIndex, InvertedScalarIndex,
            )

            if itype is ScalarIndexType.BITMAP:
                index = BitmapScalarIndex()
            else:
                dtype = _NUMERIC.get(f.data_type)
                index = InvertedScalarIndex(
                    np.dtype(dtype) if dtype else np.dtype(object)
                )

            def rows(lo: int, hi: int):
                try:
                    return self.table.column(field)[lo:hi]
                except KeyError:
                    return self.table.string_column(field)[lo:hi]

            def indexable(docid: int, value) -> bool:
                # presence-gated like every other index-build path:
                # fixed-column 0-defaults of never-set fields must not
                # become filterable values
                return (value is not None
                        and field in self.table.set_fields_of(docid))

            built = 0
            # bulk phase, lock-free: columns are append-only so the
            # captured slice is stable
            while True:
                hi = self.table.doc_count
                if hi <= built:
                    break
                for docid, value in enumerate(rows(built, hi), start=built):
                    if indexable(docid, value):
                        index.add(value, docid)
                built = hi
            with self._write_lock:
                if self._field_builds.get(field) is not marker:
                    # superseded mid-build (a remove, or a build of a
                    # different type): publishing now would resurrect a
                    # dropped index or clobber the newer build
                    return
                # exact catch-up: rows that landed since the last pass
                hi = self.table.doc_count
                for docid, value in enumerate(rows(built, hi), start=built):
                    if indexable(docid, value):
                        index.add(value, docid)
                if self._scalar_manager is None:
                    from vearch_tpu.scalar.manager import ScalarIndexManager

                    self._scalar_manager = ScalarIndexManager(self.schema)
                self._scalar_manager.add_field(field, index)
                f.scalar_index = itype  # dumps persist the new schema
                self.data_version += 1

        def run() -> None:
            try:
                build()
            except BaseException as e:
                marker.error = e
                if not background:
                    raise
                _log.warning("background field-index build %r failed: %s",
                             field, e)
            finally:
                with self._write_lock:
                    # pop only OUR marker: an overlapping build of a
                    # different type replaced it, and erasing that one
                    # would let the heartbeat reconcile spawn duplicates
                    if self._field_builds.get(field) is marker:
                        self._field_builds.pop(field)
                marker.done.set()

        if background:
            t = threading.Thread(
                target=run, daemon=True,
                name=f"vearch-field-index-{field}",
            )
            t.start()
        else:
            run()

    def add_schema_field(self, f) -> None:
        """Online schema evolution: add a NEW scalar field (reference:
        updateSpaceFields — only additions allowed on live spaces).
        Idempotent; vector fields are rejected."""
        if f.data_type is DataType.VECTOR:
            raise ValueError("vector fields cannot be added to a live space")
        target = f.scalar_index
        with self._write_lock:
            if any(x.name == f.name for x in self.schema.fields):
                return
            # append with NO index flag: the flag flips only when the
            # build publishes — the invariant the heartbeat reconcile
            # relies on to retry a failed build (flag != master's
            # expectation) instead of believing a dead index is live
            f.scalar_index = ScalarIndexType.NONE
            self.schema.fields.append(f)
            self.table.add_field(f)
            self.data_version += 1
        if target is not ScalarIndexType.NONE:
            self.add_field_index(f.name, target.value)

    def remove_field_index(self, field: str) -> None:
        """Drop a field's scalar index; in-flight filtered searches fall
        back to the columnar scan (filter.py tolerates the race)."""
        f = self.schema.field(field)
        with self._write_lock:
            # cancel any in-flight build: orphaning its marker makes the
            # publish-currency check refuse, so the dropped index cannot
            # resurrect after this remove
            self._field_builds.pop(field, None)
            if self._scalar_manager is not None:
                self._scalar_manager.remove_field(field)
            f.scalar_index = ScalarIndexType.NONE
            self.data_version += 1

    def build_index(self, field_name: str | None = None,
                    op: str = "build") -> None:
        """Train + absorb all current rows (reference: engine.cc:966
        BuildIndex -> Indexing thread; here synchronous — the cluster
        layer wraps it in a background thread).

        The build is an observable job: `self.build_job` tracks phase
        (train / assign / publish / warmup), progress (docs_done /
        docs_total) and terminal status while the build runs, with the
        real wall window of each phase kept as `_phase_spans` rows for
        the PS to replay into /debug/traces."""
        t_start = time.monotonic()
        # one wall anchor for span epochs + operator-facing timestamps;
        # phase durations are measured monotonically and offset from it
        wall0 = time.time() - t_start  # lint: allow[wall-clock] span epoch anchor, correlates with collector time
        targets = [
            (name, idx) for name, idx in self.indexes.items()
            if field_name is None or name == field_name
        ]
        job: dict[str, Any] = {
            "op": op, "status": "running", "phase": "train",
            "docs_total": sum(
                self.vector_stores[n].count for n, _ in targets),
            "docs_done": 0,
            "started": wall0 + t_start, "updated": wall0 + t_start,
            "phases_ms": {}, "error": None, "_phase_spans": [],
        }
        self.build_job = job
        phases = job["_phase_spans"]

        def mark(phase: str, t0: float, t1: float) -> None:
            phases.append((f"build.{phase}", int((wall0 + t0) * 1e6),
                           int((t1 - t0) * 1e6)))
            job["phases_ms"][phase] = round(
                job["phases_ms"].get(phase, 0.0) + (t1 - t0) * 1e3, 3)
            job["phase"] = phase
            job["updated"] = wall0 + t1

        # train/assign specialise kernels by design — expected compiles,
        # not serving-path regressions the flight recorder should ring
        from vearch_tpu.obs.flight_recorder import RECORDER

        self.status = IndexStatus.TRAINING
        try:
            with RECORDER.warmup():
                for name, index in targets:
                    store = self.vector_stores[name]
                    if index.needs_training and not index.trained:
                        t0 = time.monotonic()
                        index.train(store.host_view())
                        mark("train", t0, time.monotonic())
                        # which mesh trained the coarse quantizer (None
                        # = single device); the PS replays it as a tag
                        # on the build.train span
                        tm = getattr(index, "last_train_mesh", None)
                        if tm:
                            job["train_mesh"] = tm
                    t0 = time.monotonic()
                    index.absorb(store.count)
                    mark("assign", t0, time.monotonic())
                    job["docs_done"] += store.count
        except Exception as e:
            # a failed (possibly background) build must not wedge the
            # engine in TRAINING: record, reset, keep serving brute-force
            self.last_build_error = e
            self.status = IndexStatus.UNINDEXED
            now = time.monotonic()
            job.update(status="error",
                       error=f"{type(e).__name__}: {e}",
                       duration_seconds=round(now - t_start, 3),
                       updated=wall0 + now)
            self._notify_build(job)
            raise
        t0 = time.monotonic()
        self.status = IndexStatus.INDEXED
        mark("publish", t0, time.monotonic())
        # pre-trace the serving programs for the configured batch buckets
        # now, at publish time, so the first real query never pays the
        # compile stall (no-op unless "warmup_batches" is configured)
        t0 = time.monotonic()
        self.warmup(field_name=field_name)
        mark("warmup", t0, time.monotonic())
        now = time.monotonic()
        job.update(status="done", phase="done",
                   duration_seconds=round(now - t_start, 3),
                   updated=wall0 + now)
        self._notify_build(job)

    def _notify_build(self, job: dict) -> None:
        obs = self.build_observer
        if obs is not None:
            try:
                obs(job)
            except Exception:
                pass  # observability must never fail a build

    def warmup(
        self,
        batches: list[int] | None = None,
        k: int = 10,
        field_name: str | None = None,
    ) -> dict[str, list[int]]:
        """Pre-trace + compile the jitted search programs for the given
        query-batch sizes (default: each index's "warmup_batches" param).

        Runs real searches through the serving path with stored rows as
        queries, so the exact (shape, static-args) specialisations the
        first requests would compile are already in the jit cache — and,
        when the persistent compilation cache is enabled, on disk. The
        perf gates assert the effect: after warmup, repeated same-shape
        searches add ZERO new compiled programs. Returns the batch sizes
        traced per field.
        """
        # warmup compiles are the point, not a serving regression: keep
        # them out of the compile-audit flight recorder's ring
        from vearch_tpu.obs.flight_recorder import RECORDER

        done: dict[str, list[int]] = {}
        with RECORDER.warmup():
            return self._warmup_inner(done, batches, k, field_name)

    def _warmup_inner(self, done, batches, k, field_name):
        for name, index in self.indexes.items():
            if field_name is not None and name != field_name:
                continue
            store = self.vector_stores[name]
            if store.count == 0:
                continue
            b_list = batches if batches is not None else list(
                index.params.get("warmup_batches", []) or []
            )
            if not b_list:
                continue
            # a live row, not zeros: cosine normalisation of an all-zero
            # query would exercise a degenerate code path
            row = np.asarray(store.host_view()[:1], dtype=np.float32)
            valid = self._device_alive_mask(self.table.doc_count)
            kk = max(1, min(int(k), store.count))
            b_set = {int(x) for x in b_list if int(x) > 0}
            if self.shape_buckets:
                # warm the shapes serving will actually dispatch: the
                # engine quantizes rows and fetch-k to the declared
                # buckets, so warming the raw sizes would compile
                # programs no request ever runs
                from vearch_tpu.ops import perf_model as _perf

                kk = _perf.bucket_fetch_k(kk)
                b_set = {_perf.bucket_rows(b) for b in b_set}
            for b in sorted(b_set):
                q = np.repeat(row, b, axis=0)
                if index.trained:
                    index.search(q, kk, valid)
                else:
                    from vearch_tpu.index.flat import FlatIndex

                    FlatIndex(
                        IndexParams(metric_type=index.metric), store
                    ).search(q, kk, valid)
                done.setdefault(name, []).append(b)
        return done

    def note_index_mutation(self, op: str = "") -> None:
        """Staleness hook (lint VL105): forward a wholesale index
        replacement to the wired quality observer. Safe at any
        frequency; observability must never fail the mutation."""
        obs = self.mutation_observer
        if obs is not None:
            try:
                obs(op)
            except Exception:
                pass

    def rebuild_index(self) -> None:
        """Retrain from scratch (reference: engine.cc:1007 RebuildIndex)."""
        for name, index in self.indexes.items():
            params = index.params
            store = self.vector_stores[name]
            self.indexes[name] = create_index(params, store)
        self.status = IndexStatus.UNINDEXED
        self.build_index(op="rebuild")
        # the retrain replaced the quantizers, the int8 mirror AND the
        # stage-0 bit planes wholesale
        self.note_index_mutation(op="rebuild")

    def _training_threshold(self, index: VectorIndex) -> int:
        """Docs required before auto-build starts; explicit build_index()
        ignores it (reference: /index/forcemerge trains immediately)."""
        return int(
            index.params.get(
                "training_threshold", self.schema.training_threshold or 100_000
            )
        )

    # -- search --------------------------------------------------------------

    def _device_alive_mask(self, n: int):
        import jax.numpy as jnp

        key = (self.bitmap.version, n)
        if getattr(self, "_mask_cache_key", None) != key:
            self._mask_cache = jnp.asarray(self.bitmap.valid_mask(n))
            self._mask_cache_key = key
        return self._mask_cache

    def search(self, req: SearchRequest) -> list[SearchResult]:
        """Search entry: compatible concurrent requests pack into padded
        shape buckets and share one device dispatch
        (engine/batching.py); filtered, brute-force, and
        batching-disabled requests run directly."""
        if (
            self.micro_batch
            and req.filters is None
            and not req.brute_force
            and not req.raw_results
            and req.vectors
        ):
            mb = self._microbatcher
            if mb is None:
                with self._write_lock:
                    mb = self._microbatcher
                    # re-check micro_batch under the lock: close() flips
                    # it to False before stopping the batcher
                    if mb is None and self.micro_batch:
                        from vearch_tpu.engine.batching import BatchScheduler

                        mb = self._microbatcher = BatchScheduler(
                            self, max_rows=self.micro_batch_max_rows,
                            max_delay_ms=self.batch_delay_ms,
                        )
            if mb is not None:
                return mb.submit(req)
        # direct path: the whole engine wall slice bills to the bound
        # space (the scheduler path apportions inside _run_bucket)
        t0 = time.monotonic()
        try:
            return self._search_direct(req)
        finally:
            _acct.ACCOUNTANT.charge(
                "device_us", int((time.monotonic() - t0) * 1e6))

    def _filtered_mask(self, filters: Any, n: int) -> np.ndarray:
        """Alive∧filter mask for the first `n` rows, cached on
        (filter expression, data_version, n).

        The version is captured BEFORE evaluation: a write landing
        mid-evaluation bumps data_version, so the (possibly mixed)
        mask stays keyed to the old version and the next search —
        which reads the new version — recomputes. Searches concurrent
        with the write get no weaker ordering than they had uncached.
        """
        from vearch_tpu.scalar.filter import evaluate_filter

        version = self.data_version
        try:
            fkey = json.dumps(filters, sort_keys=True, default=str)
        except (TypeError, ValueError):
            fkey = None  # un-canonicalizable filter object: no caching
        if fkey is not None:
            key = (fkey, version, n)
            with self._filter_cache_lock:
                mask = self._filter_cache.get(key)
                if mask is not None:
                    self._filter_cache.move_to_end(key)
                    self.filter_cache_hits += 1
                    return mask
                self.filter_cache_misses += 1
        mask = self.bitmap.valid_mask(n) & evaluate_filter(
            filters, self, n
        )
        if fkey is not None:
            with self._filter_cache_lock:
                self._filter_cache[key] = mask
                self._filter_cache.move_to_end(key)
                while len(self._filter_cache) > self._filter_cache_max:
                    self._filter_cache.popitem(last=False)
        return mask

    def _search_direct(self, req: SearchRequest) -> list[SearchResult]:
        if not req.vectors:
            raise ValueError("search needs at least one vector field")
        import time as _time

        # Phase profiling (observability tentpole): when req.trace is a
        # dict, every engine phase records its wall window — both as a
        # flat `{phase}_ms` key (the profile=true breakdown) and as a
        # `_phase_spans` [name, start_us, dur_us] list the PS turns into
        # retroactive child spans under ps.search. A per-request
        # dispatch capture (ops/ivf.py) records which device programs
        # this search launched so the trace can carry measured dispatches
        # next to the perf model's DOCUMENTED_DISPATCHES prediction.
        tracing = req.trace is not None
        phases: list[tuple[str, float, float]] = []
        capture = None
        if tracing:
            from vearch_tpu.ops import ivf as _ivf_ops

            capture = _ivf_ops.begin_capture()
        try:
            t_start = _time.monotonic()
            n = self.table.doc_count
            if req.filters is not None:
                valid = self._filtered_mask(req.filters, n)
            else:
                # no filter -> the alive mask only changes on writes;
                # keep it device-resident so the hot path skips a
                # [n]-bool H2D upload
                valid = self._device_alive_mask(n)
            if tracing:
                t_filter = _time.monotonic()
                req.trace["filter_ms"] = round((t_filter - t_start) * 1e3, 3)
                phases.append(("engine.filter", t_start, t_filter))

            metrics = {self.indexes[name].metric for name in req.vectors}
            if len(metrics) > 1:
                raise ValueError(
                    "multi-field search requires a single metric across "
                    f"fields; got {[m.value for m in metrics]}"
                )

            from vearch_tpu.ops import perf_model as _perf

            per_field: dict[str, tuple[np.ndarray, np.ndarray]] = {}
            queries_by_field: dict[str, np.ndarray] = {}
            fetch_k = req.k if len(req.vectors) == 1 else max(req.k * 4, 50)
            if self.shape_buckets:
                # quantize the candidate depth UP to the declared tier —
                # uniformly, solo and batched alike, so co-batching
                # requests of differing k stays bit-identical to solo
                # runs (both scan at the tier; _shape_results trims each
                # caller to its own k) and the compiled-program universe
                # per path is bounded by the declared grid
                fetch_k = _perf.bucket_fetch_k(fetch_k)
            for name, queries in req.vectors.items():
                if req.ctx is not None:
                    req.ctx.check()
                t_field = _time.monotonic()
                index = self.indexes[name]
                queries = np.asarray(queries)  # lint: allow[host-sync] host-side input normalization, queries arrive as lists/host arrays
                if queries.ndim == 1:
                    queries = queries[None, :]
                queries = index.decode_input(
                    queries.reshape(queries.shape[0], index.input_dim)
                )
                queries_by_field[name] = queries
                b_rows = int(queries.shape[0])
                q_run = queries
                if self.shape_buckets:
                    # pad the row axis up to the declared bucket with a
                    # REAL row (cosine normalisation of a zero row is
                    # degenerate); every scan path is per-query-row, so
                    # slicing the pad rows back off preserves results
                    bb = _perf.bucket_rows(b_rows)
                    if bb != b_rows:
                        q_run = np.concatenate(
                            [queries,
                             np.repeat(queries[-1:], bb - b_rows, axis=0)],
                            axis=0,
                        )
                    self.pad_real_rows += b_rows
                    self.pad_padded_rows += bb
                    self.pad_waste_bytes += _perf.padding_waste_bytes(
                        b_rows, bb, int(queries.shape[1])
                    )
                store = self.vector_stores[name]
                use_index = index.trained and not req.brute_force
                if use_index:
                    if index.indexed_count < store.count:
                        # realtime pump: absorb rows that arrived since
                        # the last pass (reference: AddRTVecsToIndex)
                        index.absorb(store.count)
                    scores, ids = index.search(
                        q_run, fetch_k, valid, req.index_params or None
                    )
                else:
                    # brute-force fallback below training threshold
                    # (reference: engine.cc:280-302)
                    from vearch_tpu.index.flat import FlatIndex

                    flat = FlatIndex(
                        IndexParams(metric_type=index.metric), store
                    )
                    scores, ids = flat.search(q_run, fetch_k, valid)
                per_field[name] = (scores[:b_rows], ids[:b_rows])
                if tracing:
                    from vearch_tpu.ops import ivf as _ivf_ops

                    # close the open dispatch window: device work for
                    # this field is done (device_get already blocked)
                    _ivf_ops.capture_mark()
                    t_done = _time.monotonic()
                    req.trace[f"search_{name}_ms"] = round(
                        (t_done - t_field) * 1e3, 3
                    )
                    phases.append((f"engine.search.{name}", t_field, t_done))

            if req.ctx is not None:
                req.ctx.check()
            t_merge = _time.monotonic()
            merged = self._merge_fields(per_field, queries_by_field, req)
            t_shape = _time.monotonic()
            results = self._shape_results(merged, req)
            if tracing:
                t_end = _time.monotonic()
                req.trace["merge_ms"] = round((t_shape - t_merge) * 1e3, 3)
                req.trace["shape_ms"] = round((t_end - t_shape) * 1e3, 3)
                phases.append(("engine.merge", t_merge, t_shape))
                phases.append(("engine.shape", t_shape, t_end))
                req.trace["total_ms"] = round((t_end - t_start) * 1e3, 3)
                req.trace["doc_count"] = self.doc_count
            return results
        finally:
            if capture is not None:
                from vearch_tpu.ops import ivf as _ivf_ops

                _ivf_ops.end_capture()
                self._record_dispatch_trace(req, capture, phases)

    def _record_dispatch_trace(self, req, capture, phases) -> None:
        """Fold the per-request dispatch capture + phase windows into
        req.trace: measured dispatches (tags, per-dispatch wall ms) next
        to the perf model's prediction for the matched serving path, so
        model drift is visible per request (ROADMAP: perf gates as live
        signals). `_phase_spans` is consumed by cluster/ps.py to emit
        engine/kernel child spans."""
        from vearch_tpu.ops import perf_model

        trace = req.trace
        if trace is None:
            return
        tags = capture.tags
        trace["dispatches"] = tags
        trace["dispatch_count"] = len(tags)
        for tag, t0, t1 in capture.events:
            if t1 is not None:
                key = f"dispatch_{tag}_ms"
                trace[key] = round(
                    trace.get(key, 0.0) + (t1 - t0) * 1e3, 3
                )
        path = perf_model.path_for_dispatches(tags)
        if path is not None:
            trace["perf_path"] = path
            trace["predicted_dispatches"] = list(
                perf_model.DOCUMENTED_DISPATCHES[path]
            )
        trace["predicted_scan_bytes"] = sum(
            self._predicted_scan_bytes(name) for name in req.vectors
        )
        # extend, don't replace: the microbatcher may have noted its
        # queue wait on this trace before the search ran. Phase/capture
        # stamps are monotonic; mono_us anchors them to the epoch.
        from vearch_tpu.utils import mono_us

        spans = list(trace.get("_phase_spans") or [])
        spans += [
            [name, mono_us(t0), int((t1 - t0) * 1e6)]
            for name, t0, t1 in phases
        ]
        spans.extend(
            [f"kernel.{tag}", mono_us(t0), int((t1 - t0) * 1e6)]
            for tag, t0, t1 in capture.events
            if t1 is not None
        )
        spans.extend(
            [f"mesh.{name}", mono_us(t0), int((t1 - t0) * 1e6)]
            for name, t0, t1 in capture.mesh_phases
        )
        spans.extend(
            [f"tier.{name}", mono_us(t0), int((t1 - t0) * 1e6)]
            for name, t0, t1 in capture.tier_phases
        )
        spans.extend(
            [f"stage.{name}", mono_us(t0), int((t1 - t0) * 1e6)]
            for name, t0, t1 in capture.stage_phases
        )
        trace["_phase_spans"] = spans
        if capture.mesh_phases or any(t.startswith("sharded") for t in tags):
            info = self.mesh_info()
            if info is not None:
                trace["mesh"] = info
        if capture.tier_phases:
            tinfo = self.tiering_info()
            if tinfo is not None:
                trace["tiering"] = tinfo

    def mesh_info(self) -> dict[str, Any] | None:
        """Aggregate mesh data-plane summary over the engine's vector
        fields (surfaced in /ps/stats and profile:true traces); None
        when no field serves through the mesh."""
        fields = {}
        for name, index in self.indexes.items():
            try:
                info = index.mesh_info()
            except Exception:
                info = None
            if info is not None:
                fields[name] = info
        if not fields:
            return None
        out: dict[str, Any] = {
            "devices": max(f["devices"] for f in fields.values()),
            "fields": fields,
        }
        return out

    def tiering_info(self) -> dict[str, Any] | None:
        """Aggregate tiered-storage summary over the engine's vector
        fields (surfaced in /ps/stats and profile:true traces); None
        when no field serves through the storage tiers."""
        fields: dict[str, Any] = {}
        for name, index in self.indexes.items():
            try:
                info = index.tiering_info()
            except Exception:
                info = None
            row_cache = getattr(self.vector_stores[name], "row_cache", None)
            if row_cache is not None:
                info = dict(info or {"kind": "disk_store"})
                info["row_cache"] = row_cache.stats()
            if info is not None:
                fields[name] = info
        if not fields:
            return None
        return {"fields": fields}

    def _predicted_scan_bytes(self, name: str) -> int:
        """Perf-model prediction of stage-1 scan HBM read bytes for one
        field (ops/perf_model.scan_traffic_bytes): the int8 mirror when
        one is published, else the raw store rows."""
        from vearch_tpu.ops import perf_model

        index = self.indexes[name]
        store = self.vector_stores[name]
        d = store.dimension
        mirror = getattr(index, "_mirror", None)
        try:
            if mirror is not None and getattr(mirror, "_h8", None) is not None:
                return perf_model.scan_traffic_bytes(
                    1, int(mirror._h8.shape[0]), d, "xla_full"
                )
        except Exception:
            pass
        return int(store.count) * d * int(store.store_dtype.itemsize)

    def _exact_score(
        self, name: str, query: np.ndarray, docids: list[int]
    ) -> np.ndarray:
        """Host-side exact similarity scores for a small candidate set
        (union rescoring in the multi-field merge)."""
        from vearch_tpu.engine.types import MetricType

        store = self.vector_stores[name]
        vecs = np.stack([store.get(i) for i in docids])
        metric = self.indexes[name].metric
        dots = vecs @ query
        if metric is MetricType.INNER_PRODUCT:
            return dots
        if metric is MetricType.COSINE:
            qn = max(float(np.linalg.norm(query)), 1e-15)
            vn = np.maximum(np.linalg.norm(vecs, axis=1), 1e-15)
            return dots / (qn * vn)
        return -(np.sum((vecs - query) ** 2, axis=1))

    def _merge_fields(
        self,
        per_field: dict[str, tuple[np.ndarray, np.ndarray]],
        queries_by_field: dict[str, np.ndarray],
        req: SearchRequest,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Multi-vector-field rank merge with weights (reference:
        vector_manager.cc:748 docid-sorted merge + WeightedRanker).

        Candidates = union of per-field top lists; every candidate is then
        rescored *exactly* in every field, so a doc missing from one
        field's truncated list still gets its true weighted score."""
        if len(per_field) == 1:
            return next(iter(per_field.values()))
        names = list(per_field)
        b = per_field[names[0]][0].shape[0]
        out_scores = []
        out_ids = []
        for qi in range(b):
            union: set[int] = set()
            for name in names:
                _, ids = per_field[name]
                scores = per_field[name][0]
                union.update(
                    int(i)
                    for s, i in zip(scores[qi], ids[qi])
                    if i >= 0 and np.isfinite(s)
                )
            cand = sorted(union)
            if not cand:
                out_ids.append([-1] * req.k)
                out_scores.append([float("-inf")] * req.k)
                continue
            total = np.zeros(len(cand), dtype=np.float64)
            keep = np.ones(len(cand), dtype=bool)
            for name in names:
                w = req.field_weights.get(name, 1.0)
                sf = self._exact_score(
                    name, queries_by_field[name][qi], cand
                )
                if req.score_bounds and name in req.score_bounds:
                    # per-field window on the FIELD's own score, as the
                    # reference attaches min/max_score to each vector
                    # query — not to the fused total
                    from vearch_tpu.ops.distance import score_to_metric

                    lo, hi = req.score_bounds[name]
                    mf = np.asarray(score_to_metric(
                        np.asarray(sf), self.indexes[name].metric))
                    if lo is not None:
                        keep &= mf >= lo
                    if hi is not None:
                        keep &= mf <= hi
                total += w * sf
            total = np.where(keep, total, -np.inf)
            order = np.argsort(-total)[: req.k]
            ids_row = [
                cand[i] if np.isfinite(total[i]) else -1 for i in order
            ]
            sc_row = [float(total[i]) for i in order]
            pad = req.k - len(ids_row)
            out_ids.append(ids_row + [-1] * pad)
            out_scores.append(sc_row + [float("-inf")] * pad)
        return np.asarray(out_scores), np.asarray(out_ids)

    def _shape_results(
        self, merged: tuple[np.ndarray, np.ndarray], req: SearchRequest
    ) -> list[SearchResult]:
        from vearch_tpu.ops.distance import score_to_metric

        scores, ids = merged
        metric = self.indexes[next(iter(req.vectors))].metric
        # fully vectorised shaping: one score conversion, one key gather,
        # one column gather per field for the whole batch — the per-item
        # Python loop here was a measured chunk of e2e latency (r1
        # VERDICT weak-3)
        scores = np.asarray(scores)
        ids = np.asarray(ids)
        k = min(req.k, scores.shape[1])
        scores, ids = scores[:, :k], ids[:, :k]
        metric_scores = np.asarray(score_to_metric(scores, metric))
        want_fields = req.include_fields is None or bool(req.include_fields)
        ok = (ids >= 0) & np.isfinite(scores)
        if req.score_bounds and len(req.vectors) == 1:
            # single-field: the final score IS the field's score, so the
            # window applies here; multi-field requests already applied
            # per-field windows inside the rank merge
            los = [b[0] for b in req.score_bounds.values()
                   if b[0] is not None]
            his = [b[1] for b in req.score_bounds.values()
                   if b[1] is not None]
            if los:
                ok &= metric_scores >= max(los)
            if his:
                ok &= metric_scores <= min(his)
        flat_ids = ids[ok].astype(np.int64)
        keys = self.table.keys_for(flat_ids)
        if req.raw_results and not req.sort and not want_fields:
            # columnar serving shape: no per-item objects, scores stay
            # one numpy buffer end to end
            from vearch_tpu.engine.types import ColumnarSearchResults

            counts = ok.sum(axis=1).tolist()
            out_keys, pos = [], 0
            for c in counts:
                out_keys.append(keys[pos:pos + c])
                pos += c
            return ColumnarSearchResults(
                keys=out_keys,
                scores=np.ascontiguousarray(metric_scores[ok],
                                            dtype=np.float32),
            )
        fields_list = (
            self.table.gather_rows(flat_ids, req.include_fields)
            if want_fields
            else [{}] * len(keys)
        )
        flat_scores = metric_scores[ok].tolist()
        sort_rows = self._sort_value_rows(
            req.sort, flat_ids, keys, flat_scores,
            fields_list if want_fields else None, req.include_fields)
        counts = ok.sum(axis=1).tolist()
        results = []
        pos = 0
        for c in counts:
            items = [
                SearchResultItem(key=keys[j], score=float(flat_scores[j]),
                                 fields=fields_list[j],
                                 sort_values=sort_rows[j]
                                 if sort_rows is not None else None)
                for j in range(pos, pos + c)
            ]
            if req.sort:
                self._order_items(items, req.sort, metric)
            results.append(SearchResult(items=items))
            pos += c
        return results

    def _sort_value_rows(
        self, specs: list[dict] | None, flat_ids: np.ndarray,
        keys: list[str], flat_scores: list[float],
        fields_list: list[dict] | None,
        include_fields: list[str] | None,
    ) -> list[list] | None:
        """Per-hit sort-value lists (spec order) for the whole flat
        batch. _score and _id come from the hit itself; scalar fields
        are read from the already-gathered projection when it covers
        them (the router auto-adds sort fields to non-empty
        projections, so the common case pays zero extra gathers) and
        fetched in one extra gather only for fields==[] requests."""
        if not specs:
            return None
        from vearch_tpu.engine.sort import ID_FIELD, SCORE_FIELD

        covered = (fields_list is not None
                   and (include_fields is None
                        or set(include_fields).issuperset(
                            s["field"] for s in specs
                            if s["field"] not in (ID_FIELD, SCORE_FIELD))))
        if covered:
            field_rows = fields_list
        else:
            scalar_fields = [s["field"] for s in specs
                             if s["field"] not in (ID_FIELD, SCORE_FIELD)]
            field_rows = (
                self.table.gather_rows(flat_ids, scalar_fields)
                if scalar_fields else [{}] * len(keys)
            )
        out = []
        for j in range(len(keys)):
            row = []
            for s in specs:
                f = s["field"]
                if f == SCORE_FIELD:
                    row.append(flat_scores[j])
                elif f == ID_FIELD:
                    row.append(keys[j])
                else:
                    row.append(field_rows[j].get(f))
            out.append(row)
        return out

    def _order_items(self, items: list, specs: list[dict], metric) -> None:
        """In-place order of one query's hits by the sort spec; ties
        break on metric-oriented score (L2 ascending, IP/cosine
        descending) then key, so the order is deterministic and
        partition-merge-stable."""
        from vearch_tpu.engine.sort import row_sort_key
        from vearch_tpu.engine.types import MetricType

        l2 = metric is MetricType.L2
        items.sort(key=row_sort_key(
            specs,
            lambda it: it.sort_values,
            tie_key=lambda it: ((it.score if l2 else -it.score), it.key),
        ))

    # -- persistence (reference: engine.cc:1217 Dump / :1293 Load) ----------

    def snapshot_state(self) -> dict:
        """Phase 1 of a dump: capture a consistent point-in-time view
        under the write lock. Cheap — pointer copies and stable views of
        append-only copy-on-grow arrays. The caller may then persist it
        lock-free with write_snapshot()."""
        with self._write_lock:
            return {
                "table": self.table.snapshot(),
                "bits": self.bitmap.snapshot(self.table.doc_count),
                "vecs": {
                    name: store.host_view()
                    for name, store in self.vector_stores.items()
                },
                "status": int(self.status),
            }

    # rows per segment before the tail-merge compaction kicks in, and the
    # max number of undersized trailing segments tolerated before they
    # are merged (LSM-ish: flush cost stays O(new rows) for normal
    # flushes; every MAX_SMALL_SEGMENTS-th small flush pays one merge)
    SEGMENT_TARGET_ROWS = 100_000
    MAX_SMALL_SEGMENTS = 8

    def _read_manifest(self, dirpath: str) -> list[dict]:
        """Validated, contiguous-from-zero segment list (or empty)."""
        path = os.path.join(dirpath, "MANIFEST.json")
        if not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                segs = json.load(f)["segments"]
        except Exception:
            return []
        segs = sorted(segs, key=lambda s: s["start"])
        out, expect = [], 0
        for s in segs:
            if s["start"] != expect or not os.path.isdir(
                os.path.join(dirpath, "segments", s["name"])
            ):
                break
            out.append(s)
            expect = s["end"]
        return out

    def _write_segment(
        self, snap: dict, dirpath: str, start: int, end: int, in_place: bool
    ) -> dict:
        name = f"seg_{start:010d}_{end:010d}"
        final = os.path.join(dirpath, "segments", name)
        tmp = final + ".tmp"
        import shutil

        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        if os.path.isdir(final):
            # orphan from a crash between os.replace and the manifest
            # commit: rows are immutable, so a same-boundary segment has
            # identical content — but os.replace cannot rename onto a
            # non-empty dir, so drop it or every later dump wedges
            shutil.rmtree(final)
        os.makedirs(tmp)
        tsnap = snap["table"]
        np.savez(
            os.path.join(tmp, "table.npz"),
            **{n: arr[start:end] for n, arr in tsnap["fixed"].items()},
        )
        with open(os.path.join(tmp, "table.json"), "w") as f:
            json.dump({
                "keys": tsnap["keys"][start:end],
                "strings": {
                    k: v[start:end] for k, v in tsnap["strings"].items()
                },
            }, f)
        for fname, view in snap["vecs"].items():
            store = self.vector_stores[fname]
            if getattr(store, "durable_on_disk", False) and in_place:
                continue  # the store's own mmap is the durable payload
            arr = np.asarray(view[start:end])
            if arr.dtype.kind not in "fiu":
                # ml_dtypes (bfloat16) need pickle to round-trip npy;
                # widen to f32 so backups stay allow_pickle=False
                arr = arr.astype(np.float32)
            np.save(os.path.join(tmp, f"vectors_{fname}.npy"), arr)
        os.replace(tmp, final)
        return {"name": name, "start": start, "end": end}

    def write_snapshot(self, snap: dict, dirpath: str) -> None:
        """Phase 2: persist a snapshot_state() capture. Runs without any
        engine lock (a torn dump was the original bug; lock-free writes
        of the captured views are safe because stores never mutate rows
        in place).

        Segmented, append-only format (r2 VERDICT weak #5: the flat
        format rewrote every column per flush — O(N) per checkpoint at
        16M rows/chip). Rows are immutable once appended (updates append
        + soft-delete), so sealed segments never change: a flush writes
        ONE new segment covering rows since the last seal, rewrites only
        the small mutable artifacts (bitmap, index state, manifest), and
        commits via an atomic MANIFEST.json rename — a crash mid-flush
        leaves the previous manifest pointing at intact files (reference
        behavior: incremental RocksDB writes, storage_manager.h:21 +
        flush jobs, store_raft_job.go:97)."""
        os.makedirs(dirpath, exist_ok=True)
        os.makedirs(os.path.join(dirpath, "segments"), exist_ok=True)
        count = len(snap["table"]["keys"])
        in_place = bool(
            self.data_dir
            and os.path.commonpath(
                [os.path.abspath(dirpath), os.path.abspath(self.data_dir)]
            ) == os.path.abspath(self.data_dir)
        )

        segs = self._read_manifest(dirpath)
        while segs and segs[-1]["end"] > count:
            segs.pop()  # rewind (restore/truncation): reseal the tail
        sealed = segs[-1]["end"] if segs else 0
        # compaction: merge the undersized trailing run into this flush
        # once it gets long, so segment count stays ~count/target + 8
        small = 0
        while (
            small < len(segs)
            and (segs[-1 - small]["end"] - segs[-1 - small]["start"])
            < self.SEGMENT_TARGET_ROWS
        ):
            small += 1
        if small > self.MAX_SMALL_SEGMENTS:
            sealed = segs[len(segs) - small]["start"]
            del segs[len(segs) - small:]
        if sealed < count:
            segs.append(
                self._write_segment(snap, dirpath, sealed, count, in_place)
            )

        with open(os.path.join(dirpath, "schema.json"), "w") as f:
            json.dump(self.schema.to_dict(), f)
        np.save(os.path.join(dirpath, "bitmap.npy"), snap["bits"])
        for name, view in snap["vecs"].items():
            store = self.vector_stores[name]
            if getattr(store, "durable_on_disk", False) and in_place:
                # disk store dumping into its own data_dir: msync +
                # record the durable count instead of copying a
                # beyond-RAM file
                store.flush_disk(n=view.shape[0])
        for name, index in self.indexes.items():
            state = index.dump_state()
            if state:
                np.savez(os.path.join(dirpath, f"index_{name}.npz"), **state)
        with open(os.path.join(dirpath, "engine.json"), "w") as f:
            json.dump({"status": snap["status"]}, f)
        tmp = os.path.join(dirpath, "MANIFEST.json.tmp")
        with open(tmp, "w") as f:
            json.dump({"format": 2, "doc_count": count, "segments": segs}, f)
        os.replace(tmp, os.path.join(dirpath, "MANIFEST.json"))
        # GC segment dirs the (now-durable) manifest no longer references
        keep = {s["name"] for s in segs}
        segroot = os.path.join(dirpath, "segments")
        for nm in os.listdir(segroot):
            if nm not in keep:
                import shutil

                shutil.rmtree(os.path.join(segroot, nm), ignore_errors=True)

    def dump(self, dirpath: str | None = None) -> None:
        dirpath = dirpath or self.data_dir
        assert dirpath, "no data_dir configured"
        self.write_snapshot(self.snapshot_state(), dirpath)

    def load(self, dirpath: str | None = None) -> None:
        dirpath = dirpath or self.data_dir
        assert dirpath and os.path.exists(dirpath), f"no dump at {dirpath}"
        if os.path.exists(os.path.join(dirpath, "MANIFEST.json")):
            self._load_segmented(dirpath)
        else:  # legacy flat dump (pre-segment backups)
            self.table.load(os.path.join(dirpath, "table"))
            self.bitmap.load(os.path.join(dirpath, "bitmap.npy"))
            for name, store in self.vector_stores.items():
                store.load(os.path.join(dirpath, f"vectors_{name}.npy"))
        for name, index in self.indexes.items():
            p = os.path.join(dirpath, f"index_{name}.npz")
            if os.path.exists(p):
                index.load_state(dict(np.load(p, allow_pickle=False)))
        with open(os.path.join(dirpath, "engine.json")) as f:
            self.status = IndexStatus(json.load(f)["status"])
        if self._scalar_manager is not None:
            self._scalar_manager.rebuild_from_table(self.table)

    def _load_segmented(self, dirpath: str) -> None:
        segs = self._read_manifest(dirpath)
        self.bitmap.load(os.path.join(dirpath, "bitmap.npy"))
        keys: list[str] = []
        strings: dict[str, list] = {
            n: [] for n in self.table._strings
        }
        fixed_parts: dict[str, list[np.ndarray]] = {
            n: [] for n in self.table._fixed
        }
        for s in segs:
            sd = os.path.join(dirpath, "segments", s["name"])
            with open(os.path.join(sd, "table.json")) as f:
                meta = json.load(f)
            keys.extend(meta["keys"])
            for n in strings:
                part = meta["strings"].get(n)
                if part is None:
                    # segment predates this column (e.g. the hidden
                    # presence column): pad so lengths stay row-aligned
                    part = [None] * len(meta["keys"])
                strings[n].extend(part)
            data = np.load(os.path.join(sd, "table.npz"))
            for n in fixed_parts:
                fixed_parts[n].append(data[n])
        fixed = {
            n: (np.concatenate(parts) if parts
                else np.zeros(0, self.table._fixed[n].dtype))
            for n, parts in fixed_parts.items()
        }
        n_rows = len(keys)
        self.table.load_from_segments(
            keys, strings, fixed, self.bitmap.valid_mask(n_rows)
        )
        for name, store in self.vector_stores.items():
            paths = [
                p for s in segs
                if os.path.exists(p := os.path.join(
                    dirpath, "segments", s["name"], f"vectors_{name}.npy"))
            ]
            if paths:
                store.load_parts(paths)
            else:  # in-place disk store: roll back via its meta barrier
                store.load(os.path.join(dirpath, f"vectors_{name}.npy"))

    @classmethod
    def open(cls, dirpath: str) -> "Engine":
        with open(os.path.join(dirpath, "schema.json")) as f:
            schema = TableSchema.from_dict(json.load(f))
        eng = cls(schema, data_dir=dirpath)
        eng.load(dirpath)
        return eng

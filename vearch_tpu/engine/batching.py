"""Continuous-batching scheduler: concurrent searches pack into padded
shape buckets and ride shared device dispatches.

Successor to the fixed micro-batcher (engine/microbatch.py, now
retired). That design only co-batched requests with IDENTICAL compat
keys — exact k included — so realistic mixed-(k, nprobe, rows) traffic
fragmented into many small dispatches. Two changes close the gap:

1. **Fetch-k tiers.** The engine quantizes every request's candidate
   depth up to the next declared tier (ops/perf_model.FETCH_K_TIERS)
   before it reaches the index, and trims each caller back to its own k
   host-side. Solo and batched runs therefore scan at the SAME tier
   depth, so co-batching requests whose k differs within one tier is
   bit-identical to running them alone — "grouping never changes a
   result" holds by construction, and the compiled-program universe is
   bounded by the declared grid instead of by traffic entropy.
2. **Continuous admission.** Requests land in per-compat-key buckets;
   a bucket dispatches the moment it fills (max_rows) or its age bound
   expires, and the NEXT bucket keeps filling while the previous one is
   in flight — the dispatcher pops one bucket at a time and runs the
   device call outside the lock. Idle engines keep the zero-added-
   latency property: with no configured age bound the dispatcher drains
   whatever is queued the moment it is free.

Sorted and score-bounded requests still require exact-k matches to
co-batch: their result shaping (bounds window, scalar sort) is applied
at the group's k, so trimming a deeper candidate list afterwards would
diverge from the solo run. The compat key encodes that rule.

A killed sub-request is dropped at result-split time — its company
still gets answers, matching the kill switch's best-effort
phase-boundary semantics.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Any

import numpy as np

from vearch_tpu.obs import accounting as _acct
from vearch_tpu.obs import flight_recorder as _flightrec
from vearch_tpu.ops import perf_model
from vearch_tpu.tools import lockcheck

if TYPE_CHECKING:  # pragma: no cover
    from vearch_tpu.engine.engine import Engine, SearchRequest, SearchResult


class _Pending:
    __slots__ = ("req", "rows", "done", "results", "error", "t_enqueue",
                 "trace_id", "space")

    def __init__(self, req: "SearchRequest", rows: int):
        self.req = req
        self.rows = rows
        self.done = threading.Event()
        self.results: "list[SearchResult] | None" = None
        self.error: Exception | None = None
        # queue-wait observability: stamped at submit(), read by
        # _run_bucket to report how long this request sat behind the
        # in-flight device dispatch (trace key queue_ms + a
        # microbatch.queue phase span)
        self.t_enqueue = time.monotonic()
        # compile attribution crosses the thread hop with the request:
        # the dispatcher thread re-binds this around the device call so
        # a serving-path compile lands in /debug/compiles carrying the
        # trace of the request that forced it
        self.trace_id = _flightrec.current_trace()
        # cost attribution crosses the hop the same way: the dispatcher
        # re-binds the space around the device call (dispatch/H2D
        # observers fire there) and apportions the bucket's device time
        self.space = _acct.current_space()


def _note_queue_wait(p: "_Pending", t_dequeue: float) -> None:
    """Record the scheduler queue wait on a traced pending request."""
    from vearch_tpu.utils import mono_us

    if p.req.trace is None:
        return
    wait_ms = max(0.0, (t_dequeue - p.t_enqueue) * 1e3)
    p.req.trace["queue_ms"] = round(wait_ms, 3)
    # copy-on-write: the group trace dict (and its _phase_spans list) is
    # shared by every pending in the group — never mutate the shared list
    spans = list(p.req.trace.get("_phase_spans") or [])
    spans.append(["microbatch.queue", mono_us(p.t_enqueue),
                  int(wait_ms * 1e3)])
    p.req.trace["_phase_spans"] = spans


def _rows_of(req: "SearchRequest") -> int:
    q = next(iter(req.vectors.values()))
    q = np.asarray(q)
    return 1 if q.ndim == 1 else int(q.shape[0])


def _request_fetch_k(req: "SearchRequest") -> int:
    # must mirror Engine._search_direct's candidate-depth formula: the
    # tier this computes is the tier the engine will scan at
    return req.k if len(req.vectors) == 1 else max(req.k * 4, 50)


def _compat_key(req: "SearchRequest", tiered: bool = True) -> str:
    """Bucket identity: requests sharing a key may ride one dispatch.

    With `tiered` (the engine quantizes fetch-k to the declared tiers),
    plain requests co-batch across differing k within one fetch-k tier
    — each caller's slice of the shared candidate set is exactly what a
    solo run at the same tier returns. Sorted / score-bounded requests
    keep exact k in the key: their shaping applies at the group's k, so
    a deeper group would change which items survive the window/sort.
    """
    mix_k = tiered and not req.sort and not req.score_bounds
    return json.dumps({
        "fields": sorted(req.vectors),
        "k": perf_model.bucket_fetch_k(_request_fetch_k(req))
        if mix_k else req.k,
        # index_params covers every shape-bearing serving knob — notably
        # the three-stage refinement depths r0/r1 (static args of the
        # binary_refine programs): requests tuned to different depths
        # land in different buckets instead of silently sharing one
        "params": req.index_params or {},
        "weights": req.field_weights or {},
        "include": sorted(req.include_fields)
        if req.include_fields is not None else None,
        # bounds are part of the key: the group request is built from
        # the head, so mixing bounded and unbounded searches would
        # silently drop (or wrongly apply) the score window
        "bounds": {f: list(b) for f, b in sorted(req.score_bounds.items())}
        if req.score_bounds else None,
        # sort reorders each query's items; co-batching mixed sorts
        # would order one caller's hits under another's spec
        "sort": req.sort or None,
    }, sort_keys=True, default=str)


class _Bucket:
    """One shape bucket being filled: compatible pendings accumulate
    until the bucket seals (capacity) or its age bound expires."""

    __slots__ = ("key", "pendings", "rows", "t_open")

    def __init__(self, key: str):
        self.key = key
        self.pendings: list[_Pending] = []
        self.rows = 0
        self.t_open = time.monotonic()


class BatchScheduler:
    """Continuous-batching scheduler for one engine.

    Callers enqueue and block; a per-engine dispatcher thread pops ONE
    dispatch-ready bucket at a time and runs the device call outside
    the scheduler lock, so open buckets keep filling while a dispatch
    is in flight. `max_delay_ms` == 0 (default) dispatches whatever is
    ready the moment the dispatcher is free — zero added latency when
    idle; > 0 holds partial buckets up to that age waiting for company
    (age-bound expiry counts in `age_timeout_fires`).
    """

    def __init__(self, engine: "Engine", max_rows: int = 1024,
                 max_delay_ms: float = 0.0):
        self.engine = engine
        self.max_rows = max_rows
        self.max_delay_ms = float(max_delay_ms)
        self._lock = lockcheck.make_lock("engine.batch_scheduler")
        self._open: dict[str, _Bucket] = {}
        self._sealed: deque[_Bucket] = deque()
        self._wake = threading.Event()
        self._stopped = False
        # observability (surfaces in /ps/stats scheduler block)
        self.dispatches = 0  # every bucket run, solo or grouped
        self.batches = 0
        self.batched_requests = 0  # requests that shared a dispatch
        self.age_timeout_fires = 0
        self.full_dispatches = 0
        self.dispatch_rows = 0      # real rows across all dispatches
        self.dispatch_capacity = 0  # padded tier rows across dispatches
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="vearch-batch-scheduler"
        )
        self._thread.start()

    # -- caller side ---------------------------------------------------------

    def submit(self, req: "SearchRequest") -> "list[SearchResult]":
        p = _Pending(req, _rows_of(req))
        key = _compat_key(req, tiered=getattr(
            self.engine, "shape_buckets", True))
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine closed")
            b = self._open.get(key)
            if b is not None and b.rows + p.rows > self.max_rows:
                # the arrival would overflow: seal the current bucket
                # and open a fresh one for this request
                self._sealed.append(self._open.pop(key))
            b = self._open.get(key)
            if b is None:
                b = self._open[key] = _Bucket(key)
            b.pendings.append(p)
            b.rows += p.rows
            if b.rows >= self.max_rows:
                self._sealed.append(self._open.pop(key))
        self._wake.set()
        p.done.wait()
        if p.error is not None:
            raise p.error
        assert p.results is not None
        return p.results

    def stop(self) -> None:
        """Drain-on-close: every waiting caller is errored immediately —
        nobody hangs on a dispatcher that will never run again."""
        with self._lock:
            self._stopped = True
            pending: list[_Pending] = []
            for b in self._sealed:
                pending.extend(b.pendings)
            for b in self._open.values():
                pending.extend(b.pendings)
            self._sealed.clear()
            self._open.clear()
        for p in pending:
            p.error = RuntimeError("engine closed")
            p.done.set()
        self._wake.set()

    def stats(self) -> dict[str, Any]:
        """Scheduler snapshot for /ps/stats: occupancy + dispatch mix."""
        with self._lock:
            open_buckets = len(self._open) + len(self._sealed)
            open_rows = sum(b.rows for b in self._open.values()) + \
                sum(b.rows for b in self._sealed)
        cap = max(self.dispatch_capacity, 1)
        return {
            "dispatches": self.dispatches,
            "batches": self.batches,
            "batched_requests": self.batched_requests,
            "open_buckets": open_buckets,
            "open_rows": open_rows,
            "age_timeout_fires": self.age_timeout_fires,
            "full_dispatches": self.full_dispatches,
            "dispatch_rows": self.dispatch_rows,
            "dispatch_capacity": self.dispatch_capacity,
            "occupancy_pct": round(100.0 * self.dispatch_rows / cap, 2),
        }

    # -- dispatcher ----------------------------------------------------------

    def _pop_ready(self) -> "_Bucket | None":
        """Under lock: next bucket to dispatch. Sealed (full) buckets
        first, then — the dispatcher being free — the oldest open bucket
        whose age bound expired, or any open bucket when no age bound is
        configured."""
        if self._sealed:
            self.full_dispatches += 1
            return self._sealed.popleft()
        if not self._open:
            return None
        oldest_key = min(self._open, key=lambda k: self._open[k].t_open)
        if self.max_delay_ms <= 0.0:
            return self._open.pop(oldest_key)
        b = self._open[oldest_key]
        if (time.monotonic() - b.t_open) * 1e3 >= self.max_delay_ms:
            self.age_timeout_fires += 1
            return self._open.pop(oldest_key)
        return None

    def _wait_timeout(self) -> float | None:
        """Under lock: how long the dispatcher may sleep — until the
        oldest open bucket's age bound, or forever when nothing is
        held back."""
        if self._sealed or self.max_delay_ms <= 0.0 or not self._open:
            return None
        t_oldest = min(b.t_open for b in self._open.values())
        remain = self.max_delay_ms / 1e3 - (time.monotonic() - t_oldest)
        return max(remain, 0.0)

    def _loop(self) -> None:
        while True:
            with self._lock:
                timeout = self._wait_timeout()
            if timeout is None:
                self._wake.wait()
            else:
                self._wake.wait(timeout)
            while True:
                with self._lock:
                    if self._stopped and not self._sealed and not self._open:
                        return
                    self._wake.clear()
                    bucket = self._pop_ready()
                if bucket is None:
                    break
                # device call OUTSIDE the lock: submits keep packing the
                # next buckets while this one is in flight
                self._run_bucket(bucket)

    def _run_bucket(self, bucket: _Bucket) -> None:
        group = bucket.pendings
        t_dequeue = time.monotonic()
        rows = sum(p.rows for p in group)
        self.dispatches += 1
        self.dispatch_rows += rows
        self.dispatch_capacity += min(
            perf_model.bucket_rows(rows), max(self.max_rows, rows)
        )
        for p in group:
            _acct.ACCOUNTANT.charge(
                "queue_wait_us",
                int(max(0.0, t_dequeue - p.t_enqueue) * 1e6),
                space=p.space)
        if len(group) == 1:
            p = group[0]
            tok = _flightrec.set_active_trace(p.trace_id)
            stok = _acct.set_space(p.space)
            t_run0 = time.monotonic()
            try:
                _note_queue_wait(p, t_dequeue)
                p.results = self.engine._search_direct(p.req)
            except Exception as e:
                p.error = e
            finally:
                _acct.ACCOUNTANT.charge(
                    "device_us", int((time.monotonic() - t_run0) * 1e6),
                    space=p.space)
                _acct.reset_space(stok)
                _flightrec.reset_active_trace(tok)
                p.done.set()
            return

        from vearch_tpu.engine.engine import RequestKilled, SearchRequest
        from vearch_tpu.utils import mono_us

        self.batches += 1
        self.batched_requests += len(group)
        try:
            t_pack0 = time.monotonic()
            head = group[0].req
            stacked = {
                name: np.concatenate(
                    [np.atleast_2d(np.asarray(p.req.vectors[name]))
                     for p in group], axis=0,
                )
                for name in head.vectors
            }
            k = max(p.req.k for p in group)
            trace: dict[str, Any] | None = (
                {} if any(p.req.trace is not None for p in group) else None
            )
            big = SearchRequest(
                vectors=stacked, k=k, filters=None,
                include_fields=head.include_fields,
                brute_force=False,
                field_weights=head.field_weights,
                index_params=head.index_params,
                score_bounds=head.score_bounds,
                # sort rides the group request (same spec across the
                # bucket — it is part of the compat key): each query
                # row sorts independently, so the shared dispatch
                # shapes exactly what every solo run would
                sort=head.sort,
                trace=trace,
            )
            t_pack1 = time.monotonic()
            # a combined dispatch has many originators; attribute any
            # compile to the head — one real trace beats none. Discrete
            # dispatch/H2D events bill to the head's space (they cannot
            # be split); the measured device wall slice below IS split,
            # by row share, so shared-bucket device time stays
            # conservation-exact per tenant.
            tok = _flightrec.set_active_trace(group[0].trace_id)
            stok = _acct.set_space(group[0].space)
            t_run0 = time.monotonic()
            try:
                results = self.engine._search_direct(big)
            finally:
                _acct.ACCOUNTANT.apportion_device_us(
                    [(p.space, p.rows) for p in group],
                    int((time.monotonic() - t_run0) * 1e6))
                _acct.reset_space(stok)
                _flightrec.reset_active_trace(tok)
            if trace is not None:
                # pack span: host-side group assembly ahead of the
                # device dispatch (shows up next to microbatch.queue in
                # the replayed trace tree)
                spans = list(trace.get("_phase_spans") or [])
                spans.append(["batch.pack", mono_us(t_pack0),
                              int((t_pack1 - t_pack0) * 1e6)])
                trace["_phase_spans"] = spans
        except Exception:
            # One bad co-batched request (wrong dim, NaNs, ...) must not
            # fail its companymates: retry each pending alone so only the
            # genuinely bad ones error. Killed requests get their abort
            # instead of a full-cost re-run (same as the success path).
            for p in group:
                tok = _flightrec.set_active_trace(p.trace_id)
                stok = _acct.set_space(p.space)
                t_run0 = time.monotonic()
                try:
                    if p.req.ctx is not None and p.req.ctx.killed:
                        p.error = RequestKilled(
                            p.req.ctx.reason or "request killed")
                    else:
                        p.results = self.engine._search_direct(p.req)
                except Exception as e:
                    p.error = e
                finally:
                    _acct.ACCOUNTANT.charge(
                        "device_us",
                        int((time.monotonic() - t_run0) * 1e6),
                        space=p.space)
                    _acct.reset_space(stok)
                    _flightrec.reset_active_trace(tok)
                    p.done.set()
            return
        off = 0
        for p in group:
            sub = results[off : off + p.rows]
            off += p.rows
            if p.req.ctx is not None and p.req.ctx.killed:
                # best-effort kill: the shared dispatch already ran, but
                # the killed caller still gets its abort
                p.error = RequestKilled(p.req.ctx.reason or "request killed")
                p.done.set()
                continue
            if p.req.k < k:
                # the group scanned at the shared fetch-k tier and kept
                # the group max k; each caller's prefix is exactly its
                # solo result at the same tier
                for r in sub:
                    r.items = r.items[: p.req.k]
            if p.req.trace is not None and trace is not None:
                p.req.trace.update(trace)
                p.req.trace["micro_batch_rows"] = rows
                _note_queue_wait(p, t_dequeue)
            p.results = sub
            p.done.set()


# retired alias: engine code now names the scheduler directly, but
# external callers of the old entry point keep working
MicroBatcher = BatchScheduler

"""Raw vector column store with a device-resident mirror.

TPU-native re-design of the reference's RawVector hierarchy (reference:
internal/engine/vector/raw_vector.h:62; MemoryRawVector segments,
memory_raw_vector.cc). The reference grows mmap-able segments; TPU wants
one large static-shaped device array, so:

- host side: an append-only numpy buffer with capacity doubling (the
  durable source of truth — dump/load streams this, never device state);
- device side: a padded [capacity, d] jax array refreshed lazily. Appends
  land in a host "dirty tail"; the next search flushes the tail with a
  single `jax.lax.dynamic_update_slice` donation-style rebuild, so steady
  -state ingest costs one small H2D copy per refresh interval, not one
  per doc (mirrors the reference's realtime ingest pump,
  vector_manager.h:76 AddRTVecsToIndex);
- capacity doubling reallocates the device buffer (rare, amortised O(1));
- squared norms are cached device-side per refresh so the L2 hot path
  reads the base matrix exactly once per query batch.

`store_dtype` bfloat16 halves HBM traffic on the brute-force scan — the
TPU analogue of the reference's store-type choice (MemoryOnly vs RocksDB,
raw_vector.h:29 StoreParams).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.ops import perf_model
from vearch_tpu.ops.distance import host_sqnorms


class RawVectorStore:
    def __init__(
        self,
        dimension: int,
        store_dtype: str = "float32",
        init_capacity: int = 4096,
    ):
        self.dimension = dimension
        self.store_dtype = jnp.dtype(store_dtype)
        self._host = np.zeros((init_capacity, dimension), dtype=np.float32)
        self._n = 0
        self._device: jax.Array | None = None  # [capacity, d] store_dtype
        self._device_sqnorm: jax.Array | None = None  # [capacity] f32
        self._device_rows = 0  # rows already mirrored to device

    @property
    def count(self) -> int:
        return self._n

    @property
    def capacity(self) -> int:
        return self._host.shape[0]

    def add(self, vectors: np.ndarray) -> int:
        """Append [b, d] rows; returns the first assigned row id (== docid
        base, the engine keeps row id == docid)."""
        b = vectors.shape[0]
        assert vectors.shape[1] == self.dimension
        if self._n + b > self._host.shape[0]:
            new_cap = max(self._host.shape[0] * 2, self._n + b, 1024)
            grown = np.zeros((new_cap, self.dimension), dtype=np.float32)
            grown[: self._n] = self._host[: self._n]
            self._host = grown
        start = self._n
        self._host[start : start + b] = vectors
        self._n += b
        return start

    def host_view(self) -> np.ndarray:
        """[n, d] float32 host rows (training / rerank / dump path)."""
        return self._host[: self._n]

    def get(self, docid: int) -> np.ndarray:
        return self._host[docid]

    def device_buffer(self) -> tuple[jax.Array, jax.Array, int]:
        """Returns (base [capacity, d], base_sqnorm [capacity], n_rows).

        Flushes any dirty tail to the device. Rows >= n_rows are padding
        and must be masked by the caller. The buffer is rebuilt only when
        capacity changed; otherwise the tail lands via dynamic_update_slice
        on the existing device array.
        """
        # snapshot n once: a concurrent upsert may advance self._n while we
        # flush; rows past the snapshot flush on the next call
        n = self._n
        cap = self._host.shape[0]
        if self._device is None or self._device.shape[0] != cap:
            self._device = jnp.asarray(self._host, dtype=self.store_dtype)
            self._device_sqnorm = jnp.asarray(
                host_sqnorms(np.asarray(self._device))
            )
            # .nbytes is metadata — no host sync
            perf_model.note_h2d_bytes(
                int(self._device.nbytes) + int(self._device_sqnorm.nbytes)
            )
            self._device_rows = n
        elif self._device_rows < n:
            tail = jnp.asarray(
                self._host[self._device_rows : n], dtype=self.store_dtype
            )
            perf_model.note_h2d_bytes(int(tail.nbytes))
            self._device = jax.lax.dynamic_update_slice(
                self._device, tail, (self._device_rows, 0)
            )
            self._device_sqnorm = jax.lax.dynamic_update_slice(
                self._device_sqnorm,
                jnp.asarray(host_sqnorms(np.asarray(tail))),
                (self._device_rows,),
            )
            self._device_rows = n
        return self._device, self._device_sqnorm, n

    _sh_cache = None

    def device_buffer_sharded(self, mesh) -> tuple[jax.Array, jax.Array, int]:
        """Row-sharded raw buffer over the mesh "data" axis (rerank path
        of a mesh-spanning partition). Growth within the cached capacity
        tail-appends only the new rows per shard; the derived sqnorm
        column is maintained on device by the cache (sqnorm_of=0) so it
        stays bit-identical to a full rebuild."""
        from vearch_tpu.parallel.mesh import ShardedRowCache

        if self._sh_cache is None:
            self._sh_cache = ShardedRowCache(align=128, sqnorm_of=0)

        def build(cap):
            host = np.zeros((cap, self.dimension), dtype=np.float32)
            host[: self._n] = self._host[: self._n]
            return (host.astype(self.store_dtype),)

        def append(lo, hi):
            win = np.zeros((hi - lo, self.dimension), dtype=np.float32)
            m = min(hi, self._host.shape[0]) - lo
            if m > 0:
                win[:m] = self._host[lo : lo + m]
            return (win.astype(self.store_dtype),)

        (base,), _ = self._sh_cache.get(mesh, self._n, build, append)
        return base, self._sh_cache.sqnorm, self._n

    # -- persistence ---------------------------------------------------------

    def dump(self, path: str) -> None:
        np.save(path, self.host_view())

    def load(self, path: str) -> None:
        if os.path.exists(path):
            data = np.load(path)
            self._host = data.copy()
            self._n = data.shape[0]
            self._device = None
            self._device_rows = 0
            if self._sh_cache is not None:
                self._sh_cache.invalidate()

    def load_parts(self, paths: list[str]) -> None:
        """Restore from per-segment row slices in order (segmented dump
        format; Engine.load concatenates MANIFEST segments)."""
        if not paths:
            return
        parts = [np.load(p, mmap_mode="r") for p in paths]
        n = sum(p.shape[0] for p in parts)
        host = np.zeros((max(n, 1024), self.dimension), dtype=np.float32)
        off = 0
        chunk = 1 << 18  # stream from the mmap; never double peak RAM
        for p in parts:
            for lo in range(0, p.shape[0], chunk):
                hi = min(lo + chunk, p.shape[0])
                host[off + lo : off + hi] = p[lo:hi]
            off += p.shape[0]
        self._host = host
        self._n = n
        self._device = None
        self._device_rows = 0
        if self._sh_cache is not None:
            self._sh_cache.invalidate()

"""Server-side query micro-batching: concurrent small searches ride one
device dispatch.

TPU-native addition (no direct reference analogue — the reference's CPU
engine runs each request on its own thread pool slot, which is the right
shape for SIMD cores; reference: RequestConcurrentController,
search/engine.h:197). On TPU the cost model inverts: a [1, N] and a
[64, N] scan cost nearly the same device time because both are one
MXU-bound program dispatch, so the winning schedule under concurrency is
to COMBINE waiting queries into one batch.

Design — dynamic batching, zero added latency when idle:
- callers enqueue and block; a per-engine dispatcher thread drains
  WHATEVER is queued the moment the previous device call finishes;
- under low load a request finds the dispatcher idle and runs alone
  (batch of 1 — no artificial wait window, unlike time-windowed
  batching);
- under load, requests naturally pile up while the device is busy and
  the next drain combines them: throughput scales with batch size,
  per-request latency stays ~one device-call.

Only compatible requests combine (same field set / k / params /
weights / include_fields, no filters, not brute-force): grouping never
changes a result, only its schedule. A killed sub-request is dropped at
result-split time — its company still gets answers, matching the kill
switch's best-effort phase-boundary semantics.
"""

from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING, Any

import numpy as np

from vearch_tpu.obs import flight_recorder as _flightrec

if TYPE_CHECKING:  # pragma: no cover
    from vearch_tpu.engine.engine import Engine, SearchRequest, SearchResult


class _Pending:
    __slots__ = ("req", "rows", "done", "results", "error", "t_enqueue",
                 "trace_id")

    def __init__(self, req: "SearchRequest", rows: int):
        self.req = req
        self.rows = rows
        self.done = threading.Event()
        self.results: "list[SearchResult] | None" = None
        self.error: Exception | None = None
        # queue-wait observability: stamped at submit(), read by
        # _run_group to report how long this request sat behind the
        # in-flight device dispatch (trace key queue_ms + a
        # microbatch.queue phase span)
        self.t_enqueue = time.monotonic()
        # compile attribution crosses the thread hop with the request:
        # the dispatcher thread re-binds this around the device call so
        # a serving-path compile lands in /debug/compiles carrying the
        # trace of the request that forced it
        self.trace_id = _flightrec.current_trace()


def _note_queue_wait(p: "_Pending", t_dequeue: float) -> None:
    """Record the microbatch queue wait on a traced pending request."""
    from vearch_tpu.utils import mono_us

    if p.req.trace is None:
        return
    wait_ms = max(0.0, (t_dequeue - p.t_enqueue) * 1e3)
    p.req.trace["queue_ms"] = round(wait_ms, 3)
    # copy-on-write: the group trace dict (and its _phase_spans list) is
    # shared by every pending in the group — never mutate the shared list
    spans = list(p.req.trace.get("_phase_spans") or [])
    spans.append(["microbatch.queue", mono_us(p.t_enqueue),
                  int(wait_ms * 1e3)])
    p.req.trace["_phase_spans"] = spans


def _compat_key(req: "SearchRequest") -> str:
    return json.dumps({
        "fields": sorted(req.vectors),
        # k is part of the key because the engine's candidate depth
        # (fetch_k) derives from it — co-batching mixed k at max(k)
        # would give the small-k caller a different candidate set than
        # a solo run, breaking "grouping never changes a result"
        "k": req.k,
        "params": req.index_params or {},
        "weights": req.field_weights or {},
        "include": sorted(req.include_fields)
        if req.include_fields is not None else None,
        # bounds are part of the key: the group request is built from
        # the head, so mixing bounded and unbounded searches would
        # silently drop (or wrongly apply) the score window
        "bounds": {f: list(b) for f, b in sorted(req.score_bounds.items())}
        if req.score_bounds else None,
        # sort reorders each query's items; co-batching mixed sorts
        # would order one caller's hits under another's spec
        "sort": req.sort or None,
    }, sort_keys=True, default=str)


def _rows_of(req: "SearchRequest") -> int:
    q = next(iter(req.vectors.values()))
    q = np.asarray(q)
    return 1 if q.ndim == 1 else int(q.shape[0])


class MicroBatcher:
    def __init__(self, engine: "Engine", max_rows: int = 1024):
        self.engine = engine
        self.max_rows = max_rows
        self._lock = threading.Lock()
        self._queue: list[_Pending] = []
        self._wake = threading.Event()
        self._stopped = False
        # observability (surfaces in /ps/stats)
        self.batches = 0
        self.batched_requests = 0  # requests that shared a dispatch
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="vearch-microbatch"
        )
        self._thread.start()

    # -- caller side ---------------------------------------------------------

    def submit(self, req: "SearchRequest") -> "list[SearchResult]":
        p = _Pending(req, _rows_of(req))
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine closed")
            self._queue.append(p)
        self._wake.set()
        p.done.wait()
        if p.error is not None:
            raise p.error
        assert p.results is not None
        return p.results

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            pending, self._queue = self._queue, []
        for p in pending:
            p.error = RuntimeError("engine closed")
            p.done.set()
        self._wake.set()

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            self._wake.wait()
            with self._lock:
                if self._stopped and not self._queue:
                    return
                batch, self._queue = self._queue, []
                self._wake.clear()
            if not batch:
                continue
            try:
                groups = self._group(batch)
            except Exception as e:
                # grouping must never kill the dispatcher: fail THIS
                # batch loudly and stay alive for future submits (a dead
                # dispatcher would hang every later caller forever)
                for p in batch:
                    p.error = e
                    p.done.set()
                continue
            for group in groups:
                self._run_group(group)

    def _group(self, batch: list[_Pending]) -> list[list[_Pending]]:
        groups: dict[str, list[_Pending]] = {}
        order: list[list[_Pending]] = []
        rows: dict[str, int] = {}
        for p in batch:
            key = _compat_key(p.req)
            if key in groups and rows[key] + p.rows <= self.max_rows:
                groups[key].append(p)
                rows[key] += p.rows
            else:
                g = [p]
                groups[key] = g  # later arrivals join the newest group
                rows[key] = p.rows
                order.append(g)
        return order

    def _run_group(self, group: list[_Pending]) -> None:
        t_dequeue = time.monotonic()
        if len(group) == 1:
            p = group[0]
            tok = _flightrec.set_active_trace(p.trace_id)
            try:
                _note_queue_wait(p, t_dequeue)
                p.results = self.engine._search_direct(p.req)
            except Exception as e:
                p.error = e
            finally:
                _flightrec.reset_active_trace(tok)
                p.done.set()
            return

        from vearch_tpu.engine.engine import RequestKilled, SearchRequest

        self.batches += 1
        self.batched_requests += len(group)
        try:
            head = group[0].req
            stacked = {
                name: np.concatenate(
                    [np.atleast_2d(np.asarray(p.req.vectors[name]))
                     for p in group], axis=0,
                )
                for name in head.vectors
            }
            k = max(p.req.k for p in group)
            trace: dict[str, Any] | None = (
                {} if any(p.req.trace is not None for p in group) else None
            )
            big = SearchRequest(
                vectors=stacked, k=k, filters=None,
                include_fields=head.include_fields,
                brute_force=False,
                field_weights=head.field_weights,
                index_params=head.index_params,
                score_bounds=head.score_bounds,
                trace=trace,
            )
            # a combined dispatch has many originators; attribute any
            # compile to the head — one real trace beats none
            tok = _flightrec.set_active_trace(group[0].trace_id)
            try:
                results = self.engine._search_direct(big)
            finally:
                _flightrec.reset_active_trace(tok)
        except Exception:
            # One bad co-batched request (wrong dim, NaNs, ...) must not
            # fail its companymates: retry each pending alone so only the
            # genuinely bad ones error. Killed requests get their abort
            # instead of a full-cost re-run (same as the success path).
            for p in group:
                tok = _flightrec.set_active_trace(p.trace_id)
                try:
                    if p.req.ctx is not None and p.req.ctx.killed:
                        p.error = RequestKilled(
                            p.req.ctx.reason or "request killed")
                    else:
                        p.results = self.engine._search_direct(p.req)
                except Exception as e:
                    p.error = e
                finally:
                    _flightrec.reset_active_trace(tok)
                    p.done.set()
            return
        off = 0
        for p in group:
            sub = results[off : off + p.rows]
            off += p.rows
            if p.req.ctx is not None and p.req.ctx.killed:
                # best-effort kill: the shared dispatch already ran, but
                # the killed caller still gets its abort
                p.error = RequestKilled(p.req.ctx.reason or "request killed")
                p.done.set()
                continue
            if p.req.k < k:
                for r in sub:
                    r.items = r.items[: p.req.k]
            if p.req.trace is not None and trace is not None:
                p.req.trace.update(trace)
                p.req.trace["micro_batch_rows"] = sum(
                    g.rows for g in group
                )
                _note_queue_wait(p, t_dequeue)
            p.results = sub
            p.done.set()

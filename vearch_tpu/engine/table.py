"""Document profile store: key -> docid mapping + columnar scalar fields.

TPU-native re-design of the reference's Table (reference:
internal/engine/table/table.h:34 — key→docid map plus fixed/string field
column families in RocksDB). Here scalar columns are typed numpy arrays
(fixed-width types) or python lists (strings), append-only with docid as
the row index; updates of an existing key soft-delete the old row and
append a new one, which keeps every downstream structure — device vector
buffers, scalar indexes — append-only too.

Persistence: one .npz for fixed columns + a JSON sidecar for strings/keys
(Engine.dump drives it; reference: table/table_io.cc).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator

import numpy as np

from vearch_tpu.engine.types import DataType, TableSchema

_FIXED_DTYPES: dict[DataType, np.dtype] = {
    DataType.INT: np.dtype(np.int32),
    DataType.LONG: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float32),
    DataType.DOUBLE: np.dtype(np.float64),
    DataType.DATE: np.dtype(np.int64),  # epoch millis
    DataType.BOOL: np.dtype(np.bool_),
}


class _Column:
    """Append-only typed column with amortised growth."""

    def __init__(self, dtype: np.dtype):
        self.dtype = dtype
        self._data = np.zeros(1024, dtype=dtype)
        self._n = 0

    def append(self, value: Any) -> None:
        if self._n >= self._data.shape[0]:
            grown = np.zeros(max(self._data.shape[0] * 2, 1024), dtype=self.dtype)
            grown[: self._n] = self._data[: self._n]
            self._data = grown
        self._data[self._n] = value if value is not None else 0
        self._n += 1

    def view(self) -> np.ndarray:
        return self._data[: self._n]

    def __getitem__(self, docid: int) -> Any:
        return self._data[docid]


class Table:
    # hidden per-row presence column: which scalar fields the document
    # actually provided (fixed columns materialize 0-defaults, so without
    # this a partial update could not tell "price is 0" from "price was
    # never set" and would carry phantom defaults forward). Lives inside
    # _strings so every snapshot/dump/segment path persists it for free;
    # rows from pre-presence dumps read back as None == "all set".
    PRESENCE_COL = "__set__"

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self._key_to_docid: dict[str, int] = {}
        self._keys: list[str] = []  # docid -> key
        self._fixed: dict[str, _Column] = {}
        self._strings: dict[str, list[Any]] = {}
        for f in schema.scalar_fields():
            if f.data_type in _FIXED_DTYPES:
                self._fixed[f.name] = _Column(_FIXED_DTYPES[f.data_type])
            else:
                self._strings[f.name] = []
        self._strings[self.PRESENCE_COL] = []
        self._presence_intern: dict[str, str] = {}

    @property
    def doc_count(self) -> int:
        """High-water docid count (includes soft-deleted rows)."""
        return len(self._keys)

    def docid_of(self, key: str) -> int | None:
        return self._key_to_docid.get(key)

    def key_of(self, docid: int) -> str:
        return self._keys[docid]

    def add(self, key: str, fields: dict[str, Any]) -> tuple[int, int | None]:
        """Append a row; returns (new_docid, replaced_docid_or_None).

        An existing key is an update: the caller soft-deletes the old docid
        (reference: engine.cc:691 AddOrUpdate key-exists branch).
        """
        old = self._key_to_docid.get(key)
        docid = len(self._keys)
        self._keys.append(key)
        self._key_to_docid[key] = docid
        for name, col in self._fixed.items():
            col.append(fields.get(name))
        for name, lst in self._strings.items():
            if name == self.PRESENCE_COL:
                provided = ",".join(sorted(
                    k for k, v in fields.items()
                    if v is not None
                    and (k in self._fixed or (
                        k in self._strings and k != self.PRESENCE_COL))
                ))
                lst.append(self._presence_intern.setdefault(
                    provided, provided))
            else:
                lst.append(fields.get(name))
        return docid, old

    def add_field(self, f) -> None:
        """Append-only schema evolution: a new scalar column, backfilled
        with defaults for existing rows. Presence tracking already marks
        those rows as not having set it, so the defaults are inert for
        filters and partial updates (reference: updateSpaceFields new-
        field additions, space_service.go:826)."""
        n = len(self._keys)
        if f.data_type in _FIXED_DTYPES:
            col = _Column(_FIXED_DTYPES[f.data_type])
            for _ in range(n):
                col.append(None)
            self._fixed[f.name] = col
        else:
            self._strings[f.name] = [None] * n

    def validate(self, fields: dict[str, Any]) -> None:
        """Raise ValueError for values a typed column cannot take. Must
        run BEFORE any mutation of a batch: _Column.append raising
        mid-batch would leave table/vector-store row counts misaligned
        forever (docid == row id is a core invariant)."""
        for name, col in self._fixed.items():
            v = fields.get(name)
            if v is None:
                continue
            try:
                np.asarray(v).astype(col.dtype)
            except (TypeError, ValueError):
                raise ValueError(
                    f"field {name!r} value {v!r} is not coercible to "
                    f"{col.dtype}"
                ) from None

    def set_fields_of(self, docid: int) -> frozenset:
        """Scalar fields the row's document actually provided. Rows
        predating presence tracking (old dumps) report all fields.
        Memoized per token — tokens are heavily shared across rows, so
        per-row calls (e.g. index rebuild at load) stay O(1)."""
        col = self._strings.get(self.PRESENCE_COL)
        tok = col[docid] if col is not None and docid < len(col) else None
        memo = getattr(self, "_presence_sets", None)
        if memo is None:
            memo = self._presence_sets = {}
        got = memo.get(tok)
        if got is None:
            if tok is None:
                got = frozenset(self._fixed) | frozenset(
                    k for k in self._strings if k != self.PRESENCE_COL
                )
            else:
                got = frozenset(tok.split(",")) if tok else frozenset()
            memo[tok] = got
        return got

    def delete(self, key: str) -> int | None:
        """Remove the key mapping; returns the docid to soft-delete."""
        return self._key_to_docid.pop(key, None)

    def get_fields(
        self, docid: int, names: list[str] | None = None
    ) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for name, col in self._fixed.items():
            if names is None or name in names:
                out[name] = col[docid].item()
        for name, lst in self._strings.items():
            if name == self.PRESENCE_COL:
                continue
            if names is None or name in names:
                out[name] = lst[docid]
        return out

    def gather_rows(
        self, docids: np.ndarray, names: list[str] | None = None
    ) -> list[dict[str, Any]]:
        """Batch get_fields: one numpy gather per fixed column instead of
        a Python loop per (doc, field) — the search result shaping hot
        path (r1 VERDICT weak-3)."""
        cols: dict[str, list] = {}
        for name, col in self._fixed.items():
            if names is None or name in names:
                cols[name] = col._data[docids].tolist()
        for name, lst in self._strings.items():
            if name == self.PRESENCE_COL:
                continue
            if names is None or name in names:
                cols[name] = [lst[i] for i in docids.tolist()]
        field_names = list(cols)
        if not field_names:
            return [{} for _ in range(len(docids))]
        return [
            dict(zip(field_names, vals))
            for vals in zip(*(cols[f] for f in field_names))
        ]

    def keys_for(self, docids: np.ndarray) -> list[str]:
        keys = self._keys
        return [keys[i] for i in docids.tolist()]

    def column(self, name: str) -> np.ndarray:
        """Columnar view of a fixed-width field (for scalar index builds /
        filter evaluation). Raises KeyError for string fields."""
        return self._fixed[name].view()

    def string_column(self, name: str) -> list[Any]:
        return self._strings[name]

    def iter_alive(self) -> Iterator[tuple[str, int]]:
        yield from self._key_to_docid.items()

    # -- persistence ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Consistent point-in-time capture, O(n) pointer copies only.

        Caller must hold the engine write lock for the call; the returned
        snapshot may then be written to disk lock-free: columns and keys
        are append-only (growth reallocates, so captured views never see
        later writes), and the mutable dict is copied here.
        """
        return {
            "keys": list(self._keys),
            "key_to_docid": dict(self._key_to_docid),
            "strings": {k: list(v) for k, v in self._strings.items()},
            "fixed": {name: col.view() for name, col in self._fixed.items()},
        }

    def dump_snapshot(self, snap: dict, dirpath: str) -> None:
        os.makedirs(dirpath, exist_ok=True)
        np.savez(os.path.join(dirpath, "columns.npz"), **snap["fixed"])
        meta = {
            "keys": snap["keys"],
            "key_to_docid": snap["key_to_docid"],
            "strings": snap["strings"],
        }
        with open(os.path.join(dirpath, "table.json"), "w") as f:
            json.dump(meta, f)

    def dump(self, dirpath: str) -> None:
        self.dump_snapshot(self.snapshot(), dirpath)

    def load(self, dirpath: str) -> None:
        with open(os.path.join(dirpath, "table.json")) as f:
            meta = json.load(f)
        self._keys = meta["keys"]
        self._key_to_docid = {k: int(v) for k, v in meta["key_to_docid"].items()}
        self._strings = meta["strings"]
        # pre-presence dumps: None rows read as "all fields set"
        self._strings.setdefault(
            self.PRESENCE_COL, [None] * len(self._keys))
        data = np.load(os.path.join(dirpath, "columns.npz"))
        for name, col in self._fixed.items():
            arr = data[name]
            col._data = arr.copy()
            col._n = arr.shape[0]

    def load_from_segments(
        self,
        keys: list[str],
        strings: dict[str, list],
        fixed: dict[str, np.ndarray],
        alive_mask: np.ndarray,
    ) -> None:
        """Restore from concatenated segment slices. key→docid is NOT
        persisted in the segmented format — it is derivable: an update
        appends a new row and soft-deletes the old one, so for any key
        only its LATEST row can be alive, and the map is exactly
        {key: docid | alive[docid]} (deleted keys' last rows are dead)."""
        self._keys = keys
        self._strings = strings
        self._strings.setdefault(self.PRESENCE_COL, [None] * len(keys))
        for name, col in self._fixed.items():
            arr = fixed[name]
            col._data = arr.copy() if arr.base is not None else arr
            col._n = arr.shape[0]
        alive = np.asarray(alive_mask, dtype=bool)
        self._key_to_docid = {
            keys[d]: d for d in np.flatnonzero(alive[: len(keys)]).tolist()
        }

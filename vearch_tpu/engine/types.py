"""Core data model for the per-partition engine.

TPU-native re-design of the reference's table/space schema
(reference: internal/entity/space.go:75 `Space`, internal/engine/c_api/api_data/table.h:44
`TableInfo`, internal/ps/engine/mapping/field.go field types).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any


class DataType(enum.Enum):
    """Field data types (reference: internal/engine/idl/fbs/types.fbs DataType)."""

    INT = "integer"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"
    STRING_ARRAY = "stringArray"
    DATE = "date"
    VECTOR = "vector"
    BOOL = "bool"


class MetricType(enum.Enum):
    """Distance metrics (reference: index params `metric_type` L2/InnerProduct)."""

    L2 = "L2"
    INNER_PRODUCT = "InnerProduct"
    COSINE = "Cosine"


class IndexStatus(enum.IntEnum):
    """Index build state machine (reference: search/engine.h:28-33 IndexingState
    IDLE/STARTING/RUNNING/STOPPING plus engine_status INDEXED)."""

    UNINDEXED = 0
    TRAINING = 1
    INDEXING = 2
    INDEXED = 3


class ScalarIndexType(enum.Enum):
    """Scalar index flavours (reference: table/scalar_index.h:28 + inverted/bitmap/composite)."""

    NONE = "NONE"
    INVERTED = "INVERTED"
    BITMAP = "BITMAP"


@dataclass
class IndexParams:
    """Vector index configuration.

    Mirrors the reference's per-field `index` block in a space schema
    (reference: sdk/python/vearch/schema/index.py, entity/space.go index params):
    index_type one of FLAT / IVFFLAT / IVFPQ / HNSW / BINARYIVF / IVFRABITQ,
    plus params (nlist/nprobe/m/nbits/efConstruction/efSearch/training_threshold).
    """

    index_type: str = "FLAT"
    metric_type: MetricType = MetricType.L2
    params: dict[str, Any] = field(default_factory=dict)

    def get(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    def to_dict(self) -> dict[str, Any]:
        return {
            "index_type": self.index_type,
            "metric_type": self.metric_type.value,
            "params": dict(self.params),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "IndexParams":
        return cls(
            index_type=d.get("index_type", "FLAT"),
            metric_type=MetricType(d.get("metric_type", "L2")),
            params=dict(d.get("params", {})),
        )


@dataclass
class FieldSchema:
    """One field of a table (reference: entity/space.go `SpaceProperties`,
    mapping/field.go `FieldMapping`)."""

    name: str
    data_type: DataType
    dimension: int = 0  # for VECTOR fields
    index: IndexParams | None = None  # vector index or scalar index request
    scalar_index: ScalarIndexType = ScalarIndexType.NONE

    def is_vector(self) -> bool:
        return self.data_type is DataType.VECTOR

    @property
    def wire_dim(self) -> int:
        """Vector length on the wire: binary indexes pack 8 bits per
        uint8 byte (reference: faiss binary vector format)."""
        if self.index and self.index.index_type.upper() == "BINARYIVF":
            return self.dimension // 8
        return self.dimension

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "data_type": self.data_type.value,
            "dimension": self.dimension,
            "index": self.index.to_dict() if self.index else None,
            "scalar_index": self.scalar_index.value,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FieldSchema":
        return cls(
            name=d["name"],
            data_type=DataType(d["data_type"]),
            dimension=d.get("dimension", 0),
            index=IndexParams.from_dict(d["index"]) if d.get("index") else None,
            scalar_index=ScalarIndexType(d.get("scalar_index", "NONE")),
        )


@dataclass
class TableSchema:
    """Per-partition table schema (reference: api_data/table.h:44 `TableInfo`).

    `training_threshold`: docs required before background index build starts
    (reference: engine.cc:966 BuildIndex threshold check).
    `refresh_interval_ms`: realtime indexing loop cadence
    (reference: engine.cc:1146 sleep between AddRTVecsToIndex passes).
    """

    name: str
    fields: list[FieldSchema]
    training_threshold: int = 0
    refresh_interval_ms: int = 1000
    # multi-column equality indexes (reference: composite_index.h)
    composite_indexes: list[list[str]] = field(default_factory=list)

    def vector_fields(self) -> list[FieldSchema]:
        return [f for f in self.fields if f.is_vector()]

    def scalar_fields(self) -> list[FieldSchema]:
        return [f for f in self.fields if not f.is_vector()]

    def field(self, name: str) -> FieldSchema:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"no field named {name!r}")

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "fields": [f.to_dict() for f in self.fields],
            "training_threshold": self.training_threshold,
            "refresh_interval_ms": self.refresh_interval_ms,
            "composite_indexes": self.composite_indexes,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TableSchema":
        return cls(
            name=d["name"],
            fields=[FieldSchema.from_dict(f) for f in d["fields"]],
            training_threshold=d.get("training_threshold", 0),
            refresh_interval_ms=d.get("refresh_interval_ms", 1000),
            composite_indexes=[list(c) for c in d.get("composite_indexes", [])],
        )


@dataclass
class SearchResultItem:
    """One hit: doc key, score, optional fields payload, and — when the
    request carried a `sort` spec — the hit's sort values in spec order
    (reference: response/doc_results.go SortValues; the router merges on
    these without re-deriving them from fields)."""

    key: str
    score: float
    fields: dict[str, Any] = field(default_factory=dict)
    sort_values: list | None = None


@dataclass
class SearchResult:
    """Per-query result list (reference: api_data/response.h:56 `Response`)."""

    items: list[SearchResultItem] = field(default_factory=list)


@dataclass
class ColumnarSearchResults:
    """Fields-free search results in columnar form: per-query key lists
    plus ONE flat score buffer (per-query lengths are the key-list
    lengths). Returned by Engine.search for `raw_results` requests —
    building b*k SearchResultItem objects measured ~50 ms of host time
    at b=1024, which a TPU-speed kernel cannot hide; the PS columnar
    wire path consumes this shape directly."""

    keys: list[list[str]]
    scores: Any  # np.ndarray [sum(len(keys_i))] f32

"""Scalar-field result ordering.

TPU-native analogue of the reference's sort surface (reference:
internal/ps/engine/sortorder/parse.go ParseSort — the accepted request
forms; sort.go SortOrder.Compare — typed value comparison with missing
handling; consumed by the router merges client.go:779
SearchFieldSortExecute / :1062 QueryFieldSortExecute and validated in
doc_query.go:1329-1343).

Request forms accepted, matching the reference parser:

    "sort": "price"                          # field, desc (ref default)
    "sort": "_score"                         # score, desc
    "sort": "_id"                            # id, asc
    "sort": [{"price": "asc"}]               # field: order string
    "sort": [{"price": {"order": "desc",
                        "missing": "_last"}}]  # full spec

Normalized spec: {"field": str, "desc": bool, "missing_first": bool}.
Missing values (doc has no such field) sort LAST regardless of
direction unless "missing": "_first" (reference: SortFieldMissing).

The engine attaches per-hit sort values (list, spec order) so the
router's cross-partition merge compares values it never has to
re-derive; ties break on the hit's metric-oriented score and then _id
for a deterministic, partition-count-independent order.
"""

from __future__ import annotations

from functools import cmp_to_key
from typing import Any

SCORE_FIELD = "_score"
ID_FIELD = "_id"


def parse_sort(spec: Any) -> list[dict]:
    """Normalize a request `sort` value to a list of specs. Raises
    ValueError on malformed input (reference: parse.go errors
    'invalid sort')."""
    if spec is None:
        return []
    if isinstance(spec, (str, dict)):
        return [_parse_one(spec)]
    if isinstance(spec, (list, tuple)):
        return [_parse_one(s) for s in spec]
    raise ValueError(f"invalid sort type {type(spec).__name__}")


def _parse_one(s: Any) -> dict:
    if isinstance(s, str):
        if s == SCORE_FIELD:
            return {"field": SCORE_FIELD, "desc": True,
                    "missing_first": False}
        if s == ID_FIELD:
            return {"field": ID_FIELD, "desc": False,
                    "missing_first": False}
        # bare field name defaults to desc (reference: parseSort string
        # case -> SortField{Desc: true})
        return {"field": s, "desc": True, "missing_first": False}
    if isinstance(s, dict):
        if len(s) != 1:
            raise ValueError(
                f"sort spec must have exactly one field, got {sorted(s)}"
            )
        field, val = next(iter(s.items()))
        if isinstance(val, str):
            if val not in ("asc", "desc"):
                raise ValueError(f"invalid sort order {val!r}")
            return {"field": field, "desc": val == "desc",
                    "missing_first": False}
        if isinstance(val, dict):
            order = val.get("order", "asc")
            if order not in ("asc", "desc"):
                raise ValueError(f"invalid sort order {order!r}")
            missing = val.get("missing", "_last")
            if missing not in ("_first", "_last"):
                raise ValueError(f"invalid sort missing {missing!r}")
            return {"field": field, "desc": order == "desc",
                    "missing_first": missing == "_first"}
        raise ValueError(f"invalid sort spec for field {field!r}")
    raise ValueError(f"invalid sort element {s!r}")


def compare_values(a: Any, b: Any, desc: bool, missing_first: bool) -> int:
    """Three-way compare of one sort value pair. None = missing."""
    if a is None or b is None:
        if a is None and b is None:
            return 0
        # missing placement is absolute (first/last), not affected by
        # direction (reference: SortFieldMissingFirst/Last semantics)
        if a is None:
            return -1 if missing_first else 1
        return 1 if missing_first else -1
    # bools compare as ints; numerics cross-compare; strings with
    # strings — field types are schema-enforced so mixed types only
    # appear via schema evolution, where stringification is the
    # deterministic fallback
    try:
        if a < b:
            c = -1
        elif a > b:
            c = 1
        else:
            c = 0
    except TypeError:
        sa, sb = str(a), str(b)
        c = -1 if sa < sb else (1 if sa > sb else 0)
    return -c if desc else c


def compare_rows(specs: list[dict], va: list, vb: list) -> int:
    """Compare two hits' sort-value lists under the spec list."""
    for spec, a, b in zip(specs, va, vb):
        c = compare_values(a, b, spec["desc"], spec["missing_first"])
        if c:
            return c
    return 0


def row_sort_key(specs: list[dict], get_values, tie_key=None):
    """functools key for sorting hit objects: `get_values(hit)` returns
    the sort-value list; `tie_key(hit)` (optional) yields a final
    deterministic tiebreak tuple."""

    def cmp(ha, hb) -> int:
        c = compare_rows(specs, get_values(ha), get_values(hb))
        if c or tie_key is None:
            return c
        ta, tb = tie_key(ha), tie_key(hb)
        return -1 if ta < tb else (1 if ta > tb else 0)

    return cmp_to_key(cmp)


def validate_sort(specs: list[dict], schema_fields: dict,
                  allow_score: bool = True) -> None:
    """Reject sorts on unknown or vector fields (reference:
    doc_query.go:1331 'sort field [%s] not space field'). `schema_fields`
    maps field name -> data_type string."""
    for spec in specs:
        f = spec["field"]
        if f == ID_FIELD:
            continue
        if f == SCORE_FIELD:
            if allow_score:
                continue
            raise ValueError("_score sort is not valid for query "
                             "(no vector score)")
        dt = schema_fields.get(f)
        if dt is None:
            raise ValueError(f"sort field [{f}] not space field")
        if str(dt).lower() == "vector":
            raise ValueError(f"sort field [{f}] is a vector field")

"""Disk-resident raw vector store (mmap-backed).

TPU-native analogue of the reference's beyond-RAM vector storage
(reference: internal/engine/vector/rocksdb_raw_vector.cc — RocksDB-backed
RawVector — and the DiskANN static tier,
index/impl/diskann/gamma_index_diskann_static.cc:28, whose raw data lives
on disk and only compressed codes stay in RAM).

Instead of a KV store, rows live docid-ordered in one flat mmap'd file:
- append = write through the mapping (the OS page cache absorbs it);
- growth = ftruncate + remap, no copy (the file IS the buffer);
- reads (rerank gathers, training samples) fault pages on demand, so
  host RSS stays bounded by the page cache, not the dataset;
- `flush_disk()` msyncs and records the durable row count in meta.json;
  rows past that count are garbage after a crash and are re-written by
  WAL replay (same discipline as the npy-dump stores).

The full-precision file is the rerank/training tier; the scan tier is
the DISKANN index's int8 mmap + HBM bucket cache (index/disk.py). A
`device_buffer()` call on this store intentionally raises: mirroring a
beyond-RAM store into HBM is always a bug upstream.

Rerank gathers route through a host-RAM row cache
(tiering/HostRowCache): hot candidate rows — the ones Zipf query mixes
re-rank every batch — are served from anonymous RAM instead of
re-faulting mmap pages, with frequency-based admission so one-shot
scans can't flush the hot set. `row_cache_mb=0` disables it.
"""

from __future__ import annotations

import json
import os

import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.tiering import HostRowCache
from vearch_tpu.tiering import readahead


class DiskRawVectorStore(RawVectorStore):
    durable_on_disk = True

    def __init__(
        self,
        dimension: int,
        directory: str,
        init_capacity: int = 4096,
        store_dtype: str = "float32",
        row_cache_mb: int = 64,
    ):
        # note: base __init__ is NOT called — the host buffer is a memmap
        self.dimension = dimension
        if store_dtype == "bfloat16":
            # halves disk footprint + page-cache pressure; ml_dtypes
            # registers bfloat16 as a real numpy dtype so the memmap
            # reads/writes it natively (backup npy dumps widen to f32)
            import ml_dtypes

            self.store_dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            self.store_dtype = np.dtype(store_dtype)
        self._itemsize = self.store_dtype.itemsize
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._raw_path = os.path.join(directory, "raw.f32")
        self._meta_path = os.path.join(directory, "meta.json")
        self._n = 0
        durable_cap = init_capacity
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                meta = json.load(f)
            assert meta["dimension"] == dimension, (
                f"disk store at {directory} has dimension "
                f"{meta['dimension']}, schema says {dimension}"
            )
            assert meta.get("dtype", "float32") == self.store_dtype.name, (
                f"disk store at {directory} was written as "
                f"{meta.get('dtype')}, schema says {self.store_dtype.name}"
            )
            self._n = int(meta["n"])
            durable_cap = max(durable_cap, self._n)
        self._host = self._map(max(durable_cap, 1))
        self.row_cache = (
            HostRowCache(dimension, int(row_cache_mb) << 20)
            if row_cache_mb else None
        )
        # device mirror fields kept for interface parity (never populated)
        self._device = None
        self._device_sqnorm = None
        self._device_rows = 0
        self._sh_cache = None

    def _map(self, capacity: int) -> np.memmap:
        rowbytes = self.dimension * self._itemsize
        want = capacity * rowbytes
        have = (
            os.path.getsize(self._raw_path)
            if os.path.exists(self._raw_path)
            else 0
        )
        if have < want:
            with open(self._raw_path, "ab") as f:
                f.truncate(want)
        cap = max(want, have) // rowbytes
        return np.memmap(
            self._raw_path, dtype=self.store_dtype, mode="r+",
            shape=(cap, self.dimension),
        )

    def add(self, vectors: np.ndarray) -> int:
        b = vectors.shape[0]
        assert vectors.shape[1] == self.dimension
        if self._n + b > self._host.shape[0]:
            new_cap = max(self._host.shape[0] * 2, self._n + b, 1024)
            self._host.flush()
            self._host = self._map(new_cap)
        start = self._n
        self._host[start : start + b] = vectors
        self._n += b
        return start

    def get(self, docid: int) -> np.ndarray:
        """Single stored row as float32 (partial-update inheritance)."""
        return self.get_rows(np.asarray([docid]))[0]

    def get_rows(self, docids: np.ndarray) -> np.ndarray:
        """Gather [len(docids), d] f32 rows (rerank path). Hot rows come
        from the host-RAM row cache; misses fault pages in from the mmap
        (rows are append-only and immutable, so cached copies never go
        stale — the load paths clear the cache before rewriting)."""

        def _gather(ids: np.ndarray) -> np.ndarray:
            ids = np.asarray(ids, dtype=np.int64)
            # async kernel read-ahead for the strided page faults the
            # gather is about to take (tiering/readahead.py) — page
            # cache only, zero H2D
            readahead.advise_rows(self._host, ids)
            return np.asarray(self._host[ids])

        if self.row_cache is None:
            return _gather(docids).astype(np.float32, copy=False)
        return self.row_cache.get_rows(docids, _gather)

    def device_buffer(self):
        raise RuntimeError(
            "DiskRawVectorStore cannot be mirrored into HBM; use a "
            "disk-aware index type (DISKANN) for this field"
        )

    def device_buffer_sharded(self, mesh):
        raise RuntimeError(
            "DiskRawVectorStore cannot be mirrored into HBM; use a "
            "disk-aware index type (DISKANN) for this field"
        )

    def flush_disk(self, n: int | None = None) -> None:
        """msync + record the durable row count (the dump barrier).

        `n` pins the recorded count to a snapshot-consistent value: a
        concurrent upsert between snapshot capture and flush must not
        advance the durable count past the table dump it pairs with
        (rows beyond it are garbage until WAL replay rewrites them).
        """
        self._host.flush()
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"n": self._n if n is None else int(n),
                 "dimension": self.dimension,
                 "dtype": self.store_dtype.name},
                f,
            )
        os.replace(tmp, self._meta_path)

    def memory_usage_bytes(self) -> int:
        return 0  # rows live in the page cache, not anonymous memory

    # -- persistence ---------------------------------------------------------

    def dump(self, path: str) -> None:
        # called only for dumps to a foreign directory (backup staging);
        # the normal dump path flushes in place via flush_disk(). Widen
        # non-standard dtypes (bfloat16) so the npy stays pickle-free.
        view = np.asarray(self.host_view())
        if view.dtype.kind not in "fiu":
            view = view.astype(np.float32)
        np.save(path, view)

    def load(self, path: str) -> None:
        """Restore path. With an npy present (foreign-dir backup), copy
        its contents into the mmap; without one (in-place dump), roll
        the live count back to the durable barrier in meta.json so a
        live-engine load() is symmetric with RAM-backed stores (table
        and store counts must revert together — docid == row id)."""
        if self.row_cache is not None:
            self.row_cache.clear()
        if not os.path.exists(path):
            if os.path.exists(self._meta_path):
                with open(self._meta_path) as f:
                    self._n = int(json.load(f)["n"])
            return
        if os.path.exists(path):
            data = np.load(path, mmap_mode="r")
            self._n = 0
            if self._host.shape[0] < data.shape[0]:
                self._host = self._map(data.shape[0])
            # stream in chunks: the source may exceed RAM
            step = max(1, (64 << 20) // (self.dimension * 4))
            for lo in range(0, data.shape[0], step):
                hi = min(lo + step, data.shape[0])
                self._host[lo:hi] = data[lo:hi]
            self._n = data.shape[0]
            self.flush_disk()

    def load_parts(self, paths: list[str]) -> None:
        """Segmented restore: stream each segment slice into the mmap in
        row order (foreign-dir backups of a disk store; in-place dumps
        carry no vector segments — load() rolls back via meta.json)."""
        if not paths:  # in-place dump: Engine.load uses load() instead
            return
        if self.row_cache is not None:
            self.row_cache.clear()
        self._n = 0
        total = 0
        for p in paths:
            data = np.load(p, mmap_mode="r")
            if self._host.shape[0] < total + data.shape[0]:
                self._host = self._map(
                    max(total + data.shape[0], self._host.shape[0] * 2)
                )
            step = max(1, (64 << 20) // (self.dimension * 4))
            for lo in range(0, data.shape[0], step):
                hi = min(lo + step, data.shape[0])
                self._host[total + lo : total + hi] = data[lo:hi]
            total += data.shape[0]
        self._n = total
        self.flush_disk()

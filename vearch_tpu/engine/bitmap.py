"""Deletion bitmap.

TPU-native re-design of the reference's persistent BitmapManager
(reference: internal/engine/util/bitmap_manager.h:19). Deletions never
compact the device-resident vector buffers in the hot path — deleted docids
are masked out inside the top-k kernel instead, which keeps device arrays
append-only and static-shaped (what XLA wants).

Host side is a numpy bool array (grows with the docid space); `mask(n)`
hands the search path a validity view. Persistence is a raw .npy file.
"""

from __future__ import annotations

import os

import numpy as np


class BitmapManager:
    def __init__(self, capacity: int = 1024):
        self._bits = np.zeros(max(1, capacity), dtype=bool)  # True = deleted
        self._deleted_count = 0
        self.version = 0  # bumped on every mutation (device-mask cache key)

    def _ensure(self, docid: int) -> None:
        if docid >= self._bits.shape[0]:
            new_cap = max(docid + 1, self._bits.shape[0] * 2)
            grown = np.zeros(new_cap, dtype=bool)
            grown[: self._bits.shape[0]] = self._bits
            self._bits = grown

    def set_deleted(self, docid: int) -> None:
        self._ensure(docid)
        if not self._bits[docid]:
            self._bits[docid] = True
            self._deleted_count += 1
            self.version += 1

    def unset(self, docid: int) -> None:
        self._ensure(docid)
        if self._bits[docid]:
            self._bits[docid] = False
            self._deleted_count -= 1
            self.version += 1

    def is_deleted(self, docid: int) -> bool:
        return docid < self._bits.shape[0] and bool(self._bits[docid])

    @property
    def deleted_count(self) -> int:
        return self._deleted_count

    def valid_mask(self, n: int) -> np.ndarray:
        """[n] bool, True = alive; n is the current docid high-water mark."""
        self._ensure(max(n - 1, 0))
        return ~self._bits[:n]

    def snapshot(self, n: int) -> np.ndarray:
        """Point-in-time copy of the first n bits (caller holds the
        engine write lock; the copy may be persisted lock-free)."""
        return self._bits[: max(n, 1)].copy()

    def dump(self, path: str) -> None:
        np.save(path, self._bits)

    def load(self, path: str) -> None:
        if os.path.exists(path):
            self._bits = np.load(path)
            self._deleted_count = int(self._bits.sum())
            self.version += 1

"""Error-handling rules.

VL301 — no bare ``except:`` anywhere. It catches KeyboardInterrupt and
SystemExit, turning an operator's Ctrl-C into silent state corruption.

VL302 — in the replication-critical modules (raft, WAL), a broad
handler (``except Exception``/``BaseException``) must do at least one
of: re-raise, log, or count through ``internal_error()`` /
``.inc(...)``. A silently-swallowed exception in an apply or commit
path is a replica that diverged without a trace — the failure the
whole observability stack exists to surface.
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _check_bare_except(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            line = node.lineno
            ok, reason = ctx.allowed(line, "bare-except")
            yield Finding(
                "VL301", "bare-except", ctx.path, line,
                "bare `except:` catches KeyboardInterrupt/SystemExit — "
                "name the exceptions you mean to handle",
                suppressed=ok, reason=reason,
            )


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "attr", getattr(e, "id", "")) for e in t.elts]
    else:
        names = [getattr(t, "attr", getattr(t, "id", ""))]
    return any(n in ("Exception", "BaseException") for n in names)


def _handles_visibly(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if name in config.LOG_CALL_NAMES or \
                    name in config.ERROR_COUNT_CALLS:
                return True
    return False


def _check_swallow(ctx: FileContext):
    path = ctx.path.replace("\\", "/")
    if not any(path.endswith(m) for m in config.CRITICAL_ERROR_MODULES):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or _handles_visibly(node):
            continue
        line = node.lineno
        ok, reason = ctx.allowed(line, "swallow")
        yield Finding(
            "VL302", "swallow", ctx.path, line,
            "broad except swallows the exception silently in a "
            "replication-critical module — re-raise, log, or count it "
            "via internal_error(site)",
            suppressed=ok, reason=reason,
        )


register(Rule(
    id="VL301", tag="bare-except",
    doc="no bare except: anywhere in the package",
    check_file=_check_bare_except,
))

register(Rule(
    id="VL302", tag="swallow",
    doc="raft/WAL broad excepts must raise, log, or count",
    check_file=_check_swallow,
))

"""Project policy knobs for vearch-lint.

Everything path-shaped is a POSIX path *suffix* matched against the
scanned file path, so the linter works from any working directory.
"""

from __future__ import annotations

# -- VL101 dispatch hygiene ---------------------------------------------------
# Packages allowed to create device dispatches (jax.jit / pallas_call /
# pmap / shard_map). Everything else — the cluster plane above all —
# must call into these layers instead of tracing its own programs, or
# the perf model's DOCUMENTED_DISPATCHES stops being the whole story.
DISPATCH_PACKAGES = (
    "vearch_tpu/ops/",
    "vearch_tpu/engine/",
    # the mesh data plane: shard_map programs + tail-append writers are
    # first-class dispatch sources, registered in the perf model's jit
    # registry like every ops/ program
    "vearch_tpu/parallel/",
    # the tiered storage engine: the staged slab scatter is the one
    # device program of the subsystem, registered in the jit registry
    "vearch_tpu/tiering/",
)

# Names whose call or decorator use counts as creating a dispatchable
# program. Attribute form (jax.jit) and bare imported form (jit) both.
DISPATCH_CONSTRUCTS = {
    "jit", "pmap", "pallas_call", "shard_map", "xla_computation",
}

# -- VL102 host-device sync points in serving paths ---------------------------
# (path suffix, function qualname) pairs marking the hot serving path.
# Inside these functions a host sync (block_until_ready / device_get /
# .item() / np.asarray materialisation) stalls the request on device
# completion and must carry an inline allow[host-sync] justification.
SERVING_PATH_FUNCTIONS = {
    ("vearch_tpu/engine/engine.py", "Engine.search"),
    ("vearch_tpu/engine/engine.py", "Engine._search_direct"),
    ("vearch_tpu/cluster/ps.py", "PSServer._h_search"),
    ("vearch_tpu/cluster/ps.py", "PSServer._do_search"),
    ("vearch_tpu/cluster/router.py", "Router._h_search"),
    ("vearch_tpu/cluster/router.py", "Router._search_impl"),
    ("vearch_tpu/cluster/router.py", "Router._search_scatter"),
}

HOST_SYNC_METHODS = {"block_until_ready", "item"}
HOST_SYNC_CALLS = {"device_get", "asarray", "array"}

# -- VL203 wall-clock discipline ---------------------------------------------
# time.time() is banned for anything measured or compared (latency,
# deadlines, TTLs): wall clocks step under NTP and the measurement
# silently corrupts. time.monotonic() is the default; genuinely
# wall-anchored stamps (span epochs, persisted create times) carry an
# inline allow[wall-clock] with the reason.

# -- VL302 swallowed exceptions ----------------------------------------------
# Modules whose apply/commit paths must never swallow an exception
# silently: a broad handler there needs a raise, a log call, or an
# internal_error() count before it may continue.
CRITICAL_ERROR_MODULES = (
    "vearch_tpu/cluster/raft.py",
    "vearch_tpu/cluster/wal.py",
)

LOG_CALL_NAMES = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}
ERROR_COUNT_CALLS = {"internal_error", "inc"}

# -- VL103 shape-bucket drift -------------------------------------------------
# The continuous-batching scheduler only stays zero-retrace if every
# serving-path batch shape comes from ONE declared grid. The canonical
# declaration lives in BUCKET_DECL_FILE; lint pins its values here so
# the grid cannot change without a conscious policy edit, and flags any
# OTHER module re-declaring bucket/tier literals instead of importing
# the perf model's.
BUCKET_DECL_FILE = "vearch_tpu/ops/perf_model.py"
BUCKET_ROW_TIERS = (8, 64, 256, 1024)
BUCKET_FETCH_K_TIERS = (16, 64, 256, 1024)
# module-level names matched (by suffix) as shape-tier declarations
BUCKET_NAME_SUFFIXES = ("_BUCKETS", "_TIERS")

# -- VL104 tenant attribution -------------------------------------------------
# Serving-path files where billable counter mutations must carry space
# attribution (docs/ACCOUNTING.md): ISSUE 17 made every serving-path
# cost tenant-attributable, and a new .inc() that forgets the space
# label silently un-attributes a whole failure class. Matched by path
# suffix, like SERVING_PATH_FUNCTIONS.
VL104_SERVING_FILES = (
    "vearch_tpu/cluster/ps.py",
    "vearch_tpu/cluster/router.py",
)
# counter attributes whose .inc() calls are billable events: they count
# per-tenant failures (kills, sheds) and must pass a space label
VL104_BILLABLE_COUNTERS = ("_killed_total", "_shed_total")

# -- VL105 quality staleness --------------------------------------------------
# The search-quality truth layer (docs/QUALITY.md) scores served
# results against fresh exact ground truth. Any function that replaces
# the serving index (an engine build/rebuild call) must also call the
# monitor's staleness hook, or queued shadow samples get scored against
# a snapshot that no longer serves — phantom recall loss. Matched by
# path suffix in the files that own index mutation.
VL105_QUALITY_FILES = (
    "vearch_tpu/cluster/ps.py",
    # the engine owns the bit-plane / mirror rebuild paths directly:
    # rebuild_index replaces every compressed serving tier in place,
    # so engine-embedded users (bench, SDK-local) need the hook too
    "vearch_tpu/engine/engine.py",
)
# attribute-call names that replace index contents wholesale
VL105_INDEX_MUTATORS = ("build_index", "rebuild_index")
# the QualityMonitor staleness hook every such function must also call
VL105_STALENESS_HOOK = "note_index_mutation"

# -- VL201 lock discipline ----------------------------------------------------
# Methods treated as mutations when called on a guarded attribute.
MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "move_to_end",
    "appendleft", "popleft",
}

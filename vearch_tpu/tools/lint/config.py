"""Project policy knobs for vearch-lint.

Everything path-shaped is a POSIX path *suffix* matched against the
scanned file path, so the linter works from any working directory.
"""

from __future__ import annotations

# -- VL101 dispatch hygiene ---------------------------------------------------
# Packages allowed to create device dispatches (jax.jit / pallas_call /
# pmap / shard_map). Everything else — the cluster plane above all —
# must call into these layers instead of tracing its own programs, or
# the perf model's DOCUMENTED_DISPATCHES stops being the whole story.
DISPATCH_PACKAGES = (
    "vearch_tpu/ops/",
    "vearch_tpu/engine/",
    # the mesh data plane: shard_map programs + tail-append writers are
    # first-class dispatch sources, registered in the perf model's jit
    # registry like every ops/ program
    "vearch_tpu/parallel/",
    # the tiered storage engine: the staged slab scatter is the one
    # device program of the subsystem, registered in the jit registry
    "vearch_tpu/tiering/",
)

# Names whose call or decorator use counts as creating a dispatchable
# program. Attribute form (jax.jit) and bare imported form (jit) both.
DISPATCH_CONSTRUCTS = {
    "jit", "pmap", "pallas_call", "shard_map", "xla_computation",
}

# -- VL102 host-device sync points in serving paths ---------------------------
# (path suffix, function qualname) pairs marking the hot serving path.
# Inside these functions a host sync (block_until_ready / device_get /
# .item() / np.asarray materialisation) stalls the request on device
# completion and must carry an inline allow[host-sync] justification.
SERVING_PATH_FUNCTIONS = {
    ("vearch_tpu/engine/engine.py", "Engine.search"),
    ("vearch_tpu/engine/engine.py", "Engine._search_direct"),
    ("vearch_tpu/cluster/ps.py", "PSServer._h_search"),
    ("vearch_tpu/cluster/ps.py", "PSServer._do_search"),
    ("vearch_tpu/cluster/router.py", "RouterServer._h_search"),
    ("vearch_tpu/cluster/router.py", "RouterServer._search_impl"),
    ("vearch_tpu/cluster/router.py", "RouterServer._search_scatter"),
}

HOST_SYNC_METHODS = {"block_until_ready", "item"}
HOST_SYNC_CALLS = {"device_get", "asarray", "array"}

# -- VL203 wall-clock discipline ---------------------------------------------
# time.time() is banned for anything measured or compared (latency,
# deadlines, TTLs): wall clocks step under NTP and the measurement
# silently corrupts. time.monotonic() is the default; genuinely
# wall-anchored stamps (span epochs, persisted create times) carry an
# inline allow[wall-clock] with the reason.

# -- VL302 swallowed exceptions ----------------------------------------------
# Modules whose apply/commit paths must never swallow an exception
# silently: a broad handler there needs a raise, a log call, or an
# internal_error() count before it may continue.
CRITICAL_ERROR_MODULES = (
    "vearch_tpu/cluster/raft.py",
    "vearch_tpu/cluster/wal.py",
)

LOG_CALL_NAMES = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}
ERROR_COUNT_CALLS = {"internal_error", "inc"}

# -- VL103 shape-bucket drift -------------------------------------------------
# The continuous-batching scheduler only stays zero-retrace if every
# serving-path batch shape comes from ONE declared grid. The canonical
# declaration lives in BUCKET_DECL_FILE; lint pins its values here so
# the grid cannot change without a conscious policy edit, and flags any
# OTHER module re-declaring bucket/tier literals instead of importing
# the perf model's.
BUCKET_DECL_FILE = "vearch_tpu/ops/perf_model.py"
BUCKET_ROW_TIERS = (8, 64, 256, 1024)
BUCKET_FETCH_K_TIERS = (16, 64, 256, 1024)
# module-level names matched (by suffix) as shape-tier declarations
BUCKET_NAME_SUFFIXES = ("_BUCKETS", "_TIERS")

# -- VL104 tenant attribution -------------------------------------------------
# Serving-path files where billable counter mutations must carry space
# attribution (docs/ACCOUNTING.md): ISSUE 17 made every serving-path
# cost tenant-attributable, and a new .inc() that forgets the space
# label silently un-attributes a whole failure class. Matched by path
# suffix, like SERVING_PATH_FUNCTIONS.
VL104_SERVING_FILES = (
    "vearch_tpu/cluster/ps.py",
    "vearch_tpu/cluster/router.py",
)
# counter attributes whose .inc() calls are billable events: they count
# per-tenant failures (kills, sheds) and must pass a space label
VL104_BILLABLE_COUNTERS = ("_killed_total", "_shed_total")

# -- VL105 quality staleness --------------------------------------------------
# The search-quality truth layer (docs/QUALITY.md) scores served
# results against fresh exact ground truth. Any function that replaces
# the serving index (an engine build/rebuild call) must also call the
# monitor's staleness hook, or queued shadow samples get scored against
# a snapshot that no longer serves — phantom recall loss. Matched by
# path suffix in the files that own index mutation.
VL105_QUALITY_FILES = (
    "vearch_tpu/cluster/ps.py",
    # the engine owns the bit-plane / mirror rebuild paths directly:
    # rebuild_index replaces every compressed serving tier in place,
    # so engine-embedded users (bench, SDK-local) need the hook too
    "vearch_tpu/engine/engine.py",
)
# attribute-call names that replace index contents wholesale
VL105_INDEX_MUTATORS = ("build_index", "rebuild_index")
# the QualityMonitor staleness hook every such function must also call
VL105_STALENESS_HOOK = "note_index_mutation"

# -- VL201 lock discipline ----------------------------------------------------
# Methods treated as mutations when called on a guarded attribute.
MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "move_to_end",
    "appendleft", "popleft",
}

# -- VL501–VL504 interprocedural serving-path analysis ------------------------
# Entry points the whole-program call graph is rooted at:
# (path suffix, function qualname, kind). "search" marks the
# latency-critical read path (VL502 blocking and VL504 deadline rules
# apply); "write" marks ingest/apply paths (VL501 dispatch hygiene
# only — writes tolerate I/O by design, raft/WAL *are* I/O).
INTERPROC_ENTRY_POINTS = (
    ("vearch_tpu/cluster/router.py", "RouterServer._h_search", "search"),
    ("vearch_tpu/cluster/ps.py", "PSServer._h_search", "search"),
    ("vearch_tpu/engine/engine.py", "Engine.search", "search"),
    # the continuous-batching dispatch thread serves queued searches
    ("vearch_tpu/engine/batching.py", "BatchScheduler._loop", "search"),
    ("vearch_tpu/cluster/router.py", "RouterServer._h_upsert", "write"),
    ("vearch_tpu/cluster/router.py", "RouterServer._h_delete", "write"),
    ("vearch_tpu/cluster/ps.py", "PSServer._h_upsert", "write"),
    ("vearch_tpu/cluster/ps.py", "PSServer._h_delete", "write"),
    ("vearch_tpu/engine/engine.py", "Engine.upsert", "write"),
    ("vearch_tpu/engine/engine.py", "Engine.delete", "write"),
    # raft/WAL observer callbacks run on the apply thread; their
    # closures are reachable through the closure rule
    ("vearch_tpu/cluster/ps.py", "PSServer._raft_observer", "write"),
    ("vearch_tpu/cluster/ps.py", "PSServer._wal_observer", "write"),
)

# Ubiquitous method names whose name-based fan-out would connect every
# class in the project; calls on untyped receivers with these names
# land in the unresolved bucket instead of fanning out.
FANOUT_STOPLIST = {
    "get", "put", "pop", "add", "append", "extend", "remove", "discard",
    "clear", "update", "setdefault", "items", "keys", "values", "copy",
    "close", "start", "stop", "join", "wait", "set", "reset", "acquire",
    "release", "read", "write", "send", "recv", "open", "flush", "load",
    "save", "notify", "notify_all", "count", "index", "sort", "split",
    "strip", "encode", "decode", "format", "lower", "upper", "popleft",
    "appendleft", "info", "debug", "warning", "error", "exception",
}

# Layers that sit ABOVE the cluster (clients of it): excluded from
# name-based fan-out so VearchClient.search cannot be mistaken for a
# callee of Engine.search.
INTERPROC_FANOUT_EXCLUDE = ("vearch_tpu/sdk/",)

# Packages whose host-device syncs are their own business (VL502's
# host-sync subset): the device layers and the CPU-side index/scalar
# data structures materialise arrays by design. The blocking-I/O
# subset is exempt NOWHERE — an open()/sleep()/socket reachable from
# a search handler needs a justification wherever it lives.
VL502_SYNC_EXEMPT_PACKAGES = DISPATCH_PACKAGES + (
    "vearch_tpu/index/",
    "vearch_tpu/scalar/",
)

# Blocking primitives: bare-name calls that resolve to nothing in the
# project (true builtins/externals)...
VL502_BLOCKING_BARE = {"open", "urlopen"}
# ...module-qualified calls (module -> functions; None = any)...
VL502_BLOCKING_MODULES = {
    "time": {"sleep"},
    "socket": None,
    "select": None,
    "subprocess": {"run", "Popen", "check_call", "check_output", "call"},
    "mmap": {"mmap"},
    "os": {"read", "write", "fsync", "system", "popen", "sendfile"},
    "urllib.request": {"urlopen"},
    "requests": None,
    "numpy": {"memmap"},
}
# ...and methods on receivers the resolver could not type (file/socket
# handles reaching the serving path through parameters).
VL502_BLOCKING_METHODS = {
    "recv", "recv_into", "sendall", "accept", "connect", "readinto",
    "readline", "readlines", "madvise",
}

# Known mmap page-fault gather frames: functions whose subscript
# gathers fault NVMe pages on the request thread (no call for the
# analyzer to see). Serving-path reachability of these frames is a
# VL502 finding unless the def line carries the justification.
VL502_PAGEFAULT_FUNCS = (
    ("vearch_tpu/tiering/ram_tier.py", "HostRowCache.get_rows"),
    ("vearch_tpu/tiering/ram_tier.py", "HostRamSlabTier.get"),
    ("vearch_tpu/tiering/readahead.py", "advise_rows"),
)

# -- VL504 deadline propagation ----------------------------------------------
# RPC/HTTP boundary calls on the search serving path: every one must
# thread the request deadline downstream (an explicit timeout= derived
# from the armed RequestContext, or a body dict that carries
# deadline_ms for the receiving node to arm its own context).
VL504_BOUNDARY_SUFFIXES = ("cluster.rpc:call",)
VL504_BOUNDARY_DOTTED = ("rpc.call",)
VL504_DEADLINE_KWARGS = {"timeout", "deadline_ms", "deadline"}
VL504_BODY_DEADLINE_KEY = "deadline_ms"

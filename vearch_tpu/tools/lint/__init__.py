"""vearch-lint: project-invariant static analysis for vearch-tpu.

Run: ``python -m vearch_tpu.tools.lint [paths...]`` (defaults to the
installed package). Rule catalogue and the allowlist workflow are
documented in docs/STATIC_ANALYSIS.md.
"""

from vearch_tpu.tools.lint.core import (
    Allowlist,
    FileContext,
    Finding,
    Rule,
    RULES,
    run_paths,
)

__all__ = [
    "Allowlist",
    "FileContext",
    "Finding",
    "Rule",
    "RULES",
    "run_paths",
    "default_allowlist_path",
]


def default_allowlist_path() -> str:
    import os

    return os.path.join(os.path.dirname(__file__), "allowlist.txt")

"""Tenant attribution discipline.

VL104 — serving-path billable counter mutations must carry space
attribution. The per-tenant cost layer (docs/ACCOUNTING.md) only adds
up to the truth if every serving-path failure counter — kills, sheds —
names the space it happened to. A `.inc()` on one of the billable
counters (`tools/lint/config.py: VL104_BILLABLE_COUNTERS`) inside the
serving files (`VL104_SERVING_FILES`) that passes no space-shaped
argument silently un-attributes a whole failure class: the cluster
rollup still balances, but the tenant who ate the 429s disappears from
`/cluster/usage` and their SLO burn never moves.

An increment counts as attributed when any argument expression
references the space — an identifier, attribute, or string literal
whose name contains ``space`` (``space_lbl``, ``self._space_key(pid)``,
``accounting.SYSTEM_SPACE`` all qualify). Genuinely tenant-free
increments (zero-fill label registration, process-level events) carry
an inline ``allow[space-attr]`` with the reason.
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _mentions_space(node: ast.AST) -> bool:
    """True if any sub-expression names the space: an identifier,
    attribute, or string literal containing `space` (case-blind)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "space" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "space" in sub.attr.lower():
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and "space" in sub.value.lower():
            return True
    return False


def _counter_name(func: ast.AST) -> str | None:
    """For a `<target>.inc` callee, the attribute/name the counter
    lives under (`self._shed_total.inc` -> `_shed_total`)."""
    if not (isinstance(func, ast.Attribute) and func.attr == "inc"):
        return None
    target = func.value
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return None


def _check_space_attr(ctx: FileContext):
    path = _norm(ctx.path)
    if not path.endswith(tuple(config.VL104_SERVING_FILES)):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _counter_name(node.func)
        if name is None or name not in config.VL104_BILLABLE_COUNTERS:
            continue
        exprs: list[ast.AST] = list(node.args)
        exprs.extend(kw.value for kw in node.keywords)
        if any(_mentions_space(e) for e in exprs):
            continue
        ok, reason = ctx.allowed(node.lineno, "space-attr")
        yield Finding(
            "VL104", "space-attr", ctx.path, node.lineno,
            f"`{name}.inc(...)` on the serving path passes no space "
            "attribution — billable counters must name the tenant or "
            "the cost layer (docs/ACCOUNTING.md) loses this failure "
            "class",
            suppressed=ok, reason=reason,
        )


register(Rule(
    id="VL104", tag="space-attr",
    doc="serving-path billable counters must carry space attribution",
    check_file=_check_space_attr,
))

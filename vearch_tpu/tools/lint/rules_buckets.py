"""Shape-bucket discipline.

VL103 — serving code must not construct batch shapes outside the
declared bucket set. The continuous-batching scheduler's zero-retrace
guarantee (docs/PERF.md Tier 7) rests on every padded dispatch shape
coming from ONE grid: `ops/perf_model.ROW_BUCKETS` x
`FETCH_K_TIERS`. Two failure modes this rule closes:

- a module re-declares its own `*_BUCKETS` / `*_TIERS` literal instead
  of importing the perf model's — the grids drift apart and the
  compiled-program bound silently stops holding;
- the canonical declaration itself changes without the policy pin in
  `tools/lint/config.py` moving with it — tier changes are a perf-model
  event (warmup sets, program-count gates, bench baselines all shift)
  and must be conscious.
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _int_seq(node: ast.AST) -> tuple[int, ...] | None:
    """Evaluate a Tuple/List literal of plain ints; None otherwise."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out: list[int] = []
    for elt in node.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, int) \
                and not isinstance(elt.value, bool):
            out.append(elt.value)
        else:
            return None
    return tuple(out)


def _tier_assigns(ctx: FileContext):
    """Module-level `NAME = (ints...)` where NAME looks like a shape
    tier declaration. Yields (name, values, line)."""
    for node in ctx.tree.body:
        targets: list[ast.AST] = []
        value: ast.AST | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        seq = _int_seq(value)
        if seq is None:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and any(
                t.id.endswith(suf) for suf in config.BUCKET_NAME_SUFFIXES
            ):
                yield t.id, seq, node.lineno


def _check_buckets(ctx: FileContext):
    path = _norm(ctx.path)
    if "/tools/lint/" in path:
        # the lint package IS the policy pin — its copies of the grid
        # are the reference the rule compares against
        return
    if path.endswith(config.BUCKET_DECL_FILE):
        # the canonical declaration: its values must match the policy
        # pin, so a grid change is a conscious two-file edit
        want = {
            "ROW_BUCKETS": tuple(config.BUCKET_ROW_TIERS),
            "FETCH_K_TIERS": tuple(config.BUCKET_FETCH_K_TIERS),
        }
        seen: dict[str, tuple[tuple[int, ...], int]] = {}
        for name, seq, line in _tier_assigns(ctx):
            seen[name] = (seq, line)
        for name, values in want.items():
            if name not in seen:
                yield Finding(
                    "VL103", "bucket-drift", ctx.path, 1,
                    f"canonical shape grid `{name}` missing from the "
                    "perf model — the scheduler's zero-retrace bound "
                    "has no declaration to hold against",
                )
            elif seen[name][0] != values:
                got, line = seen[name]
                ok, reason = ctx.allowed(line, "bucket-drift")
                yield Finding(
                    "VL103", "bucket-drift", ctx.path, line,
                    f"`{name}` = {got} diverges from the lint policy "
                    f"pin {values} (tools/lint/config.py) — tier "
                    "changes must move both or the program-count "
                    "gates drift",
                    suppressed=ok, reason=reason,
                )
        return
    for name, seq, line in _tier_assigns(ctx):
        ok, reason = ctx.allowed(line, "bucket-drift")
        yield Finding(
            "VL103", "bucket-drift", ctx.path, line,
            f"shape-tier literal `{name}` = {seq} declared outside "
            f"{config.BUCKET_DECL_FILE} — serving code must import "
            "the declared bucket grid, not re-declare it",
            suppressed=ok, reason=reason,
        )


register(Rule(
    id="VL103", tag="bucket-drift",
    doc="batch shapes only from the declared perf-model bucket grid",
    check_file=_check_buckets,
))

"""CLI: python -m vearch_tpu.tools.lint [paths...]

Exit 0 when every finding is suppressed with a reason (inline pragma
or allowlist entry); exit 1 otherwise. `--show-allowed` prints the
suppressed findings too, so the waiver inventory stays reviewable.
"""

from __future__ import annotations

import argparse
import os
import sys

from vearch_tpu.tools.lint import (
    Allowlist, RULES, default_allowlist_path, run_paths,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="vearch-lint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the vearch_tpu "
                         "package)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file (default: the checked-in "
                         "tools/lint/allowlist.txt)")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="ignore the allowlist (show everything)")
    ap.add_argument("--show-allowed", action="store_true",
                    help="also print suppressed findings with reasons")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    # importing run_paths' rule modules happens inside run_paths; for
    # --list-rules force it eagerly
    from vearch_tpu.tools.lint import (  # noqa: F401
        rules_accounting, rules_buckets, rules_dispatch, rules_errors,
        rules_locks, rules_obs, rules_quality,
    )

    if args.list_rules:
        for r in RULES:
            print(f"{r.id}  allow[{r.tag}]  {r.doc}")
        return 0

    paths = args.paths
    if not paths:
        import vearch_tpu

        paths = [os.path.dirname(os.path.abspath(vearch_tpu.__file__))]

    allowlist = None
    if not args.no_allowlist:
        allowlist = Allowlist(args.allowlist or default_allowlist_path())

    findings = run_paths(paths, allowlist=allowlist)
    hard = [f for f in findings if not f.suppressed]
    soft = [f for f in findings if f.suppressed]
    for f in hard:
        print(f.render())
    if args.show_allowed:
        for f in soft:
            print(f.render())
    print(f"vearch-lint: {len(hard)} finding(s), "
          f"{len(soft)} allowed with reasons")
    return 1 if hard else 0


if __name__ == "__main__":
    sys.exit(main())

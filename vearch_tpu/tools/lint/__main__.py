"""CLI: python -m vearch_tpu.tools.lint [paths...]

Exit 0 when every finding is suppressed with a reason (inline pragma
or allowlist entry); exit 1 otherwise. `--show-allowed` prints the
suppressed findings too, so the waiver inventory stays reviewable.

`--json` emits the findings as a machine-readable object (CI
annotators); `--changed-only <git-ref>` still analyzes the WHOLE
package (the interprocedural rules need the full call graph) but only
*reports* findings in files changed since the ref; `--lock-graph`
prints the static lock-order artifact the stress suite diffs runtime
lockcheck edges against.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from vearch_tpu.tools.lint import (
    Allowlist, RULES, default_allowlist_path, run_paths,
)


def _changed_files(ref: str) -> set[str] | None:
    """Absolute paths of files changed vs `ref` (committed, staged and
    unstaged), or None when git cannot answer."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    top = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        capture_output=True, text=True,
    ).stdout.strip()
    return {
        os.path.abspath(os.path.join(top, line.strip()))
        for line in out.stdout.splitlines() if line.strip()
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="vearch-lint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the vearch_tpu "
                         "package)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist file (default: the checked-in "
                         "tools/lint/allowlist.txt)")
    ap.add_argument("--no-allowlist", action="store_true",
                    help="ignore the allowlist (show everything)")
    ap.add_argument("--show-allowed", action="store_true",
                    help="also print suppressed findings with reasons")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--changed-only", metavar="GIT_REF", default=None,
                    help="analyze the whole package but report only "
                         "findings in files changed since GIT_REF")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the static lock-order graph artifact "
                         "(JSON) instead of findings")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    # importing run_paths' rule modules happens inside run_paths; for
    # --list-rules force it eagerly
    from vearch_tpu.tools.lint import (  # noqa: F401
        rules_accounting, rules_buckets, rules_dispatch, rules_errors,
        rules_interproc, rules_locks, rules_obs, rules_quality,
    )

    if args.list_rules:
        for r in RULES:
            print(f"{r.id}  allow[{r.tag}]  {r.doc}")
        return 0

    paths = args.paths
    if not paths:
        import vearch_tpu

        paths = [os.path.dirname(os.path.abspath(vearch_tpu.__file__))]

    allowlist = None
    if not args.no_allowlist:
        allowlist = Allowlist(args.allowlist or default_allowlist_path())

    findings = run_paths(paths, allowlist=allowlist)

    if args.lock_graph:
        from vearch_tpu.tools.lint import callgraph

        artifact = (callgraph.LAST.lock_graph_artifact()
                    if callgraph.LAST is not None
                    else {"nodes": [], "edges": [], "cycles": []})
        print(json.dumps(artifact, indent=2, sort_keys=True))
        return 0

    if args.changed_only is not None:
        changed = _changed_files(args.changed_only)
        if changed is None:
            print(f"vearch-lint: cannot diff against "
                  f"{args.changed_only!r} (not a git checkout?)",
                  file=sys.stderr)
            return 2

        def _keep(f) -> bool:
            # unused-allowlist bookkeeping is whole-tree state: in a
            # changed-only run the tree wasn't fully relinted from the
            # ref's point of view, so it cannot be judged here
            if f.line == 0 and f.rule == "VL000":
                return False
            return os.path.abspath(f.path) in changed

        findings = [f for f in findings if _keep(f)]

    hard = [f for f in findings if not f.suppressed]
    soft = [f for f in findings if f.suppressed]

    if args.as_json:
        shown = hard + (soft if args.show_allowed else [])
        print(json.dumps({
            "findings": [
                {"rule": f.rule, "tag": f.tag, "path": f.path,
                 "line": f.line, "message": f.message,
                 "suppressed": f.suppressed, "reason": f.reason}
                for f in shown
            ],
            "hard": len(hard),
            "allowed": len(soft),
        }, indent=2))
        return 1 if hard else 0

    for f in hard:
        print(f.render())
    if args.show_allowed:
        for f in soft:
            print(f.render())
    print(f"vearch-lint: {len(hard)} finding(s), "
          f"{len(soft)} allowed with reasons")
    return 1 if hard else 0


if __name__ == "__main__":
    sys.exit(main())

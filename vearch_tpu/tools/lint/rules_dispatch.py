"""Dispatch-hygiene rules.

VL101 — dispatch-creating constructs (`jax.jit`, `pallas_call`,
`pmap`, `shard_map`) may only appear in the device layers
(`ops/`, `engine/`). A jit hidden in the cluster plane creates device
programs the perf model never counted — the zero-retrace and
dispatch-count CI gates (docs/PERF.md) only hold if every program is
born where the model can see it.

VL102 — host-device sync points (`block_until_ready`, `device_get`,
`.item()`, `np.asarray` / `np.array` materialisation) inside the
configured serving-path functions. Each one stalls the request thread
on device completion; the intended ones (terminal result
materialisation) carry an inline `allow[host-sync]` reason.
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _dotted(node: ast.AST) -> str | None:
    """Render a Name/Attribute chain as 'a.b.c'; None for anything else."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _qualname(ctx: FileContext, func: ast.AST) -> str:
    names = [func.name]
    for anc in ctx.ancestors(func):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(anc.name)
    return ".".join(reversed(names))


def _check_dispatch(ctx: FileContext):
    path = _norm(ctx.path)
    if any(pkg in path for pkg in config.DISPATCH_PACKAGES):
        return
    for node in ast.walk(ctx.tree):
        name = None
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dname = _dotted(target)
                if dname and dname.split(".")[-1] in \
                        config.DISPATCH_CONSTRUCTS:
                    name = dname
                    node = dec  # report the decorator line
                    break
        if not name:
            continue
        last = name.split(".")[-1]
        if last not in config.DISPATCH_CONSTRUCTS:
            continue
        # bare `jit` must come from jax to count; attribute forms
        # (jax.jit, pl.pallas_call, jax.experimental...) always count
        line = node.lineno
        ok, reason = ctx.allowed(line, "dispatch")
        yield Finding(
            "VL101", "dispatch", ctx.path, line,
            f"dispatch-creating construct `{name}` outside the device "
            "layers (ops/, engine/) — the perf model cannot see "
            "programs born here",
            suppressed=ok, reason=reason,
        )


def _check_host_sync(ctx: FileContext):
    path = _norm(ctx.path)
    serving: list[ast.AST] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = _qualname(ctx, node)
            for suffix, want in config.SERVING_PATH_FUNCTIONS:
                if path.endswith(suffix) and qn == want:
                    serving.append(node)
    for func in serving:
        fa, freason = ctx.func_allowed(func, "host-sync")
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            hit = None
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in config.HOST_SYNC_METHODS and not node.args:
                    hit = f".{attr}()"
                elif attr in config.HOST_SYNC_CALLS:
                    base = _dotted(node.func.value)
                    if base in ("np", "numpy", "_np", "jax"):
                        hit = f"{base}.{attr}(...)"
            if hit is None:
                continue
            line = node.lineno
            ok, reason = ctx.allowed(line, "host-sync")
            if not ok and fa:
                ok, reason = True, freason
            yield Finding(
                "VL102", "host-sync", ctx.path, line,
                f"host-device sync `{hit}` inside serving-path "
                f"function `{func.name}` — stalls the request on "
                "device completion; justify inline if intended",
                suppressed=ok, reason=reason,
            )


register(Rule(
    id="VL101", tag="dispatch",
    doc="jit/pallas_call/pmap/shard_map only in ops/ and engine/",
    check_file=_check_dispatch,
))

register(Rule(
    id="VL102", tag="host-sync",
    doc="no unjustified host-device sync inside serving-path functions",
    check_file=_check_host_sync,
))

"""Interprocedural serving-path rules (VL501–VL504).

All four share ONE whole-program analysis (`callgraph.analysis_for`)
built from the same parsed contexts the lexical rules already use, so
the package is parsed once and analyzed once per lint run.

VL501 — transitive dispatch. VL101 bans dispatch constructs outside
the device layers *lexically*; a pragma'd or allowlisted site can
still be laundered onto a serving path through helpers. VL501 re-runs
the check over every function *reachable from a serving entry point*
and reports the full call chain, so a waiver for "offline tooling"
stops holding the moment a handler can reach the site.

VL502 — transitive host-sync / blocking I/O on the search path. A
`time.sleep`, `open()`, socket call, unjustified `np.asarray`, or a
known mmap page-fault gather frame reachable from a search handler
stalls the request thread. Reported with the entry-to-frame chain;
the justification pragma must sit at the offending frame (tag
`serving-blocking`; the sync subset also honors the existing
`host-sync` pragmas so VL102's inventory carries over).

VL503 — static lock-order graph. Every `with <lock>` nesting,
explicit `.acquire()` on a minted lock, and lock taken transitively
by a callee while another is held is a directed edge; a cycle is a
deadlock the runtime lockcheck would only catch if the schedule got
unlucky. The edge set is exported (`lint --lock-graph`) and the
stress suite asserts runtime lockcheck edges ⊆ this graph.

VL504 — deadline propagation. Every `rpc.call` boundary reachable
from a search handler must thread the request deadline: a `timeout=`
derived from the armed RequestContext, or a literal body dict
carrying `deadline_ms` for the callee to arm its own context. A
dropped deadline is an unkillable downstream call — the 499 kill
machinery cannot reach work the caller never bounded.
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import callgraph, config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _allowed_at(ctx: FileContext, fn, line: int, tags: tuple[str, ...]) \
        -> tuple[bool, str]:
    for tag in tags:
        ok, reason = ctx.allowed(line, tag)
        if ok:
            return ok, reason
        ok, reason = ctx.func_allowed(fn.node, tag)
        if ok:
            return ok, reason
    return False, ""


# -- VL501 --------------------------------------------------------------------

def _check_transitive_dispatch(contexts: list[FileContext]):
    a = callgraph.analysis_for(contexts)
    reach: dict[str, str] = {}
    for kind in ("search", "write"):
        for q in a.reachable(kind):
            reach.setdefault(q, kind)
    for qual, kind in sorted(reach.items()):
        fn = a.funcs[qual]
        path = _norm(fn.ctx.path)
        if any(pkg in path for pkg in config.DISPATCH_PACKAGES):
            continue
        hits: list[tuple[int, str]] = []
        for rec in fn.calls:
            last = (rec.dotted or "").split(".")[-1]
            if last in config.DISPATCH_CONSTRUCTS:
                hits.append((rec.line, rec.dotted))
        for dec in getattr(fn.node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            dname = _dotted(target)
            if dname and dname.split(".")[-1] in \
                    config.DISPATCH_CONSTRUCTS:
                hits.append((dec.lineno, dname))
        for line, name in hits:
            ok, reason = _allowed_at(
                fn.ctx, fn, line, ("transitive-dispatch",))
            yield Finding(
                "VL501", "transitive-dispatch", fn.ctx.path, line,
                f"`{name}` dispatches outside the device layers on a "
                f"{kind} serving path: "
                f"{a.render_chain(qual, kind)} — the perf model "
                "cannot see programs born here",
                suppressed=ok, reason=reason,
            )


# -- VL502 --------------------------------------------------------------------

_SYNC_TAGS = ("serving-blocking", "host-sync")
_IO_TAGS = ("serving-blocking",)


def _sync_hit(rec) -> str | None:
    node = rec.node
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    if attr in config.HOST_SYNC_METHODS and not node.args:
        return f".{attr}()"
    if attr in config.HOST_SYNC_CALLS:
        base = _dotted(node.func.value)
        if base in ("np", "numpy", "_np", "jax", "jnp"):
            return f"{base}.{attr}(...)"
    return None


def _io_hit(rec, fn, analysis) -> str | None:
    d = rec.dotted or ""
    parts = d.split(".")
    if rec.kind in ("external", "dynamic"):
        if len(parts) == 1 and parts[0] in config.VL502_BLOCKING_BARE:
            return f"{d}(...)"
        if len(parts) >= 2:
            mod = analysis.modules[fn.module]
            base = ".".join(parts[:-1])
            real = mod.mod_alias.get(parts[0])
            if real is not None:
                base = ".".join([real] + parts[1:-1])
            funcs = config.VL502_BLOCKING_MODULES.get(base)
            if funcs is not None and (not funcs or parts[-1] in funcs):
                return f"{base}.{parts[-1]}(...)"
            if funcs is None and base in config.VL502_BLOCKING_MODULES \
                    and config.VL502_BLOCKING_MODULES[base] is None:
                return f"{base}.{parts[-1]}(...)"
    if rec.kind == "dynamic" and len(parts) >= 2 and \
            parts[-1] in config.VL502_BLOCKING_METHODS:
        return f".{parts[-1]}(...) on an untyped handle"
    return None


def _check_transitive_blocking(contexts: list[FileContext]):
    a = callgraph.analysis_for(contexts)
    for qual in sorted(a.reachable("search")):
        fn = a.funcs[qual]
        path = _norm(fn.ctx.path)
        sync_exempt = any(pkg in path
                          for pkg in config.VL502_SYNC_EXEMPT_PACKAGES)
        chain = a.render_chain(qual, "search")
        for rec in fn.calls:
            hit, tags = None, _IO_TAGS
            if not sync_exempt:
                hit = _sync_hit(rec)
                if hit:
                    tags = _SYNC_TAGS
            if hit is None:
                hit = _io_hit(rec, fn, a)
            if hit is None:
                continue
            ok, reason = _allowed_at(fn.ctx, fn, rec.line, tags)
            yield Finding(
                "VL502", "serving-blocking", fn.ctx.path, rec.line,
                f"`{hit}` blocks the request thread on a search "
                f"serving path: {chain} — justify at this frame or "
                "hoist off the request thread",
                suppressed=ok, reason=reason,
            )
        # known mmap page-fault gather frames (subscript gathers the
        # resolver cannot see as calls)
        for suffix, qn in config.VL502_PAGEFAULT_FUNCS:
            if path.endswith(suffix) and fn.qualname == qn:
                ok, reason = _allowed_at(
                    fn.ctx, fn, fn.node.lineno, _IO_TAGS)
                yield Finding(
                    "VL502", "serving-blocking", fn.ctx.path,
                    fn.node.lineno,
                    f"mmap page-fault gather frame `{qn}` on a search "
                    f"serving path: {chain} — justify the fault cost "
                    "at this frame (readahead/cache mitigation) or "
                    "hoist",
                    suppressed=ok, reason=reason,
                )


# -- VL503 --------------------------------------------------------------------

def _check_lock_cycles(contexts: list[FileContext]):
    a = callgraph.analysis_for(contexts)
    for cycle in a.lock_cycles:
        members = set(cycle)
        site_path, site_line = "<lock-graph>", 0
        for (x, y), site in sorted(a.lock_edges.items()):
            if x in members and y in members:
                site_path, _, line = site.rpartition(":")
                site_line = int(line)
                break
        yield Finding(
            "VL503", "lock-order", site_path, site_line,
            "static lock-order cycle: " + " -> ".join(
                cycle + [cycle[0]]) + " — a schedule interleaving "
            "these acquisitions deadlocks; break the cycle or impose "
            "a total order",
        )


# -- VL504 --------------------------------------------------------------------

def _is_boundary(rec) -> bool:
    if any(t.endswith(s) for t in rec.targets
           for s in config.VL504_BOUNDARY_SUFFIXES):
        return True
    d = rec.dotted or ""
    return any(d == b or d.endswith("." + b)
               for b in config.VL504_BOUNDARY_DOTTED)


def _threads_deadline(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg in config.VL504_DEADLINE_KWARGS:
            return True
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Dict):
            for k in arg.keys:
                if isinstance(k, ast.Constant) and \
                        k.value == config.VL504_BODY_DEADLINE_KEY:
                    return True
    return False


def _check_deadline_propagation(contexts: list[FileContext]):
    a = callgraph.analysis_for(contexts)
    for qual in sorted(a.reachable("search")):
        fn = a.funcs[qual]
        chain = a.render_chain(qual, "search")
        for rec in fn.calls:
            if not _is_boundary(rec) or rec.node is None:
                continue
            if _threads_deadline(rec.node):
                continue
            ok, reason = _allowed_at(
                fn.ctx, fn, rec.line, ("deadline",))
            yield Finding(
                "VL504", "deadline", fn.ctx.path, rec.line,
                f"RPC boundary `{rec.dotted}` on a search serving "
                f"path drops the request deadline: {chain} — pass "
                "timeout= from the armed RequestContext or carry "
                "deadline_ms in the body, or the 499 kill machinery "
                "cannot bound this call",
                suppressed=ok, reason=reason,
            )


register(Rule(
    id="VL501", tag="transitive-dispatch",
    doc="no dispatch constructs reachable from serving entry points "
        "outside the device layers (interprocedural VL101)",
    check_project=_check_transitive_dispatch,
))

register(Rule(
    id="VL502", tag="serving-blocking",
    doc="no unjustified host-sync/blocking-I/O reachable from search "
        "handlers; reported with the full call chain",
    check_project=_check_transitive_blocking,
))

register(Rule(
    id="VL503", tag="lock-order",
    doc="static with-lock acquisition graph must be cycle-free "
        "(artifact: lint --lock-graph)",
    check_project=_check_lock_cycles,
))

register(Rule(
    id="VL504", tag="deadline",
    doc="serving-path RPC boundaries must thread the request "
        "deadline (timeout= or body deadline_ms)",
    check_project=_check_deadline_propagation,
))

"""vearch-lint core: rule registry, file contexts, suppression.

The analyzer turns the project's prose invariants (ROADMAP, PERF.md,
OBSERVABILITY.md, review feedback) into machine-checked properties of
every future PR. It is deliberately dependency-free: stdlib `ast` over
the package tree, one process, no plugins.

Suppression model (both forms REQUIRE a reason — a bare waiver is
itself a finding):

- inline, for a single line::

      t = time.time()  # lint: allow[wall-clock] span epochs correlate with OTLP

  The pragma may also sit alone on the line directly above the
  flagged line. A pragma on a ``def`` line exempts the whole function
  for that rule (used for construction-time helpers).

- file-scoped, in the checked-in allowlist (one entry per line)::

      VL101 vearch_tpu/parallel/sharded.py  device-parallel layer owns its dispatches

  Entries match by path suffix. Unused entries are reported as
  findings so the allowlist can only shrink or stay honest.

A function whose body runs entirely under a lock taken by every caller
declares it with ``# lint: holds[_lock]`` on its ``def`` line; the
static lock rule then treats the lock as held inside (the runtime
lockcheck layer verifies the claim when VEARCH_LOCKCHECK=1).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable

__all__ = [
    "Finding",
    "FileContext",
    "Rule",
    "Allowlist",
    "run_paths",
    "iter_py_files",
    "RULES",
    "register",
]

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\[([a-z0-9_-]+)\]\s*(.*)")
_HOLDS_RE = re.compile(r"#\s*lint:\s*holds\[([A-Za-z0-9_.,\s]+)\]")


@dataclass
class Finding:
    rule: str  # rule id, e.g. "VL203"
    tag: str  # pragma tag, e.g. "wall-clock"
    path: str  # path as given to the runner
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        sup = f"  [allowed: {self.reason}]" if self.suppressed else ""
        return f"{loc}: {self.rule}[{self.tag}] {self.message}{sup}"


@dataclass
class Rule:
    id: str
    tag: str
    doc: str
    # per-file rules get a FileContext; project rules get the list of
    # FileContexts (after every file parsed) for cross-file invariants
    check_file: Callable[["FileContext"], Iterable[Finding]] | None = None
    check_project: Callable[[list["FileContext"]], Iterable[Finding]] | None = None


RULES: list[Rule] = []


def register(rule: Rule) -> Rule:
    RULES.append(rule)
    return rule


class FileContext:
    """One parsed source file plus per-line pragma information."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> (tag, reason) inline allow pragmas
        self.allows: dict[int, tuple[str, str]] = {}
        # def-lines carrying a holds[] pragma: line -> set of lock names
        self.holds: dict[int, set[str]] = {}
        self.pragma_findings: list[Finding] = []
        for i, text in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(text)
            if m:
                tag, reason = m.group(1), m.group(2).strip()
                if not reason:
                    self.pragma_findings.append(Finding(
                        "VL000", "pragma", path, i,
                        f"allow[{tag}] pragma has no reason — every "
                        "waiver must say why",
                    ))
                self.allows[i] = (tag, reason)
            m = _HOLDS_RE.search(text)
            if m:
                names = {n.strip().lstrip("self.").strip()
                         for n in m.group(1).split(",")}
                self.holds[i] = {n for n in names if n}
        # parent links (ast doesn't keep them) for lexical-scope walks
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # -- pragma lookups ------------------------------------------------------

    def allowed(self, line: int, tag: str) -> tuple[bool, str]:
        """Inline suppression for (line, tag): same line, or a pragma
        alone on the line above."""
        hit = self.allows.get(line)
        if hit and hit[0] == tag:
            return True, hit[1]
        above = self.allows.get(line - 1)
        if above and above[0] == tag:
            text = self.lines[line - 2].strip() if line >= 2 else ""
            if text.startswith("#"):
                return True, above[1]
        return False, ""

    def func_allowed(self, func: ast.AST, tag: str) -> tuple[bool, str]:
        """allow[] pragma on the def line exempts the whole function."""
        line = getattr(func, "lineno", 0)
        hit = self.allows.get(line)
        if hit and hit[0] == tag:
            return True, hit[1]
        return False, ""

    def func_holds(self, func: ast.AST) -> set[str]:
        line = getattr(func, "lineno", 0)
        return self.holds.get(line, set())

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


class Allowlist:
    """Checked-in, reason-carrying suppression file."""

    def __init__(self, path: str | None):
        self.path = path
        self.entries: list[tuple[str, str, str]] = []  # (rule, suffix, reason)
        self.used: set[int] = set()
        self.findings: list[Finding] = []
        if path and os.path.exists(path):
            for i, raw in enumerate(open(path), start=1):
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(None, 2)
                if len(parts) < 3:
                    self.findings.append(Finding(
                        "VL000", "pragma", path, i,
                        "allowlist entry needs `RULE path reason`; a "
                        "reasonless waiver is not accepted",
                    ))
                    continue
                self.entries.append((parts[0], parts[1], parts[2]))

    def match(self, f: Finding) -> tuple[bool, str]:
        norm = f.path.replace(os.sep, "/")
        for i, (rule, suffix, reason) in enumerate(self.entries):
            if rule == f.rule and norm.endswith(suffix):
                self.used.add(i)
                return True, reason
        return False, ""

    def unused_findings(self) -> list[Finding]:
        out = []
        for i, (rule, suffix, reason) in enumerate(self.entries):
            if i not in self.used:
                out.append(Finding(
                    "VL000", "pragma", self.path or "<allowlist>", 0,
                    f"unused allowlist entry: {rule} {suffix} ({reason}) "
                    "— delete it",
                ))
        return out


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield os.path.join(root, fn)


def run_paths(
    paths: Iterable[str],
    allowlist: Allowlist | None = None,
    rules: Iterable[Rule] | None = None,
) -> list[Finding]:
    """Run every rule over every file; returns ALL findings, with
    suppressed ones marked (callers filter on `.suppressed`)."""
    # import for side effect: rule registration
    from vearch_tpu.tools.lint import (  # noqa: F401
        rules_accounting, rules_buckets, rules_dispatch, rules_errors,
        rules_interproc, rules_locks, rules_obs, rules_quality,
    )

    active = list(rules) if rules is not None else list(RULES)
    contexts: list[FileContext] = []
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                ctx = FileContext(path, f.read())
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "VL001", "parse", path, getattr(e, "lineno", 0) or 0,
                f"unparseable: {e}"))
            continue
        contexts.append(ctx)
        findings.extend(ctx.pragma_findings)
        for rule in active:
            if rule.check_file is not None:
                findings.extend(rule.check_file(ctx))
    for rule in active:
        if rule.check_project is not None:
            findings.extend(rule.check_project(contexts))
    if allowlist is not None:
        for f in findings:
            if f.suppressed:
                continue
            ok, reason = allowlist.match(f)
            if ok:
                f.suppressed, f.reason = True, reason
        findings.extend(allowlist.unused_findings())
    return findings

"""Search-quality staleness discipline.

VL105 — index-mutating paths must call the quality staleness hook.
The shadow recall sampler (obs/quality.py, docs/QUALITY.md) queues
served results and later scores them against fresh exact ground truth.
A function in the quality-wired files (`tools/lint/config.py:
VL105_QUALITY_FILES`) that calls an index mutator — an attribute call
named in `VL105_INDEX_MUTATORS`, i.e. an engine build/rebuild that
replaces the serving snapshot wholesale — without also calling the
monitor's `note_index_mutation` hook leaves the estimators comparing
fresh truth against pre-mutation serving behaviour: the recall gauge
reports phantom loss (or worse, hides a real one behind a reset that
never happened).

Doc-level writes (upsert/delete through the replicated log) are out of
scope: every queued shadow job pins the engine `data_version` it was
served at and is dropped as `stale` if the corpus moved — the hook is
for *structural* replacement, where the version bump alone cannot say
"the quantizers changed too". A genuinely estimator-neutral mutator
call carries an inline ``allow[quality-staleness]`` with the reason.
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _called_attrs(func: ast.AST) -> set[str]:
    """Attribute names invoked anywhere in the function body
    (`eng.build_index()` -> `build_index`), plus bare call names."""
    out: set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute):
            out.add(node.func.attr)
        elif isinstance(node.func, ast.Name):
            out.add(node.func.id)
    return out


def _check_quality_staleness(ctx: FileContext):
    path = _norm(ctx.path)
    if not path.endswith(tuple(config.VL105_QUALITY_FILES)):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        called = _called_attrs(node)
        mutators = sorted(
            m for m in config.VL105_INDEX_MUTATORS if m in called
        )
        if not mutators or config.VL105_STALENESS_HOOK in called:
            continue
        ok, reason = ctx.func_allowed(node, "quality-staleness")
        yield Finding(
            "VL105", "quality-staleness", ctx.path, node.lineno,
            f"`{node.name}` calls {', '.join(mutators)} but never "
            f"calls {config.VL105_STALENESS_HOOK}() — the shadow "
            "recall estimators will score fresh ground truth against "
            "the pre-mutation serving snapshot (docs/QUALITY.md)",
            suppressed=ok, reason=reason,
        )


register(Rule(
    id="VL105", tag="quality-staleness",
    doc="index-mutating paths must call the quality staleness hook",
    check_file=_check_quality_staleness,
))

"""Whole-program call graph for the interprocedural lint rules.

Built once per lint run from the SAME parsed ``FileContext`` list the
lexical rules use (the package is parsed exactly once; see
``run_paths``). The graph gives the VL5xx family three things:

1. **Reachability** from the declared serving entry points
   (``config.INTERPROC_ENTRY_POINTS``) with the discovery chain kept,
   so a finding three helpers deep is reported with the full call path
   from the handler that makes it hot.
2. **Call resolution** with an explicit honesty ledger: every call
   site is classified ``resolved`` (precise target), ``fanout``
   (dynamic receiver, matched by method name across the project),
   ``external`` (known non-project module), or ``dynamic`` (we cannot
   say — the *unresolved bucket*). Rules treat the unresolved bucket
   conservatively instead of pretending it is empty.
3. **The static lock-order graph**: every ``with self._lock`` nesting,
   explicit ``.acquire()`` on a minted lock, and lock acquired
   *transitively* by a callee while another lock is held becomes a
   directed edge; cycles are deadlocks-in-waiting (VL503) and the
   edge set is the artifact the stress suite diffs runtime lockcheck
   edges against.

Resolution strategy (documented blind spots in STATIC_ANALYSIS.md):

- ``self.m()``       -> method lookup with a DFS MRO over parsed bases
- ``self.attr.m()``  -> type of ``attr`` inferred from
                        ``self.attr = Class(...)`` assignments or a
                        ``self.attr: dict[K, Class]`` annotation
                        (containers type as their VALUE class, so
                        ``self.nodes[pid].m()`` resolves too)
- ``mod.f()``        -> module alias / from-import tables per module
- ``var.m()``        -> flow-insensitive ``var = Class(...)`` typing,
                        plus return-annotation typing: ``var =
                        self._node(pid)`` types ``var`` when the
                        resolved callee is annotated ``-> Class``
- ``self.cb(...)``   -> constructor-injected callbacks: when every
                        observed binding site (``Class(..., cb=X)`` or
                        ``obj.cb = X``) passes a resolvable function,
                        lambda, or closure-returning call, the dynamic
                        ``self.cb(...)`` invocation resolves to those
                        targets (raft's ``apply_fn``/``observer``/
                        ``snapshot_fn`` pattern)
- anything else      -> name fan-out over every parsed class method of
                        that name, unless the name is in
                        ``config.FANOUT_STOPLIST`` (ubiquitous names
                        whose fan-out would connect everything), in
                        which case the call lands in the unresolved
                        bucket.

Nested ``def``s (closures handed to observers, hedges, executors) are
NOT scanned as part of their parent's body; instead a reachable parent
makes its nested defs reachable ("closure rule"), so the offending
frame in a report is the closure itself, where a pragma can sit.
"""

from __future__ import annotations

import ast
import builtins as _builtins
from dataclasses import dataclass, field

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext

__all__ = ["Analysis", "build", "analysis_for", "edge_covered"]

_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef)


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _module_name(path: str) -> str:
    parts = _norm(path)[:-3].split("/") if path.endswith(".py") \
        else _norm(path).split("/")
    if "vearch_tpu" in parts:
        parts = parts[parts.index("vearch_tpu"):]
    else:
        parts = parts[-1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _dotted_thru_subscript(node: ast.AST) -> str | None:
    """Dotted chain with subscripts elided: `self.nodes[pid].close`
    -> "self.nodes.close". Only returns a value when a subscript was
    actually present (plain chains take the exact `_dotted` path)."""
    parts: list[str] = []
    seen_sub = False
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            seen_sub = True
            cur = cur.value
        else:
            break
    if seen_sub and isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _expr_walk(node: ast.AST):
    """ast.walk that does not descend into nested function/class
    definitions (their bodies belong to other graph nodes)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _FUNC + (ast.ClassDef,)):
                continue
            stack.append(child)


# -- graph node types ---------------------------------------------------------

@dataclass
class LockNode:
    """A statically-identified lock. `match` ties it to runtime
    lockcheck names: literal (exact make_lock string), prefix
    (f-string with a constant head), any (name passed as a parameter),
    none (a plain threading primitive lockcheck never sees)."""
    id: str
    match: str = "none"  # literal | prefix | any | none
    name: str = ""

    def matches(self, runtime_name: str) -> bool:
        if self.match == "literal":
            return runtime_name == self.name
        if self.match == "prefix":
            return runtime_name.startswith(self.name)
        return self.match == "any"


@dataclass
class CallRec:
    line: int
    dotted: str | None
    targets: tuple[str, ...]
    kind: str  # resolved | callback | fanout | external | dynamic
    # "callback": resolved through a binding pass (ctor-injected attr
    # or function-valued param). Lock-graph edges treat it as resolved
    # (the invocation frame is where ordering happens); reachability
    # SKIPS it — the binding call site already contributes a
    # context-correct deferred edge, and a global union here would
    # launder one entry's callbacks onto another entry's path.
    node: ast.Call = None


@dataclass
class FuncInfo:
    qual: str          # "module:Class.method" — globally unique
    module: str
    qualname: str      # "Class.method" / "func" / "outer.inner"
    name: str
    cls: str | None    # owning class key, if a method
    node: ast.AST
    ctx: FileContext
    nested: list[str] = field(default_factory=list)
    calls: list[CallRec] = field(default_factory=list)
    unresolved: list[tuple[str, int]] = field(default_factory=list)
    local_types: dict[str, set[str]] = field(default_factory=dict)
    # local name -> self attr it aliases (`obs = self.observer`)
    attr_aliases: dict[str, str] = field(default_factory=dict)
    # param name -> quals call sites pass for it (`resolve(.., fetch)`)
    param_callbacks: dict[str, set[str]] = field(default_factory=dict)
    # local name -> quals it holds (`fetch = self._make_fetch(...)`)
    local_callbacks: dict[str, set[str]] = field(default_factory=dict)
    lock_vars: dict[str, list[LockNode]] = field(default_factory=dict)
    direct_locks: set[str] = field(default_factory=set)  # LockNode ids


@dataclass
class ClassInfo:
    key: str           # "module:ClassName"
    module: str
    name: str
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)   # raw dotted names
    methods: dict[str, str] = field(default_factory=dict)  # name -> qual
    attr_types: dict[str, set[str]] = field(default_factory=dict)
    attr_locks: dict[str, LockNode] = field(default_factory=dict)
    cond_alias: dict[str, str] = field(default_factory=dict)
    minted: dict[str, LockNode] = field(default_factory=dict)
    # __init__ param name -> attr it is stored into (`self._observer =
    # observer`): lets constructor call sites bind callback targets
    param_attrs: dict[str, str] = field(default_factory=dict)
    # attr name -> quals every observed binding site passes for it
    callback_targets: dict[str, set[str]] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str
    ctx: FileContext
    funcs: dict[str, str] = field(default_factory=dict)    # name -> qual
    classes: dict[str, str] = field(default_factory=dict)  # name -> key
    mod_alias: dict[str, str] = field(default_factory=dict)
    from_bind: dict[str, tuple[str, str]] = field(default_factory=dict)
    # name -> ("module", dotted) | ("func", qual) | ("class", key)
    locks: dict[str, LockNode] = field(default_factory=dict)


class Analysis:
    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.method_index: dict[str, list[str]] = {}
        self.entries: list[tuple[str, str]] = []  # (qual, kind)
        # kind -> {qual: (parent_qual | None, call line in parent)}
        self.reach: dict[str, dict[str, tuple[str | None, int]]] = {}
        self.lock_nodes: dict[str, LockNode] = {}
        # (first_id, then_id) -> "path:line" of the inner acquisition
        self.lock_edges: dict[tuple[str, str], str] = {}
        self.lock_cycles: list[list[str]] = []
        # transitive acquire sets per function qual (LockNode ids)
        self.acq: dict[str, set[str]] = {}

    # -- queries -------------------------------------------------------------

    def reachable(self, kind: str) -> set[str]:
        return set(self.reach.get(kind, ()))

    def chain(self, qual: str, kind: str) -> list[str]:
        """Entry-to-qual call chain as recorded at first discovery."""
        parents = self.reach.get(kind, {})
        out, cur = [], qual
        while cur is not None and cur in parents and len(out) < 64:
            out.append(cur)
            cur = parents[cur][0]
        return list(reversed(out))

    def render_chain(self, qual: str, kind: str) -> str:
        names = [q.split(":", 1)[1] for q in self.chain(qual, kind)]
        return " -> ".join(names) if names else qual

    def lock_graph_artifact(self) -> dict:
        """Machine-readable lock graph (`lint --lock-graph`); the
        stress suite asserts runtime lockcheck edges are covered."""
        return {
            "nodes": [
                {"id": n.id, "match": n.match, "name": n.name}
                for n in sorted(self.lock_nodes.values(),
                                key=lambda n: n.id)
            ],
            "edges": [
                {"first": a, "then": b, "site": site}
                for (a, b), site in sorted(self.lock_edges.items())
            ],
            "cycles": self.lock_cycles,
        }

    def edge_covered(self, first_name: str, then_name: str) -> bool:
        for (a, b) in self.lock_edges:
            na, nb = self.lock_nodes[a], self.lock_nodes[b]
            if na.matches(first_name) and nb.matches(then_name):
                return True
        return False


def edge_covered(artifact: dict, first_name: str, then_name: str) -> bool:
    """Same coverage test against the serialized artifact."""
    nodes = {n["id"]: n for n in artifact["nodes"]}

    def _m(nid: str, runtime: str) -> bool:
        n = nodes[nid]
        if n["match"] == "literal":
            return runtime == n["name"]
        if n["match"] == "prefix":
            return runtime.startswith(n["name"])
        return n["match"] == "any"

    return any(_m(e["first"], first_name) and _m(e["then"], then_name)
               for e in artifact["edges"])


# -- builder ------------------------------------------------------------------

class _Builder:
    def __init__(self, contexts: list[FileContext]):
        self.a = Analysis()
        self.contexts = contexts

    # .. indexing ............................................................

    def build(self) -> Analysis:
        for ctx in self.contexts:
            self._index_module(ctx)
        for cls in self.a.classes.values():
            self._collect_attrs(cls)
        # local typing must precede the callback pass (binding sites
        # like `node.wal.observer = ...` need the receiver's type), and
        # both must precede the call-record walk so dynamic
        # `self.cb(...)` sites resolve against bound targets
        for fn in self.a.funcs.values():
            self._local_types(fn, self.a.modules[fn.module])
        # fixpoint: a callback forwarded through a call chain
        # (`resolve(fetch)` -> `_resolve_locked(fetch)` -> `_upload`)
        # binds one hop per pass
        for _ in range(4):
            before = self._binding_count()
            for fn in self.a.funcs.values():
                self._collect_callbacks(fn)
            if self._binding_count() == before:
                break
        for fn in self.a.funcs.values():
            self._scan_function(fn)
        self._find_entries()
        self._reachability()
        self._lock_graph()
        return self.a

    def _index_module(self, ctx: FileContext) -> None:
        mod = ModuleInfo(_module_name(ctx.path), ctx)
        # last parse wins on duplicate module names (fixture trees)
        self.a.modules[mod.name] = mod
        self._collect_imports(mod, ctx.tree)
        for stmt in ctx.tree.body:
            if isinstance(stmt, _FUNC):
                self._index_func(mod, stmt, prefix="", cls=None)
            elif isinstance(stmt, ast.ClassDef):
                self._index_class(mod, stmt)
            elif isinstance(stmt, ast.Assign):
                spec = self._make_lock_spec(stmt.value)
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and spec is not None:
                        node = self._lock_node(
                            f"{mod.name}:{t.id}", spec)
                        mod.locks[t.id] = node

    def _index_func(self, mod: ModuleInfo, node: ast.AST, prefix: str,
                    cls: str | None) -> FuncInfo:
        qualname = f"{prefix}{node.name}"
        qual = f"{mod.name}:{qualname}"
        fn = FuncInfo(qual, mod.name, qualname, node.name, cls, node,
                      mod.ctx)
        self.a.funcs[qual] = fn
        if not prefix:
            mod.funcs[node.name] = qual
        for child in ast.walk(node):
            if isinstance(child, _FUNC) and child is not node and \
                    self._direct_parent_func(mod.ctx, child) is node:
                sub = self._index_func(
                    mod, child, prefix=f"{qualname}.", cls=cls)
                fn.nested.append(sub.qual)
        return fn

    @staticmethod
    def _direct_parent_func(ctx: FileContext, node: ast.AST) -> ast.AST | None:
        cur = ctx.parents.get(node)
        while cur is not None and not isinstance(cur, _FUNC):
            if isinstance(cur, ast.ClassDef):
                return None
            cur = ctx.parents.get(cur)
        return cur

    def _index_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        key = f"{mod.name}:{node.name}"
        ci = ClassInfo(key, mod.name, node.name, node)
        self.a.classes[key] = ci
        mod.classes[node.name] = key
        for b in node.bases:
            d = _dotted(b)
            if d:
                ci.bases.append(d)
        # leaf layers (the SDK client sits ABOVE the cluster, never
        # below the engine) are excluded from name fan-out, or their
        # same-named methods (search/upsert) would pull a client
        # round-trip onto the server's own serving path
        fanout_ok = not any(
            pkg in _norm(mod.ctx.path)
            for pkg in config.INTERPROC_FANOUT_EXCLUDE)
        for stmt in node.body:
            if isinstance(stmt, _FUNC):
                fn = self._index_func(
                    mod, stmt, prefix=f"{node.name}.", cls=key)
                ci.methods[stmt.name] = fn.qual
                if fanout_ok:
                    self.a.method_index.setdefault(
                        stmt.name, []).append(fn.qual)

    def _collect_imports(self, mod: ModuleInfo, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    top = al.name.split(".")[0]
                    mod.mod_alias[al.asname or top] = \
                        al.name if al.asname else top
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = mod.name.split(".")
                    parts = parts[:len(parts) - node.level] if \
                        len(parts) >= node.level else []
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for al in node.names:
                    name = al.asname or al.name
                    mod.from_bind[name] = ("pending", f"{base}.{al.name}"
                                           if base else al.name)

    # .. lock + type extraction ..............................................

    def _lock_node(self, nid: str, spec: tuple[str, str]) -> LockNode:
        node = self.a.lock_nodes.get(nid)
        if node is None:
            node = LockNode(nid, spec[0], spec[1])
            self.a.lock_nodes[nid] = node
        elif node.match == "none" and spec[0] != "none":
            node.match, node.name = spec
        return node

    @staticmethod
    def _make_lock_spec(expr: ast.AST) -> tuple[str, str] | None:
        """("literal"|"prefix"|"any"|"none", name) when expr mints a
        lock-like object anywhere inside; None otherwise."""
        for node in _expr_walk(expr):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            last = d.split(".")[-1]
            if last == "make_lock":
                if not node.args:
                    return ("any", "")
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    return ("literal", arg.value)
                if isinstance(arg, ast.JoinedStr):
                    head = ""
                    for part in arg.values:
                        if isinstance(part, ast.Constant) and \
                                isinstance(part.value, str):
                            head += part.value
                        else:
                            break
                    return ("prefix", head) if head else ("any", "")
                return ("any", "")
            if last in ("Lock", "RLock", "Semaphore", "BoundedSemaphore") \
                    and d.split(".")[0] in ("threading", "_threading"):
                return ("none", "")
        return None

    def _collect_attrs(self, ci: ClassInfo) -> None:
        mod = self.a.modules[ci.module]
        init_params = self._init_params(ci)
        for stmt in ast.walk(ci.node):
            if isinstance(stmt, ast.Assign):
                # chained targets (`mb = self._mb = Cls(...)`) record
                # the attr type too
                for t in stmt.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self._record_attr(ci, mod, t.attr, stmt.value)
                        if isinstance(stmt.value, ast.Name) and \
                                stmt.value.id in init_params:
                            ci.param_attrs[stmt.value.id] = t.attr
            elif isinstance(stmt, ast.AnnAssign):
                t = stmt.target
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    # `self.nodes: dict[int, RaftNode] = {}` — the
                    # annotation types the attr (containers type as
                    # their value class)
                    keys = self._annotation_keys(mod, stmt.annotation)
                    if keys:
                        ci.attr_types.setdefault(t.attr, set()) \
                            .update(keys)
                    if stmt.value is not None:
                        self._record_attr(ci, mod, t.attr, stmt.value)
            elif isinstance(stmt, ast.Call) and \
                    isinstance(stmt.func, ast.Attribute) and \
                    stmt.func.attr == "setdefault":
                base = stmt.func.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self" and len(stmt.args) == 2:
                    spec = self._make_lock_spec(stmt.args[1])
                    if spec is not None:
                        ci.attr_locks[base.attr] = self._lock_node(
                            f"{ci.name}.{base.attr}", spec)
        # methods that mint a lock (e.g. `_flush_lock(pid)` returning a
        # per-pid DebugLock): `with self._flush_lock(pid):` resolves
        # through them
        for mname, mqual in ci.methods.items():
            fnode = self.a.funcs[mqual].node
            spec = None
            for stmt in fnode.body:
                spec = spec or self._make_lock_spec(stmt)
            if spec is not None:
                # reuse the backing-attr node when the mint flows into
                # one (setdefault into self._flush_locks)
                backing = None
                for stmt in ast.walk(fnode):
                    if isinstance(stmt, ast.Call) and \
                            isinstance(stmt.func, ast.Attribute) and \
                            stmt.func.attr == "setdefault":
                        b = stmt.func.value
                        if isinstance(b, ast.Attribute) and \
                                isinstance(b.value, ast.Name) and \
                                b.value.id == "self":
                            backing = ci.attr_locks.get(b.attr)
                ci.minted[mname] = backing or self._lock_node(
                    f"{ci.name}.{mname}()", spec)

    def _record_attr(self, ci: ClassInfo, mod: ModuleInfo, attr: str,
                     value: ast.AST) -> None:
        spec = self._make_lock_spec(value)
        if spec is not None:
            ci.attr_locks[attr] = self._lock_node(
                f"{ci.name}.{attr}", spec)
            return
        if isinstance(value, ast.Call):
            d = _dotted(value.func) or ""
            if d.split(".")[-1] == "Condition":
                if value.args:
                    arg = value.args[0]
                    if isinstance(arg, ast.Attribute) and \
                            isinstance(arg.value, ast.Name) and \
                            arg.value.id == "self":
                        ci.cond_alias[attr] = arg.attr
                        return
                ci.attr_locks[attr] = self._lock_node(
                    f"{ci.name}.{attr}", ("none", ""))
                return
            keys = self._class_keys_of_call(mod, d)
            if keys:
                ci.attr_types.setdefault(attr, set()).update(keys)

    def _init_params(self, ci: ClassInfo) -> set[str]:
        qual = ci.methods.get("__init__")
        if qual is None:
            return set()
        args = self.a.funcs[qual].node.args
        names = [a.arg for a in args.posonlyargs + args.args +
                 args.kwonlyargs]
        return set(names[1:]) if names[:1] == ["self"] else set(names)

    def _annotation_keys(self, mod: ModuleInfo, ann: ast.AST) \
            -> set[str]:
        """Class keys a type annotation names. Containers (`dict[K, V]`,
        `list[T]`, `Optional[T]`) type as their LAST parameter — the
        element/value position — so subscripted reads type correctly."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return set()
        if isinstance(ann, (ast.Name, ast.Attribute)):
            return self._class_keys_of_call(mod, _dotted(ann) or "")
        if isinstance(ann, ast.Subscript):
            sl = ann.slice
            elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            return self._annotation_keys(mod, elts[-1]) if elts else set()
        if isinstance(ann, ast.BinOp):  # PEP 604: `RaftNode | None`
            return self._annotation_keys(mod, ann.left) | \
                self._annotation_keys(mod, ann.right)
        return set()

    def _class_keys_of_call(self, mod: ModuleInfo, dotted: str) \
            -> set[str]:
        """Class keys a `Name(...)`/`mod.Name(...)` call constructs."""
        if not dotted:
            return set()
        parts = dotted.split(".")
        if len(parts) == 1:
            key = mod.classes.get(parts[0])
            if key:
                return {key}
            bind = self._resolve_from_bind(mod, parts[0])
            if bind and bind[0] == "class":
                return {bind[1]}
            return set()
        tmod = self._module_of_prefix(mod, parts[:-1])
        if tmod is not None:
            key = tmod.classes.get(parts[-1])
            if key:
                return {key}
        return set()

    def _resolve_from_bind(self, mod: ModuleInfo, name: str) \
            -> tuple[str, str] | None:
        """from-import binding -> ("module", dotted) | ("func", qual)
        | ("class", key) | ("external", dotted)."""
        bind = mod.from_bind.get(name)
        if bind is None:
            return None
        kind, target = bind
        if kind != "pending":
            return bind
        # sentinel first: re-export chasing below can revisit this
        # binding on an import cycle; the sentinel makes that a benign
        # "external" instead of infinite recursion
        mod.from_bind[name] = ("external", target)
        if target in self.a.modules:
            out = ("module", target)
        else:
            head, _, member = target.rpartition(".")
            src = self.a.modules.get(head)
            if src is not None and member in src.classes:
                out = ("class", src.classes[member])
            elif src is not None and member in src.funcs:
                out = ("func", src.funcs[member])
            elif src is not None and member in src.from_bind:
                # package __init__ re-export:
                # `from vearch_tpu.tiering import HostRamSlabTier`
                # where tiering/__init__.py itself imports it
                out = self._resolve_from_bind(src, member) or \
                    ("external", target)
            else:
                out = ("external", target)
        mod.from_bind[name] = out
        return out

    def _module_of_prefix(self, mod: ModuleInfo, parts: list[str]) \
            -> ModuleInfo | None:
        """Module named by an attribute prefix like ["rpc"] or
        ["vearch_tpu", "cluster", "rpc"]."""
        if not parts:
            return None
        head = parts[0]
        bind = self._resolve_from_bind(mod, head)
        if bind and bind[0] == "module":
            full = ".".join([bind[1]] + parts[1:])
        elif head in mod.mod_alias:
            full = ".".join([mod.mod_alias[head]] + parts[1:])
        else:
            full = ".".join(parts)
        return self.a.modules.get(full)

    def _method_lookup(self, key: str, name: str, seen=None) \
            -> str | None:
        seen = seen or set()
        if key in seen:
            return None
        seen.add(key)
        ci = self.a.classes.get(key)
        if ci is None:
            return None
        if name in ci.methods:
            return ci.methods[name]
        mod = self.a.modules[ci.module]
        for b in ci.bases:
            bkeys = self._class_keys_of_call(mod, b)
            for bk in bkeys:
                hit = self._method_lookup(bk, name, seen)
                if hit:
                    return hit
        return None

    def _lock_attr_lookup(self, key: str, attr: str, seen=None) \
            -> LockNode | None:
        seen = seen or set()
        if key in seen:
            return None
        seen.add(key)
        ci = self.a.classes.get(key)
        if ci is None:
            return None
        if attr in ci.cond_alias:
            return self._lock_attr_lookup(key, ci.cond_alias[attr])
        if attr in ci.attr_locks:
            return ci.attr_locks[attr]
        mod = self.a.modules[ci.module]
        for b in ci.bases:
            for bk in self._class_keys_of_call(mod, b):
                hit = self._lock_attr_lookup(bk, attr, seen)
                if hit:
                    return hit
        return None

    # .. per-function scan ...................................................

    def _scan_function(self, fn: FuncInfo) -> None:
        mod = self.a.modules[fn.module]
        walker = _FuncWalker(self, fn, mod)
        walker.run()

    def _local_types(self, fn: FuncInfo, mod: ModuleInfo) -> None:
        fargs = fn.node.args
        for a in fargs.posonlyargs + fargs.args + fargs.kwonlyargs:
            if a.annotation is not None:
                keys = self._annotation_keys(mod, a.annotation)
                if keys:
                    fn.local_types.setdefault(a.arg, set()).update(keys)
        for stmt in self._own_statements(fn.node):
            if not isinstance(stmt, ast.Assign):
                continue
            names = [t for t in stmt.targets if isinstance(t, ast.Name)]
            if not names:
                continue
            t = names[0]
            v = stmt.value
            spec = self._make_lock_spec(v)
            if spec is not None:
                for t in names:
                    fn.lock_vars.setdefault(t.id, []).append(
                        self._lock_node(f"{fn.qual}:{t.id}", spec))
                continue
            if isinstance(v, ast.Call):
                keys = self._class_keys_of_call(mod, _dotted(v.func) or "")
                if not keys and isinstance(v.func, ast.Attribute) and \
                        v.func.attr in ("get", "pop", "setdefault"):
                    # element access on a typed container:
                    # `node = self.raft_nodes.pop(pid, None)` types the
                    # local as the dict's value class
                    keys = self._expr_class_keys(fn, mod, v.func.value)
                if not keys:
                    # `node = self._node(pid)` with `_node -> RaftNode`:
                    # type the local from the callee's return annotation
                    targets, kind = self.resolve_call(fn, v)
                    if kind == "resolved":
                        for tq in targets:
                            tfn = self.a.funcs.get(tq)
                            ret = getattr(tfn.node, "returns", None) \
                                if tfn else None
                            if ret is not None:
                                keys |= self._annotation_keys(
                                    self.a.modules[tfn.module], ret)
                if keys:
                    for t in names:
                        fn.local_types.setdefault(t.id, set()) \
                            .update(keys)
            elif isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "self" \
                    and fn.cls:
                ci = self.a.classes[fn.cls]
                keys = ci.attr_types.get(v.attr)
                for t in names:
                    fn.attr_aliases[t.id] = v.attr
                    if keys:
                        fn.local_types.setdefault(t.id, set()) \
                            .update(keys)

    def _own_statements(self, node: ast.AST):
        for child in _expr_walk(node):
            if isinstance(child, ast.stmt) and child is not node:
                yield child

    # .. constructor-injected callbacks ......................................

    def _collect_callbacks(self, fn: FuncInfo) -> None:
        """Bind function-valued values flowing into object attributes:
        `RaftNode(..., apply_fn=lambda op: self._apply(pid, op))` maps
        the ctor arg through ClassInfo.param_attrs, and
        `node.wal.observer = self._wal_observer(pid)` binds through the
        receiver's inferred type. The bound targets make later dynamic
        `self.apply_fn(...)` sites resolvable — at the INVOCATION
        frame, so lock ordering is recorded where the callback actually
        runs, not where it was bound."""
        mod = self.a.modules[fn.module]
        # locals holding callbacks first, so passing them as args below
        # (and in later fixpoint passes) binds through them
        for stmt in self._own_statements(fn.node):
            if not isinstance(stmt, ast.Assign):
                continue
            cbs = self._callback_targets(fn, stmt.value)
            if not cbs:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    fn.local_callbacks.setdefault(
                        t.id, set()).update(cbs)
        for node in _expr_walk(fn.node):
            if isinstance(node, ast.Call):
                targets, kind = self.resolve_call(fn, node)
                if kind != "resolved":
                    continue
                for tq in targets:
                    tfn = self.a.funcs.get(tq)
                    if tfn is None:
                        continue
                    if tfn.name == "__init__" and tfn.cls is not None:
                        self._bind_ctor_args(
                            fn, self.a.classes[tfn.cls], tfn, node)
                    else:
                        self._bind_param_callbacks(fn, tfn, node)
            elif isinstance(node, ast.Assign):
                attrs = [t for t in node.targets
                         if isinstance(t, ast.Attribute)]
                if not attrs:
                    continue
                cbs = self._callback_targets(fn, node.value)
                if not cbs:
                    continue
                for t in attrs:
                    for key in self._expr_class_keys(fn, mod, t.value):
                        self.a.classes[key].callback_targets.setdefault(
                            t.attr, set()).update(cbs)

    def _bind_ctor_args(self, fn: FuncInfo, ci: ClassInfo,
                        init: FuncInfo, call: ast.Call) -> None:
        args = init.node.args
        params = [a.arg for a in args.posonlyargs + args.args][1:]
        pairs: list[tuple[str, ast.AST]] = []
        for i, arg in enumerate(call.args):
            if i < len(params):
                pairs.append((params[i], arg))
        for kw in call.keywords:
            if kw.arg is not None:
                pairs.append((kw.arg, kw.value))
        for pname, value in pairs:
            attr = ci.param_attrs.get(pname)
            if attr is None:
                continue
            cbs = self._callback_targets(fn, value)
            if cbs:
                ci.callback_targets.setdefault(attr, set()).update(cbs)

    def _bind_param_callbacks(self, fn: FuncInfo, tfn: FuncInfo,
                              call: ast.Call) -> None:
        """Function-valued call arguments (`self.hbm.resolve(buckets,
        gens, self._fetch_slabs)`) bind to the callee's params so the
        callee's own `fetch(...)` invocation resolves — lock ordering
        lands at the invocation frame, under whatever the callee holds
        there."""
        args = tfn.node.args
        params = [a.arg for a in args.posonlyargs + args.args]
        if tfn.cls is not None and params[:1] == ["self"]:
            params = params[1:]
        named = set(params) | {a.arg for a in args.kwonlyargs}
        pairs: list[tuple[str, ast.AST]] = []
        for i, arg in enumerate(call.args):
            if i < len(params):
                pairs.append((params[i], arg))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in named:
                pairs.append((kw.arg, kw.value))
        for pname, value in pairs:
            cbs = self._callback_targets(fn, value)
            if cbs:
                tfn.param_callbacks.setdefault(
                    pname, set()).update(cbs)

    def _binding_count(self) -> int:
        return sum(len(v) for ci in self.a.classes.values()
                   for v in ci.callback_targets.values()) + \
            sum(len(v) for fn in self.a.funcs.values()
                for v in fn.param_callbacks.values())

    def _callback_targets(self, fn: FuncInfo, expr: ast.AST) \
            -> set[str]:
        """Quals a function-valued expression will invoke: a lambda's
        resolvable body calls, a direct function/method reference, or a
        call to a factory that returns one of its own nested defs
        (`observer=self._raft_observer(pid)`)."""
        out: set[str] = set()
        mod = self.a.modules[fn.module]
        if isinstance(expr, ast.Lambda):
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    targets, kind = self.resolve_call(fn, sub)
                    if kind in ("resolved", "callback"):
                        out.update(targets)
        elif isinstance(expr, (ast.Name, ast.Attribute)):
            d = _dotted(expr)
            parts = d.split(".") if d else []
            if len(parts) == 2 and parts[0] == "self" and fn.cls:
                hit = self._method_lookup(fn.cls, parts[1])
                if hit:
                    out.add(hit)
            elif len(parts) == 1:
                if parts[0] in fn.local_callbacks:
                    out.update(fn.local_callbacks[parts[0]])
                else:
                    targets, kind = self._resolve_name_call(
                        fn, mod, parts[0])
                    if kind in ("resolved", "callback"):
                        out.update(targets)
        elif isinstance(expr, ast.Call):
            targets, kind = self.resolve_call(fn, expr)
            if kind == "resolved":
                for tq in targets:
                    tfn = self.a.funcs.get(tq)
                    if tfn is None:
                        continue
                    for stmt in self._own_statements(tfn.node):
                        if isinstance(stmt, ast.Return) and \
                                isinstance(stmt.value, ast.Name):
                            nq = f"{tfn.module}:{tfn.qualname}." \
                                 f"{stmt.value.id}"
                            if nq in tfn.nested:
                                out.add(nq)
        return out

    def _expr_class_keys(self, fn: FuncInfo, mod: ModuleInfo,
                         expr: ast.AST) -> set[str]:
        """Inferred class keys of a receiver expression: typed local,
        self attr, or an attribute chain over either (subscripts are
        transparent — containers type as their value class)."""
        if isinstance(expr, ast.Subscript):
            return self._expr_class_keys(fn, mod, expr.value)
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fn.cls:
                return {fn.cls}
            return set(fn.local_types.get(expr.id, ()))
        if isinstance(expr, ast.Attribute):
            out: set[str] = set()
            for base in self._expr_class_keys(fn, mod, expr.value):
                out |= self.a.classes[base].attr_types.get(
                    expr.attr, set())
            return out
        return set()

    def _callback_lookup(self, key: str, attr: str, seen=None) \
            -> set[str]:
        seen = seen or set()
        if key in seen:
            return set()
        seen.add(key)
        ci = self.a.classes.get(key)
        if ci is None:
            return set()
        if attr in ci.callback_targets:
            return ci.callback_targets[attr]
        mod = self.a.modules[ci.module]
        out: set[str] = set()
        for b in ci.bases:
            for bk in self._class_keys_of_call(mod, b):
                out |= self._callback_lookup(bk, attr, seen)
        return out

    # .. call resolution .....................................................

    def resolve_call(self, fn: FuncInfo, call: ast.Call) \
            -> tuple[tuple[str, ...], str]:
        """-> (target quals, kind)."""
        d = _dotted(call.func)
        mod = self.a.modules[fn.module]
        if d is None:
            # subscripted receivers (`self.nodes[pid].m()`) get one
            # shot at precise resolution through container value
            # types; anything short of "resolved" stays dynamic so
            # flattening never widens fan-out
            d = _dotted_thru_subscript(call.func)
            if d is not None:
                parts = d.split(".")
                targets, kind = (
                    self._resolve_name_call(fn, mod, parts[0])
                    if len(parts) == 1
                    else self._resolve_attr_call(fn, mod, parts))
                if kind == "resolved":
                    return targets, kind
            return (), "dynamic"
        parts = d.split(".")
        if len(parts) == 1:
            return self._resolve_name_call(fn, mod, parts[0])
        return self._resolve_attr_call(fn, mod, parts)

    def _resolve_name_call(self, fn: FuncInfo, mod: ModuleInfo,
                           name: str) -> tuple[tuple[str, ...], str]:
        # nested def in the same lexical function chain
        cur: FuncInfo | None = fn
        while cur is not None:
            child = f"{cur.module}:{cur.qualname}.{name}"
            if child in self.a.funcs:
                return (child,), "resolved"
            head, _, _ = cur.qualname.rpartition(".")
            cur = self.a.funcs.get(f"{cur.module}:{head}") if head else None
        pc = fn.param_callbacks.get(name) or \
            fn.local_callbacks.get(name)
        if pc:  # `fetch(...)` where every call site passed a known fn
            return tuple(sorted(pc)), "callback"
        alias = fn.attr_aliases.get(name)
        if alias and fn.cls:  # `obs = self.observer; obs(...)`
            hit = self._method_lookup(fn.cls, alias)
            if hit:
                return (hit,), "resolved"
            cb = self._callback_lookup(fn.cls, alias)
            if cb:
                return tuple(sorted(cb)), "callback"
        if name in mod.funcs:
            return (mod.funcs[name],), "resolved"
        if name in mod.classes:
            return self._ctor(mod.classes[name])
        bind = self._resolve_from_bind(mod, name)
        if bind:
            if bind[0] == "func":
                return (bind[1],), "resolved"
            if bind[0] == "class":
                return self._ctor(bind[1])
            return (), "external"
        if name in _PY_BUILTINS or hasattr(_builtins, name):
            return (), "external"
        return (), "dynamic"

    def _ctor(self, key: str) -> tuple[tuple[str, ...], str]:
        init = self._method_lookup(key, "__init__")
        return ((init,), "resolved") if init else ((), "resolved")

    def _resolve_attr_call(self, fn: FuncInfo, mod: ModuleInfo,
                           parts: list[str]) \
            -> tuple[tuple[str, ...], str]:
        method = parts[-1]
        base = parts[:-1]
        # self.m() / self.attr.m() / self.a.b.m() — attr chains walk
        # attr_types; bound callbacks resolve dynamic self.cb() sites
        if base[0] == "self" and fn.cls:
            if len(base) == 1:
                hit = self._method_lookup(fn.cls, method)
                if hit:
                    return (hit,), "resolved"
                cb = self._callback_lookup(fn.cls, method)
                if cb:
                    return tuple(sorted(cb)), "callback"
                return self._fanout(method)
            return self._chain_resolve(
                {fn.cls}, base[1:], method)
        # module-qualified: rpc.call(...), pkg.mod.f(...)
        tmod = self._module_of_prefix(mod, base)
        if tmod is not None:
            if method in tmod.funcs:
                return (tmod.funcs[method],), "resolved"
            if method in tmod.classes:
                return self._ctor(tmod.classes[method])
            return (), "external"
        head = base[0]
        bind = self._resolve_from_bind(mod, head)
        if head in mod.mod_alias or (bind and bind[0] in
                                     ("module", "external")):
            return (), "external"  # known external module
        if bind and bind[0] == "class" and len(base) == 1:
            hit = self._method_lookup(bind[1], method)
            if hit:
                return (hit,), "resolved"
        # typed local var (`node.m()`, `node.wal.m()`)
        types = set(fn.local_types.get(head, ()))
        if types:
            return self._chain_resolve(types, base[1:], method)
        return self._fanout(method)

    def _chain_resolve(self, keys: set[str], steps: list[str],
                       method: str) -> tuple[tuple[str, ...], str]:
        """Walk an attribute chain through attr_types, then look the
        method (or a bound callback) up on the final classes."""
        for step in steps:
            keys = {k2 for k in keys
                    for k2 in self.a.classes[k].attr_types.get(
                        step, ())}
        hits = {h for k in keys
                for h in [self._method_lookup(k, method)] if h}
        if hits:
            return tuple(sorted(hits)), "resolved"
        cb: set[str] = set()
        for k in keys:
            cb |= self._callback_lookup(k, method)
        if cb:
            return tuple(sorted(cb)), "callback"
        return self._fanout(method)

    def _fanout(self, method: str) -> tuple[tuple[str, ...], str]:
        if method in config.FANOUT_STOPLIST:
            return (), "dynamic"
        hits = self.a.method_index.get(method)
        if hits:
            return tuple(sorted(hits)), "fanout"
        return (), "dynamic"

    # .. lock expression resolution ..........................................

    def locks_of_expr(self, fn: FuncInfo, expr: ast.AST) \
            -> list[LockNode]:
        """LockNodes acquired by `with <expr>:` / `<expr>.acquire()`."""
        if isinstance(expr, ast.Subscript):
            return self.locks_of_expr(fn, expr.value)
        if isinstance(expr, ast.Name):
            if expr.id in fn.lock_vars:
                return list(fn.lock_vars[expr.id])
            mod = self.a.modules[fn.module]
            if expr.id in mod.locks:
                return [mod.locks[expr.id]]
            bind = self._resolve_from_bind(mod, expr.id)
            if bind and bind[0] == "external":
                head, _, member = bind[1].rpartition(".")
                src = self.a.modules.get(head)
                if src and member in src.locks:
                    return [src.locks[member]]
            return []
        if isinstance(expr, ast.Call):
            targets, kind = self.resolve_call(fn, expr)
            out = []
            for t in targets:
                tfn = self.a.funcs.get(t)
                if tfn is None or tfn.cls is None:
                    continue
                minted = self.a.classes[tfn.cls].minted.get(tfn.name)
                if minted:
                    out.append(minted)
            return out
        if isinstance(expr, ast.Attribute):
            d = _dotted(expr)
            if not d:
                return []
            parts = d.split(".")
            if parts[0] == "self" and fn.cls:
                if len(parts) == 2:
                    hit = self._lock_attr_lookup(fn.cls, parts[1])
                    if hit:
                        return [hit]
                    # unknown self attr in a with: plain lock
                    ci = self.a.classes[fn.cls]
                    return [self._lock_node(
                        f"{ci.name}.{parts[1]}", ("none", ""))]
                if len(parts) == 3:
                    ci = self.a.classes[fn.cls]
                    keys = ci.attr_types.get(parts[1], set())
                    out = []
                    for k in keys:
                        hit = self._lock_attr_lookup(k, parts[2])
                        if hit:
                            out.append(hit)
                    return out
            # lock attr on a typed local: lk.m is rare; skip
            types = fn.local_types.get(parts[0], set())
            out = []
            if len(parts) == 2:
                for k in types:
                    hit = self._lock_attr_lookup(k, parts[1])
                    if hit:
                        out.append(hit)
            return out
        return []

    # .. entries + reachability ..............................................

    def _find_entries(self) -> None:
        for suffix, qualname, kind in config.INTERPROC_ENTRY_POINTS:
            for fn in self.a.funcs.values():
                if fn.qualname == qualname and \
                        _norm(fn.ctx.path).endswith(suffix):
                    self.a.entries.append((fn.qual, kind))

    def _reachability(self) -> None:
        kinds = {k for _, k in self.a.entries}
        for kind in sorted(kinds):
            parents: dict[str, tuple[str | None, int]] = {}
            queue = [q for q, k in self.a.entries if k == kind]
            for q in queue:
                parents[q] = (None, 0)
            while queue:
                cur = queue.pop()
                fn = self.a.funcs[cur]
                succ: list[tuple[str, int]] = []
                for rec in fn.calls:
                    if rec.kind == "callback":
                        continue  # binding site already contributed
                    for t in rec.targets:
                        succ.append((t, rec.line))
                for n in fn.nested:  # closure rule
                    succ.append((n, self.a.funcs[n].node.lineno))
                for t, line in succ:
                    if t not in parents and t in self.a.funcs:
                        parents[t] = (cur, line)
                        queue.append(t)
            self.a.reach[kind] = parents

    # .. lock graph ..........................................................

    def _lock_graph(self) -> None:
        # transitive acquires: direct sets propagated caller <- callee
        # over precisely-resolved edges (fan-out edges would invent
        # orderings; documented blind spot)
        acq = {q: set(fn.direct_locks)
               for q, fn in self.a.funcs.items()}
        changed = True
        while changed:
            changed = False
            for q, fn in self.a.funcs.items():
                for rec in fn.calls:
                    if rec.kind not in ("resolved", "callback"):
                        continue
                    for t in rec.targets:
                        extra = acq.get(t, set()) - acq[q]
                        if extra:
                            acq[q] |= extra
                            changed = True
        self.a.acq = acq
        # expand held-across-call edges
        for q, fn in self.a.funcs.items():
            for held_ids, rec in getattr(fn, "_held_calls", ()):
                if rec.kind not in ("resolved", "callback"):
                    continue
                for t in rec.targets:
                    for m in acq.get(t, ()):
                        for h in held_ids:
                            if h != m:
                                self.a.lock_edges.setdefault(
                                    (h, m),
                                    f"{fn.ctx.path}:{rec.line}")
        self._cycles()

    def _cycles(self) -> None:
        graph: dict[str, set[str]] = {}
        for (a, b) in self.a.lock_edges:
            graph.setdefault(a, set()).add(b)
        # Tarjan SCC
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        onstack: set[str] = set()
        stack: list[str] = []
        counter = [0]
        sccs: list[list[str]] = []

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(graph.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    if w in onstack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        for scc in sccs:
            if len(scc) > 1 or (len(scc) == 1 and
                                scc[0] in graph.get(scc[0], ())):
                self.a.lock_cycles.append(sorted(scc))


_PY_BUILTINS = {
    "len", "range", "print", "sorted", "enumerate", "zip", "min", "max",
    "sum", "abs", "isinstance", "getattr", "setattr", "hasattr", "repr",
    "str", "int", "float", "bool", "list", "dict", "set", "tuple",
    "frozenset", "bytes", "bytearray", "iter", "next", "type", "super",
    "id", "hash", "map", "filter", "any", "all", "round", "divmod",
    "vars", "format", "ord", "chr", "callable", "issubclass",
}


class _FuncWalker:
    """Statement-sequential walk of one function body: collects call
    records, unresolved names, direct lock acquisitions (with-blocks
    and explicit .acquire() on resolvable locks), and nesting edges."""

    def __init__(self, b: _Builder, fn: FuncInfo, mod: ModuleInfo):
        self.b = b
        self.fn = fn
        self.mod = mod
        self.held_calls: list[tuple[tuple[str, ...], CallRec]] = []

    def run(self) -> None:
        self._block(self.fn.node.body, self._initial_held())
        self.fn._held_calls = self.held_calls

    def _initial_held(self) -> tuple[str, ...]:
        held: list[str] = []
        if self.fn.cls:
            for name in self.fn.ctx.func_holds(self.fn.node):
                hit = self.b._lock_attr_lookup(self.fn.cls, name)
                if hit:
                    held.append(hit.id)
        return tuple(held)

    def _block(self, stmts, held: tuple[str, ...]) -> None:
        for stmt in stmts:
            held = self._stmt(stmt, held)

    def _stmt(self, stmt: ast.AST, held: tuple[str, ...]) \
            -> tuple[str, ...]:
        if isinstance(stmt, _FUNC + (ast.ClassDef,)):
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                self._exprs(item.context_expr, inner)
                locks = self.b.locks_of_expr(self.fn, item.context_expr)
                for lk in locks:
                    self.fn.direct_locks.add(lk.id)
                    for h in inner:
                        if h != lk.id:
                            self.b.a.lock_edges.setdefault(
                                (h, lk.id),
                                f"{self.fn.ctx.path}:{stmt.lineno}")
                    inner = inner + (lk.id,)
            self._block(stmt.body, inner)
            return held
        sub_blocks = []
        exprs: list[ast.AST] = []
        if isinstance(stmt, (ast.If, ast.While)):
            exprs.append(stmt.test)
            sub_blocks = [stmt.body, stmt.orelse]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            exprs.append(stmt.iter)
            sub_blocks = [stmt.body, stmt.orelse]
        elif isinstance(stmt, ast.Try):
            sub_blocks = [stmt.body, stmt.orelse, stmt.finalbody] + \
                [h.body for h in stmt.handlers]
        if sub_blocks:
            for e in exprs:
                held = self._exprs(e, held)
            for blk in sub_blocks:
                self._block(blk, held)
            return held
        return self._exprs(stmt, held)

    def _exprs(self, node: ast.AST, held: tuple[str, ...]) \
            -> tuple[str, ...]:
        """Scan an expression/simple statement; explicit .acquire() on
        a resolvable lock extends `held` for the rest of the block
        (release tracking is deliberately ignored: over-approximation
        keeps the runtime-coverage direction safe)."""
        for call, in_lambda in self._calls_in(node):
            d = _dotted(call.func)
            if in_lambda:
                # a lambda body runs when the lambda is invoked, not
                # here: resolvable targets become deferred reachability
                # edges (no lock ordering, no held-across-call), while
                # unresolvable calls keep their primitive
                # classification for the blocking rules
                targets, kind = self.b.resolve_call(self.fn, call)
                if kind in ("resolved", "callback", "fanout"):
                    kind = "deferred"
                rec = CallRec(call.lineno, d, targets, kind, call)
                self.fn.calls.append(rec)
                if kind == "dynamic":
                    self.fn.unresolved.append(
                        (d or "<expr>", call.lineno))
                continue
            if d and d.endswith(".acquire"):
                locks = self.b.locks_of_expr(
                    self.fn, call.func.value)
                if locks:
                    for lk in locks:
                        self.fn.direct_locks.add(lk.id)
                        for h in held:
                            if h != lk.id:
                                self.b.a.lock_edges.setdefault(
                                    (h, lk.id),
                                    f"{self.fn.ctx.path}:{call.lineno}")
                        held = held + (lk.id,)
                    continue
            targets, kind = self.b.resolve_call(self.fn, call)
            rec = CallRec(call.lineno, d, targets, kind, call)
            self.fn.calls.append(rec)
            if kind == "dynamic":
                self.fn.unresolved.append((d or "<expr>", call.lineno))
            if held and kind in ("resolved", "callback"):
                self.held_calls.append((held, rec))
            # deferred-call rule: a project function passed BY VALUE
            # (executor.submit(self._call_partition, ...),
            # Thread(target=...), observer registration) will run
            # later — a reachability edge, but NOT a lock-ordering
            # edge (it executes on another thread/stack)
            for ref in self._func_refs(call):
                self.fn.calls.append(CallRec(
                    call.lineno, f"{_dotted(call.func)}->deferred",
                    (ref,), "deferred", call))
        return held

    def _func_refs(self, call: ast.Call) -> list[str]:
        refs: list[str] = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            d = _dotted(arg)
            if not d:
                continue
            parts = d.split(".")
            if len(parts) == 2 and parts[0] == "self" and self.fn.cls:
                hit = self.b._method_lookup(self.fn.cls, parts[1])
                if hit:
                    refs.append(hit)
            elif len(parts) == 1:
                targets, kind = self.b._resolve_name_call(
                    self.fn, self.mod, parts[0])
                if kind == "resolved":
                    refs.extend(targets)
        return refs

    @staticmethod
    def _calls_in(node: ast.AST):
        out: list[tuple[ast.Call, bool]] = []

        def rec(n: ast.AST, in_lambda: bool) -> None:
            if isinstance(n, ast.Call):
                out.append((n, in_lambda))
            for child in ast.iter_child_nodes(n):
                if isinstance(child, _FUNC + (ast.ClassDef,)):
                    continue
                rec(child, in_lambda or isinstance(child, ast.Lambda))

        rec(node, isinstance(node, ast.Lambda))
        out.sort(key=lambda t: (t[0].lineno, t[0].col_offset))
        return out


# -- memoized entry point -----------------------------------------------------

_MEMO: dict[tuple[int, ...], Analysis] = {}
LAST: Analysis | None = None


def build(contexts: list[FileContext]) -> Analysis:
    return _Builder(list(contexts)).build()


def analysis_for(contexts: list[FileContext]) -> Analysis:
    """One shared Analysis per run_paths invocation: the four VL5xx
    rules (and the CLI artifact writers) key on the identity of the
    parsed-context list, so the package is analyzed once per run no
    matter how many rules consume it."""
    global LAST
    key = tuple(id(c) for c in contexts)
    hit = _MEMO.get(key)
    if hit is None:
        _MEMO.clear()  # one live entry: contexts die with the run
        hit = _MEMO[key] = build(contexts)
    LAST = hit
    return hit

"""Observability-drift rule (VL401).

The registries of record are the metric registrations, span factories,
and span tags in the source tree; docs/OBSERVABILITY.md must document
exactly that set, both directions. An undocumented metric is invisible
to the operator; a documented-but-unregistered one lies to them
mid-incident, which is worse.

This is the old ``scripts/check_obs_docs.py`` folded into the lint
framework — the script remains as a thin CLI delegating here, and
``tests/test_obs_docs.py`` keeps gating tier-1 through it.

Names are compared after normalizing dynamic segments: an f-string
``{tag}`` in source and a ``{tag}``/``<tag>`` placeholder in the doc
both become ``*``.
"""

from __future__ import annotations

import os
import re

from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register

# metric registration call sites — counter/gauge/histogram and the
# callback variants — with the name literal possibly on the next line.
# Anchored on the quote right after the paren so the Registry method
# definitions themselves don't match.
_METRIC_RE = re.compile(
    r"\.(?:counter|gauge|histogram|callback_gauge|callback_counter)"
    r"\(\s*[\"']([A-Za-z_][\w]*)[\"']",
    re.S,
)

# post-creation span tags — set_tag with a literal key — mark
# per-request facts the operator greps for mid-incident; every literal
# key must appear backticked in the doc. One-directional: single-word
# doc backticks are too generic to demand a registration behind each.
_TAG_RE = re.compile(r"\.set_tag\(\s*[\"']([a-z_]+)[\"']")

# span factories — tracer span/record calls with a (possibly
# f-string) name literal — plus the engine's phase rows appended to
# `phases`/`spans` lists, which the PS replays as retroactive spans.
_SPAN_RES = [
    re.compile(r"\.span\(\s*f?[\"']([a-z_.{}]+)[\"']", re.S),
    re.compile(r"\.record\(\s*f?[\"']([a-z_.{}]+)[\"']", re.S),
    re.compile(r"phases\.append\(\(\s*f?[\"']([a-z_.{}]+)[\"']", re.S),
    re.compile(r"spans\.append\(\[\s*f?[\"']([a-z_.{}]+)[\"']", re.S),
    re.compile(r"spans\.extend\(\s*\[\s*f?[\"']([a-z_.{}]+)[\"']", re.S),
]


def repo_root() -> str:
    import vearch_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        vearch_tpu.__file__)))


def default_doc_path() -> str:
    return os.path.join(repo_root(), "docs", "OBSERVABILITY.md")


def _normalize(name: str) -> str:
    return re.sub(r"[{<][^}>]*[}>]", "*", name)


def names_from_text(text: str) -> tuple[set[str], set[str], set[str]]:
    """(metrics, spans, tags) registered/emitted by one source file."""
    metrics = set(_METRIC_RE.findall(text))
    tags = set(_TAG_RE.findall(text))
    spans: set[str] = set()
    for rx in _SPAN_RES:
        spans.update(_normalize(n) for n in rx.findall(text))
    return metrics, spans, tags


def source_names(src_dir: str) -> tuple[set[str], set[str], set[str]]:
    """Walk a source tree for every metric/span/tag name."""
    metrics: set[str] = set()
    spans: set[str] = set()
    tags: set[str] = set()
    for root, _dirs, files in os.walk(src_dir):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                m, s, t = names_from_text(f.read())
            metrics |= m
            spans |= s
            tags |= t
    return metrics, spans, tags


def doc_names(doc_path: str) -> tuple[set[str], set[str]]:
    """Backticked tokens in the doc, split into metric-shaped
    (prometheus identifier) and span-shaped (dotted) names. Prose
    backticks (`trace: true`, file paths, field names) match neither
    shape and are ignored."""
    with open(doc_path) as f:
        text = f.read()
    metrics: set[str] = set()
    spans: set[str] = set()
    for tok in re.findall(r"`([^`\n]+)`", text):
        if re.fullmatch(r"(?:vearch|tracing)_[a-z0-9_]+", tok):
            metrics.add(tok)
        elif re.fullmatch(r"[a-z_]+(?:\.[a-z_{}<>]+)+", tok):
            spans.add(_normalize(tok))
    return metrics, spans


def drift_failures(
    src_metrics: set[str], src_spans: set[str], src_tags: set[str],
    doc_path: str,
) -> list[str]:
    doc_metrics, doc_spans = doc_names(doc_path)
    with open(doc_path) as f:
        doc_words = set(re.findall(r"`([a-z_]+)`", f.read()))
    # keep only doc tokens whose first segment matches an emitted span
    # family — drops dotted prose like `dispatches.tags` (a JSON field,
    # not a span) without a hand-maintained prefix list
    span_roots = {s.split(".", 1)[0] for s in src_spans}
    doc_spans = {s for s in doc_spans if s.split(".", 1)[0] in span_roots}

    failures = []
    for name in sorted(src_metrics - doc_metrics):
        failures.append(f"metric registered but undocumented: {name}")
    for name in sorted(doc_metrics - src_metrics):
        failures.append(f"metric documented but not registered: {name}")
    for name in sorted(src_spans - doc_spans):
        failures.append(f"span emitted but undocumented: {name}")
    for name in sorted(doc_spans - src_spans):
        failures.append(f"span documented but never emitted: {name}")
    for name in sorted(src_tags - doc_words):
        failures.append(f"span tag set but undocumented: {name}")
    return failures


def check_package(src_dir: str | None = None,
                  doc_path: str | None = None) -> list[str]:
    """The whole-package drift check the script CLI runs: returns the
    failure lines (empty = in sync)."""
    src = src_dir or os.path.join(repo_root(), "vearch_tpu")
    doc = doc_path or default_doc_path()
    metrics, spans, tags = source_names(src)
    return drift_failures(metrics, spans, tags, doc)


def summary(src_dir: str | None = None) -> str:
    src = src_dir or os.path.join(repo_root(), "vearch_tpu")
    metrics, spans, tags = source_names(src)
    return (f"obs docs in sync: {len(metrics)} metrics, "
            f"{len(spans)} span families, {len(tags)} span tags")


def _check_project(contexts: list[FileContext]):
    # only meaningful on a whole-package scan: the bidirectional check
    # needs every registration in view, or documented names would look
    # stale. cluster/metrics.py anchors "the package is in the scan".
    if not any(c.path.replace("\\", "/").endswith("cluster/metrics.py")
               for c in contexts):
        return
    doc = default_doc_path()
    if not os.path.exists(doc):
        yield Finding("VL401", "obs-drift", doc, 0,
                      "docs/OBSERVABILITY.md missing")
        return
    metrics: set[str] = set()
    spans: set[str] = set()
    tags: set[str] = set()
    for c in contexts:
        m, s, t = names_from_text(c.source)
        metrics |= m
        spans |= s
        tags |= t
    for failure in drift_failures(metrics, spans, tags, doc):
        yield Finding("VL401", "obs-drift", doc, 0, failure)


register(Rule(
    id="VL401", tag="obs-drift",
    doc="metric/span/tag names in source and OBSERVABILITY.md stay in "
        "sync, both directions",
    check_project=_check_project,
))

"""Lock-discipline rules.

VL201 — `_guarded_by` enforcement. A class declares which lock guards
which attribute::

    class PSServer:
        _guarded_by = {
            "engines": "_lock",
            "applied": ("_lock", "_apply_lock"),
        }

Every mutation of ``self.<attr>`` (assignment, augmented assignment,
subscript store, del, or a mutator method call like ``.pop()``) must
then sit lexically inside ``with self.<lock>:`` for one of the declared
locks. ``__init__`` is exempt (construction happens-before
publication); a method whose *callers* all hold the lock declares
``# lint: holds[_lock]`` on its def line — a claim the runtime
lockcheck layer (VEARCH_LOCKCHECK=1) verifies instead of trusting.

VL202 — every ``threading.Thread(...)`` names itself and pins
``daemon=``. Anonymous threads make stack dumps and the lockcheck
acquisition graph unreadable, and an implicit non-daemon thread hangs
interpreter shutdown the first time its owner forgets to join it.

VL203 — ``time.time()`` is banned: monotonic clocks for anything
measured or compared (latency, deadlines, TTLs — NTP steps corrupt
wall-clock math), inline-justified `allow[wall-clock]` for genuinely
wall-anchored stamps (span epochs, persisted create times).
"""

from __future__ import annotations

import ast

from vearch_tpu.tools.lint import config
from vearch_tpu.tools.lint.core import FileContext, Finding, Rule, register


def _self_attr(node: ast.AST) -> str | None:
    """'attr' when node is `self.attr`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _mutation_root(target: ast.AST) -> str | None:
    """Attribute name mutated by an assignment target: `self.a`,
    `self.a[k]`, `self.a[k][j]` all root at 'a'."""
    cur = target
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    return _self_attr(cur)


def _guard_map(cls: ast.ClassDef) -> dict[str, tuple[str, ...]]:
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "_guarded_by"
                   for t in stmt.targets):
            continue
        if not isinstance(stmt.value, ast.Dict):
            return {}
        out: dict[str, tuple[str, ...]] = {}
        for k, v in zip(stmt.value.keys, stmt.value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out[k.value] = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)):
                locks = tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
                if locks:
                    out[k.value] = locks
        return out
    return {}


def _held_locks(ctx: FileContext, node: ast.AST) -> set[str]:
    """Lock attribute names lexically held at `node` via `with
    self.<name>:` ancestors (multiple with-items included)."""
    held: set[str] = set()
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                name = _self_attr(item.context_expr)
                if name:
                    held.add(name)
    return held


def _iter_mutations(func: ast.AST):
    """(node, attr, kind) for every self-attribute mutation in func."""
    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for tt in targets:
                    attr = _mutation_root(tt)
                    if attr:
                        yield node, attr, "assignment"
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            attr = _mutation_root(node.target)
            if attr:
                yield node, attr, "assignment"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _mutation_root(t)
                if attr:
                    yield node, attr, "del"
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in config.MUTATOR_METHODS:
            attr = _self_attr(node.func.value)
            if attr:
                yield node, attr, f".{node.func.attr}()"


def _check_guarded(ctx: FileContext):
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guards = _guard_map(cls)
        if not guards:
            continue
        for func in cls.body:
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if func.name == "__init__":
                continue
            fa, freason = ctx.func_allowed(func, "guarded")
            holds = ctx.func_holds(func)
            for node, attr, kind in _iter_mutations(func):
                locks = guards.get(attr)
                if locks is None:
                    continue
                inner = ctx.enclosing_function(node)
                if inner is not None and inner is not func and \
                        inner.name == "__init__":
                    continue
                held = _held_locks(ctx, node) | holds
                if inner is not None and inner is not func:
                    holds_inner = ctx.func_holds(inner)
                    held |= holds_inner
                if any(lk in held for lk in locks):
                    continue
                line = node.lineno
                ok, reason = ctx.allowed(line, "guarded")
                if not ok and fa:
                    ok, reason = True, freason
                want = " or ".join(f"self.{lk}" for lk in locks)
                yield Finding(
                    "VL201", "guarded", ctx.path, line,
                    f"{kind} to self.{attr} outside `with {want}` in "
                    f"{cls.name}.{func.name} (declared in _guarded_by)",
                    suppressed=ok, reason=reason,
                )


def _check_threads(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_thread = (
            (isinstance(func, ast.Attribute) and func.attr == "Thread"
             and isinstance(func.value, ast.Name)
             and func.value.id == "threading")
            or (isinstance(func, ast.Name) and func.id == "Thread")
        )
        if not is_thread:
            continue
        kw = {k.arg for k in node.keywords}
        missing = [k for k in ("daemon", "name") if k not in kw]
        if not missing:
            continue
        line = node.lineno
        ok, reason = ctx.allowed(line, "thread")
        yield Finding(
            "VL202", "thread", ctx.path, line,
            f"threading.Thread without {'/'.join(missing)}= — name "
            "every thread (stack dumps, lockcheck graphs) and pin "
            "daemonness explicitly",
            suppressed=ok, reason=reason,
        )


def _time_aliases(ctx: FileContext) -> tuple[set[str], set[str]]:
    """(module aliases of `time`, names bound to `time.time`)."""
    mods: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    funcs.add(a.asname or "time")
    return mods, funcs


def _check_wall_clock(ctx: FileContext):
    mods, funcs = _time_aliases(ctx)
    if not mods and not funcs:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = (
            (isinstance(func, ast.Attribute) and func.attr == "time"
             and isinstance(func.value, ast.Name) and func.value.id in mods)
            or (isinstance(func, ast.Name) and func.id in funcs)
        )
        if not hit:
            continue
        line = node.lineno
        ok, reason = ctx.allowed(line, "wall-clock")
        yield Finding(
            "VL203", "wall-clock", ctx.path, line,
            "time.time() — use time.monotonic() for latency/deadline/"
            "TTL math; justify inline if a wall-anchored stamp is "
            "genuinely required",
            suppressed=ok, reason=reason,
        )


register(Rule(
    id="VL201", tag="guarded",
    doc="_guarded_by attributes mutate only under their declared lock",
    check_file=_check_guarded,
))

register(Rule(
    id="VL202", tag="thread",
    doc="threading.Thread requires explicit daemon= and name=",
    check_file=_check_threads,
))

register(Rule(
    id="VL203", tag="wall-clock",
    doc="time.time() banned; monotonic for measurements, justified "
        "pragma for wall stamps",
    check_file=_check_wall_clock,
))

"""Elasticity operator CLI: split / migrate / rebalance / drain / plan
/ jobs, a thin REST wrapper over the master's elastic endpoints (see
docs/ELASTICITY.md for the runbook these verbs implement).

Also reachable as verbs of the role launcher:

    python -m vearch_tpu rebalance --master host:port --apply
    python -m vearch_tpu drain 3 --master host:port --apply
    python -m vearch_tpu split --master host:port \
        --db mydb --space items --partition 7
    python -m vearch_tpu migrate --master host:port \
        --partition 7 --to 4
    python -m vearch_tpu plan --master host:port
    python -m vearch_tpu jobs --master host:port [--job split-3]

Mutating verbs return a job id and (unless --no-wait) poll
GET /cluster/jobs/{id} to completion, streaming phase/progress to
stderr the same way backup_cli streams backup jobs.
"""

from __future__ import annotations

import argparse
import json
import sys


def _poll_job(master: str, job_id: str, auth, timeout_s: float) -> dict:
    """Poll one elastic job to a terminal status, painting progress on
    stderr. Transient master errors (leader failover, dropped poll) are
    ridden out; only CONSECUTIVE 404s mean the record is really gone
    (master restarted — the registry is in-memory)."""
    import time as _time

    from vearch_tpu.cluster import rpc

    deadline = _time.monotonic() + timeout_s
    misses = 0
    while True:
        if _time.monotonic() > deadline:
            print(f"\ngave up polling after {int(timeout_s)}s; job may "
                  f"still be running: GET /cluster/jobs/{job_id}",
                  file=sys.stderr)
            raise SystemExit(1)
        try:
            job = rpc.call(master, "GET", f"/cluster/jobs/{job_id}",
                           auth=auth)
            misses = 0
        except rpc.RpcError as e:
            misses = misses + 1 if e.code == 404 else 0
            if e.code == 404 and misses >= 5:
                print(f"\njob record lost ({e.msg}); check "
                      "`elastic_cli jobs` later", file=sys.stderr)
                raise SystemExit(1) from None
            _time.sleep(1.0)
            continue
        d = job.get("detail") or {}
        bits = [job["status"], job.get("phase") or ""]
        if d.get("docs_total"):
            bits.append(f"{d.get('docs_done', 0)}/{d['docs_total']} docs")
        if d.get("lag") is not None:
            bits.append(f"lag={d['lag']}")
        steps = job.get("steps") or []
        if steps:
            done = sum(1 for s in steps if s.get("status") == "done")
            bits.append(f"moves {done}/{len(steps)}")
        print("\r" + " ".join(b for b in bits if b).ljust(60), end="",
              file=sys.stderr, flush=True)
        if job["status"] != "running":
            print(file=sys.stderr)
            return job
        _time.sleep(0.5)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="vearch-tpu-elastic")
    ap.add_argument("command",
                    choices=["split", "migrate", "rebalance", "drain",
                             "plan", "jobs"])
    ap.add_argument("node", nargs="?", default=None,
                    help="drain: the PS node id to empty")
    ap.add_argument("--master", required=True,
                    help="master address(es), comma-separated for a "
                         "multi-master group")
    ap.add_argument("--user", default=None)
    ap.add_argument("--password", default=None)
    ap.add_argument("--db", default=None, help="split: database name")
    ap.add_argument("--space", default=None, help="split: space name")
    ap.add_argument("--partition", type=int, default=None,
                    help="split/migrate: target partition id")
    ap.add_argument("--to", type=int, default=None,
                    help="migrate: destination PS node id")
    ap.add_argument("--from", dest="from_node", type=int, default=None,
                    help="migrate: source PS node id (default: a "
                         "follower replica)")
    ap.add_argument("--node", dest="node_flag", type=int, default=None,
                    help="drain: alternative to the positional node id")
    ap.add_argument("--apply", action="store_true",
                    help="rebalance/drain: execute the plan instead of "
                         "printing it")
    ap.add_argument("--max-moves", type=int, default=4,
                    help="rebalance: cap on replica moves per run")
    ap.add_argument("--job", default=None, help="jobs: one job id")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="job wall-clock budget (server + client poll)")
    ap.add_argument("--no-wait", action="store_true",
                    help="return the job id immediately, don't poll")
    args = ap.parse_args(argv)

    from vearch_tpu.cluster import rpc

    auth = (args.user, args.password) if args.user else None
    try:
        if args.command == "plan":
            out = rpc.call(args.master, "GET", "/cluster/plan", auth=auth)
        elif args.command == "jobs":
            path = "/cluster/jobs" + (f"/{args.job}" if args.job else "")
            out = rpc.call(args.master, "GET", path, auth=auth)
        elif args.command == "split":
            if not (args.db and args.space and args.partition is not None):
                raise SystemExit("split needs --db, --space, --partition")
            out = rpc.call(args.master, "POST", "/partitions/split", {
                "db_name": args.db, "space_name": args.space,
                "partition_id": args.partition,
                "timeout_s": args.timeout,
            }, auth=auth)
        elif args.command == "migrate":
            if args.partition is None or args.to is None:
                raise SystemExit("migrate needs --partition and --to")
            body = {"partition_id": args.partition, "to_node": args.to,
                    "timeout_s": args.timeout}
            if args.from_node is not None:
                body["from_node"] = args.from_node
            out = rpc.call(args.master, "POST", "/partitions/migrate",
                           body, auth=auth)
        elif args.command == "rebalance":
            out = rpc.call(args.master, "POST", "/cluster/rebalance", {
                "apply": args.apply, "max_moves": args.max_moves,
            }, auth=auth)
        else:  # drain
            node = args.node_flag if args.node_flag is not None \
                else args.node
            if node is None:
                raise SystemExit("drain needs a node id: "
                                 "`drain <node>` or --node")
            out = rpc.call(args.master, "POST", "/cluster/drain", {
                "node_id": int(node), "apply": args.apply,
            }, auth=auth)
        job_id = out.get("job_id") if isinstance(out, dict) else None
        if job_id and not args.no_wait:
            out = _poll_job(args.master, job_id, auth, args.timeout + 60.0)
            print(json.dumps(out, indent=2))
            return 0 if out.get("status") == "done" else 1
    except rpc.RpcError as e:
        print(f"error ({e.code}): {e.msg}", file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Opt-in runtime lock-discipline checker (``VEARCH_LOCKCHECK=1``).

The static side of lock discipline (vearch-lint VL201) proves lexical
placement; this layer proves the *dynamic* claims the linter must take
on faith — that a ``# lint: holds[_lock]`` method really runs under
the lock, and that no pair of locks is ever taken in both orders.

Three pieces:

- :func:`make_lock` — the cluster layer creates its locks through
  this. Plain ``threading.Lock``/``RLock`` normally (zero overhead);
  a named :class:`DebugLock` when checking is enabled.
- :class:`DebugLock` — records, per thread, the stack of held locks,
  and the global edge set "A held while acquiring B". A new edge whose
  reverse already exists is a lock-order inversion: two threads can
  interleave into deadlock, which a test run may never hit but the
  graph proves possible. Recorded once per pair, with both stacks.
- :func:`guarded` — class decorator reading the class's
  ``_guarded_by`` map (the same map VL201 enforces statically). When
  checking is enabled, a write to a guarded attribute outside its
  DebugLock — from *any* thread after ``__init__`` finishes — records
  an unguarded-access violation.

Violations accumulate in a process-wide list; tests call
:func:`check` (raises with every violation) or :func:`violations`.
Enablement is read per lock/instance creation: set the env var (or
call :func:`enable`) *before* constructing the objects under test.
"""

from __future__ import annotations

import functools
import os
import threading
import traceback

__all__ = [
    "enabled", "enable", "disable", "reset",
    "make_lock", "DebugLock", "guarded",
    "violations", "check", "acquisition_edges",
]

_forced: bool | None = None
_state_lock = threading.Lock()
_violations: list[dict] = []
# (first, then) -> short stack summary of the acquisition that created
# the edge; the reverse-edge check is the inversion detector
_edges: dict[tuple[str, str], str] = {}
_tls = threading.local()


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return os.environ.get("VEARCH_LOCKCHECK", "") not in ("", "0")


def enable() -> None:
    global _forced
    _forced = True


def disable() -> None:
    global _forced
    _forced = False


def reset() -> None:
    """Clear recorded state (between tests)."""
    global _forced
    with _state_lock:
        _violations.clear()
        _edges.clear()
    _forced = None


def violations() -> list[dict]:
    with _state_lock:
        return list(_violations)


def acquisition_edges() -> dict[tuple[str, str], str]:
    with _state_lock:
        return dict(_edges)


def check() -> None:
    """Raise AssertionError listing every recorded violation."""
    v = violations()
    if v:
        lines = [f"- [{x['kind']}] {x['detail']}" for x in v]
        raise AssertionError(
            f"lockcheck recorded {len(v)} violation(s):\n" +
            "\n".join(lines))


def _record(kind: str, detail: str, stack: str = "") -> None:
    with _state_lock:
        _violations.append({"kind": kind, "detail": detail, "stack": stack})


def _held_stack() -> list["DebugLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _site() -> str:
    # the caller outside this module: the acquisition site
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if "lockcheck" not in (frame.filename or ""):
            return f"{frame.filename}:{frame.lineno} in {frame.name}"
    return "<unknown>"


class DebugLock:
    """Named reentrant lock recording order edges and ownership.

    Reentrant on purpose even for call sites that asked for a plain
    Lock: the checker must observe nested acquisition rather than
    deadlock on it, and a same-lock re-acquire that would deadlock a
    plain Lock is recorded as a violation instead.
    """

    def __init__(self, name: str, reentrant: bool = True):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock()

    # -- ownership ------------------------------------------------------------

    def held_by_current(self) -> bool:
        return self in _held_stack()

    def _note_edges(self) -> None:
        held = _held_stack()
        site = _site()
        for h in held:
            if h.name == self.name:
                continue
            edge = (h.name, self.name)
            rev = (self.name, h.name)
            with _state_lock:
                known = edge in _edges
                rev_site = _edges.get(rev)
                if not known:
                    _edges[edge] = site
            if rev_site is not None:
                _record(
                    "lock-order-inversion",
                    f"{h.name} -> {self.name} at {site}; reverse order "
                    f"previously at {rev_site}",
                    site,
                )

    # -- lock protocol --------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held_stack()
        if not self.reentrant and self in held:
            _record(
                "self-deadlock",
                f"re-acquiring non-reentrant lock {self.name} at "
                f"{_site()} (a plain Lock would deadlock here)",
            )
        if self not in held:
            self._note_edges()
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(self)
        return got

    def release(self) -> None:
        held = _held_stack()
        if self in held:
            # remove the most recent entry (reentrant stacking)
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        else:
            _record("foreign-release",
                    f"{self.name} released by a thread that never "
                    f"acquired it, at {_site()}")
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition(lock) integration: delegate the save/restore pair so
    # cv.wait() keeps the held-stack honest while the lock is out
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        held = _held_stack()
        count = held.count(self)
        for _ in range(count):
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        return (self._inner._release_save(), count)

    def _acquire_restore(self, state) -> None:
        inner_state, count = state
        self._inner._acquire_restore(inner_state)
        held = _held_stack()
        held.extend([self] * count)

    def __repr__(self) -> str:
        return f"<DebugLock {self.name}>"


def make_lock(name: str, reentrant: bool = False):
    """A lock for cluster-layer shared state. Plain Lock/RLock unless
    lockcheck is enabled, then a named DebugLock."""
    if enabled():
        return DebugLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()


def _lock_names(value) -> tuple[str, ...]:
    return (value,) if isinstance(value, str) else tuple(value)


def guarded(cls):
    """Class decorator: runtime-verify the class's ``_guarded_by`` map.

    No-ops (beyond one dict lookup per setattr) when lockcheck is off
    or the instance's locks are plain locks. Construction is exempt:
    writes during ``__init__`` happen before the object is published.
    """
    guards = getattr(cls, "_guarded_by", None)
    if not guards:
        return cls

    orig_init = cls.__init__
    orig_setattr = cls.__setattr__

    @functools.wraps(orig_init)
    def __init__(self, *args, **kw):
        object.__setattr__(self, "_lockcheck_in_init", True)
        try:
            orig_init(self, *args, **kw)
        finally:
            object.__setattr__(self, "_lockcheck_in_init", False)

    def __setattr__(self, name, value):
        if name in guards and enabled() and \
                not self.__dict__.get("_lockcheck_in_init", True):
            lock_attrs = _lock_names(guards[name])
            locks = [getattr(self, a, None) for a in lock_attrs]
            debug = [lk for lk in locks if isinstance(lk, DebugLock)]
            if debug and not any(lk.held_by_current() for lk in debug):
                _record(
                    "unguarded-write",
                    f"{cls.__name__}.{name} written without "
                    f"{' or '.join(lock_attrs)} held, at {_site()} "
                    f"(thread {threading.current_thread().name})",
                )
        orig_setattr(self, name, value)

    cls.__init__ = __init__
    cls.__setattr__ = __setattr__
    return cls

"""Standalone backup CLI (reference: tools/backup/vearch_backup.go —
a thin REST wrapper over the master's backup API).

Usage:
    python -m vearch_tpu.tools.backup_cli \
        --master host:port --db mydb --space myspace create \
        --store-root /mnt/backups
    python -m vearch_tpu.tools.backup_cli ... list --store-root ...
    python -m vearch_tpu.tools.backup_cli ... restore --version 3 \
        --s3-endpoint minio:9000 --s3-bucket vearch \
        --s3-access-key ak --s3-secret-key sk
"""

from __future__ import annotations

import argparse
import json
import sys


def build_store_spec(args) -> dict:
    if args.s3_endpoint:
        spec: dict = {
            "type": "s3",
            "endpoint": args.s3_endpoint,
            "bucket": args.s3_bucket or "vearch",
            "access_key": args.s3_access_key or "",
            "secret_key": args.s3_secret_key or "",
        }
        if args.s3_region:
            spec["region"] = args.s3_region
        if args.s3_prefix:
            spec["prefix"] = args.s3_prefix
        return {"store": spec}
    if not args.store_root:
        raise SystemExit("need --store-root or --s3-endpoint")
    return {"store_root": args.store_root}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="vearch-tpu-backup")
    ap.add_argument("--master", required=True,
                    help="master address(es), comma-separated for a "
                         "multi-master group")
    ap.add_argument("--db", required=True)
    ap.add_argument("--space", required=True)
    ap.add_argument("--user", default=None)
    ap.add_argument("--password", default=None)
    ap.add_argument("command",
                    choices=["create", "list", "restore", "delete"])
    ap.add_argument("--version", type=int, default=None,
                    help="backup version (restore/delete)")
    ap.add_argument("--store-root", default=None,
                    help="local/NFS object store root")
    ap.add_argument("--s3-endpoint", default=None)
    ap.add_argument("--s3-bucket", default=None)
    ap.add_argument("--s3-access-key", default=None)
    ap.add_argument("--s3-secret-key", default=None)
    ap.add_argument("--s3-region", default=None)
    ap.add_argument("--s3-prefix", default=None)
    args = ap.parse_args(argv)

    from vearch_tpu.cluster import rpc

    body = {"command": args.command, **build_store_spec(args)}
    if args.command in ("restore", "delete"):
        if args.version is None:
            raise SystemExit(f"{args.command} needs --version")
        body["version"] = args.version
    auth = (args.user, args.password) if args.user else None
    try:
        out = rpc.call(
            args.master, "POST",
            f"/backup/dbs/{args.db}/spaces/{args.space}", body, auth=auth,
        )
    except rpc.RpcError as e:
        print(f"error ({e.code}): {e.msg}", file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Standalone backup CLI (reference: tools/backup/vearch_backup.go —
a thin REST wrapper over the master's backup API).

Usage:
    python -m vearch_tpu.tools.backup_cli \
        --master host:port --db mydb --space myspace create \
        --store-root /mnt/backups
    python -m vearch_tpu.tools.backup_cli ... list --store-root ...
    python -m vearch_tpu.tools.backup_cli ... restore --version 3 \
        --s3-endpoint minio:9000 --s3-bucket vearch \
        --s3-access-key ak --s3-secret-key sk
"""

from __future__ import annotations

import argparse
import json
import sys


def build_store_spec(args) -> dict:
    if args.s3_endpoint:
        spec: dict = {
            "type": "s3",
            "endpoint": args.s3_endpoint,
            "bucket": args.s3_bucket or "vearch",
            "access_key": args.s3_access_key or "",
            "secret_key": args.s3_secret_key or "",
        }
        if args.s3_region:
            spec["region"] = args.s3_region
        if args.s3_prefix:
            spec["prefix"] = args.s3_prefix
        return {"store": spec}
    if not args.store_root:
        raise SystemExit("need --store-root or --s3-endpoint")
    return {"store_root": args.store_root}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="vearch-tpu-backup")
    ap.add_argument("--master", required=True,
                    help="master address(es), comma-separated for a "
                         "multi-master group")
    ap.add_argument("--db", required=True)
    ap.add_argument("--space", required=True)
    ap.add_argument("--user", default=None)
    ap.add_argument("--password", default=None)
    ap.add_argument("command",
                    choices=["create", "list", "restore", "delete"])
    ap.add_argument("--version", type=int, default=None,
                    help="backup version (restore/delete)")
    ap.add_argument("--store-root", default=None,
                    help="local/NFS object store root")
    ap.add_argument("--s3-endpoint", default=None)
    ap.add_argument("--s3-bucket", default=None)
    ap.add_argument("--s3-access-key", default=None)
    ap.add_argument("--s3-secret-key", default=None)
    ap.add_argument("--s3-region", default=None)
    ap.add_argument("--s3-prefix", default=None)
    ap.add_argument("--sync", action="store_true",
                    help="create: block until the backup finishes "
                         "instead of running it as an async job")
    args = ap.parse_args(argv)

    from vearch_tpu.cluster import rpc

    body = {"command": args.command, **build_store_spec(args)}
    if args.command in ("restore", "delete"):
        if args.version is None:
            raise SystemExit(f"{args.command} needs --version")
        body["version"] = args.version
    if args.command == "create" and not args.sync:
        body["async"] = True
    auth = (args.user, args.password) if args.user else None
    try:
        out = rpc.call(
            args.master, "POST",
            f"/backup/dbs/{args.db}/spaces/{args.space}", body, auth=auth,
        )
        if args.command == "create" and not args.sync:
            # poll the master job to completion, showing per-partition
            # progress (reference: async backup + progress endpoints)
            job_id = out["job_id"]
            import time as _time

            poll_deadline = _time.monotonic() + 3600.0
            misses = 0
            while True:
                if _time.monotonic() > poll_deadline:
                    print("\ngave up polling after 1h; job may still be "
                          f"running: GET /backup/jobs/{job_id}",
                          file=sys.stderr)
                    return 1
                try:
                    job = rpc.call(args.master, "GET",
                                   f"/backup/jobs/{job_id}", auth=auth)
                    misses = 0
                except rpc.RpcError as e:
                    # ride out leader failover / transient network; only
                    # CONSECUTIVE 404s mean the job record is really
                    # gone (master restarted — records are in-memory)
                    misses = misses + 1 if e.code == 404 else 0
                    if e.code == 404 and misses >= 5:
                        print(f"\njob record lost ({e.msg}); the backup "
                              "may still complete — check "
                              "`backup_cli list` later", file=sys.stderr)
                        return 1
                    _time.sleep(1.0)
                    continue
                parts = job["partitions"]
                line = " ".join(
                    f"p{pid}:{p['status']}"
                    + (f"({p['files_done']}/{p['files_total']})"
                       if p.get("files_total") else "")
                    for pid, p in sorted(parts.items())
                )
                print(f"\r{job['status']}: {line}", end="",
                      file=sys.stderr, flush=True)
                if job["status"] != "running":
                    print(file=sys.stderr)
                    break
                _time.sleep(0.5)
            out = job
            if job["status"] == "error":
                print(json.dumps(out, indent=2))
                return 1
    except rpc.RpcError as e:
        print(f"error ({e.code}): {e.msg}", file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Batch distance kernels, MXU-first.

TPU-native replacement for the reference's faiss SIMD distance loops
(reference: internal/engine/index/impl/gamma_index_flat.cc brute-force scan,
faiss distances). Everything is expressed as one big matmul so XLA tiles it
onto the MXU:

    L2:   d(q, x)^2 = ||q||^2 - 2 q.x + ||x||^2
    IP:   s(q, x)   = q.x
    COS:  s(q, x)   = (q/||q||) . (x/||x||)

Scores are returned in "similarity" orientation — HIGHER is always better —
so `lax.top_k` applies uniformly. `score_to_metric` converts back to the
user-facing metric value (L2 distance is `-score`).

Matmuls accumulate in float32 (`preferred_element_type`); inputs may be
bfloat16 for 2x HBM bandwidth (the usual bottleneck for brute-force scan).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.types import MetricType

# Plain float, not a jnp scalar: a module-level jnp value would initialise
# the XLA backend at import time and pin the platform before the app
# configures it.
NEG_INF = float("-inf")


def sqnorms(x: jax.Array) -> jax.Array:
    """Row-wise squared L2 norms, accumulated in f32. Shape [n]."""
    xf = x.astype(jnp.float32)
    return jnp.sum(xf * xf, axis=-1)


def host_sqnorms(x: np.ndarray) -> np.ndarray:
    """Host-side sqnorms for DERIVED device columns (raw-base sqnorm).
    numpy's fixed-length inner-axis pairwise sum is deterministic, so
    every placement path — full place, single-device tail flush, mesh
    shard rebuild, mesh tail-append — lands the bit-identical column;
    XLA reductions reassociate per program shape and would drift by an
    ulp between paths (the int8 mirror's _h_vsq follows the same
    host-derived design)."""
    xf = np.asarray(x).astype(np.float32)
    return np.sum(xf * xf, axis=-1)


def dot_precision(*arrays: jax.Array):
    """Pick matmul precision by input dtype.

    float32 inputs get HIGHEST: the default truncates to bf16-ish passes
    (~2e-3 rel err) and breaks the exactness invariant. Quantized inputs
    (bf16/int8) are already single-MXU-pass exact, and HIGHEST on them
    triggers a multi-pass f32 emulation measured 20x slower at 1M scale —
    so they get DEFAULT.
    """
    if any(a.dtype == jnp.float32 or a.dtype == jnp.float64 for a in arrays):
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def to_device_mask(valid_mask, n: int, cap: int) -> jax.Array:
    """Normalise a validity mask to a device bool array of length `cap`.

    `valid_mask` may be a host numpy array (per-request filter result),
    an engine-cached `jax.Array` of length n, or None (all alive). Rows
    in [n, cap) are padding and always False. Padding to the *capacity*
    of the backing buffer (not the live count) keeps kernel input shapes
    stable across ingest so jit doesn't retrace on every write.
    """
    if isinstance(valid_mask, jax.Array):
        m = valid_mask[:n]
        if m.shape[0] < cap:
            m = jnp.pad(m, (0, cap - m.shape[0]))
        return m
    v = np.zeros(cap, dtype=np.bool_)
    if valid_mask is not None:
        v[:n] = valid_mask[:n]
    else:
        v[:n] = True
    return jnp.asarray(v)


@functools.partial(jax.jit, static_argnames=("metric",))
def similarity_scores(
    queries: jax.Array,
    base: jax.Array,
    metric: MetricType = MetricType.L2,
    base_sqnorm: jax.Array | None = None,
) -> jax.Array:
    """Dense [B, N] similarity matrix (higher = better).

    queries: [B, d]; base: [N, d]; base_sqnorm: optional precomputed [N]
    (cached per segment by the raw-vector store so the hot path reads the
    base matrix exactly once).
    """
    dots = jax.lax.dot_general(
        queries,
        base,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=dot_precision(queries, base),
    )  # [B, N]
    if metric is MetricType.INNER_PRODUCT:
        return dots
    if metric is MetricType.COSINE:
        qn = jnp.sqrt(jnp.maximum(sqnorms(queries), 1e-30))[:, None]
        if base_sqnorm is None:
            base_sqnorm = sqnorms(base)
        bn = jnp.sqrt(jnp.maximum(base_sqnorm, 1e-30))[None, :]
        return dots / (qn * bn)
    # L2: score = -(||q||^2 - 2 q.x + ||x||^2)
    if base_sqnorm is None:
        base_sqnorm = sqnorms(base)
    qn = sqnorms(queries)
    d2 = qn[:, None] - 2.0 * dots + base_sqnorm[None, :]
    return -jnp.maximum(d2, 0.0)


def score_to_metric(scores: jax.Array, metric: MetricType) -> jax.Array:
    """Convert internal similarity scores to user-facing metric values."""
    if metric is MetricType.L2:
        return -scores
    return scores


@functools.partial(jax.jit, static_argnames=("k",))
def masked_topk(
    scores: jax.Array, valid: jax.Array | None, k: int
) -> tuple[jax.Array, jax.Array]:
    """Top-k over [B, N] scores with an optional [N] or [B, N] validity mask.

    Invalid slots (deleted docs — reference: util/bitmap_manager.h:19 —
    padding rows, or scalar-filtered docs) score -inf and sink to the
    bottom. Returns (scores [B, k], indices [B, k]); callers must drop
    hits whose score is -inf when fewer than k valid docs exist. When
    k > N the result is padded with (-inf, -1) columns so the output
    shape is always [B, k] (a fresh partition may hold fewer docs than
    the requested top-k).
    """
    if valid is not None:
        if valid.ndim == 1:
            valid = valid[None, :]
        scores = jnp.where(valid, scores, NEG_INF)
    n = scores.shape[-1]
    if k <= n:
        return jax.lax.top_k(scores, k)
    top_s, top_i = jax.lax.top_k(scores, n)
    pad = ((0, 0),) * (scores.ndim - 1) + ((0, k - n),)
    return (
        jnp.pad(top_s, pad, constant_values=-jnp.inf),
        jnp.pad(top_i, pad, constant_values=-1),
    )


@functools.partial(jax.jit, static_argnames=("metric", "k"))
def brute_force_search(
    queries: jax.Array,
    base: jax.Array,
    valid: jax.Array | None,
    k: int,
    metric: MetricType = MetricType.L2,
    base_sqnorm: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused exact search: distance matmul + masked top-k.

    The engine's brute-force path, used by the FLAT index and as the
    below-training-threshold fallback (reference: engine.cc:280-302).
    """
    scores = similarity_scores(queries, base, metric, base_sqnorm)
    return masked_topk(scores, valid, k)


# compiled-program tracking (ops/perf_model.py): lets the perf gates
# assert the brute-force/FLAT path compiles once per shape
from vearch_tpu.ops.perf_model import register_jit  # noqa: E402

# rebinding through the returned proxy is what lets the compile-audit
# flight recorder see cache growth on these entry points — importers
# (index/flat.py, index/_store_paths.py) pick up the proxy because the
# rebind happens before their `from ... import` executes
similarity_scores = register_jit(
    "distance.similarity_scores", similarity_scores
)
masked_topk = register_jit("distance.masked_topk", masked_topk)
brute_force_search = register_jit(
    "distance.brute_force_search", brute_force_search
)


def merge_topk(
    scores_list: list[jax.Array],
    ids_list: list[jax.Array],
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """Merge per-segment / per-shard top-k candidate lists into a global
    top-k (reference: router-side sorted merge, client/client.go:779).

    scores_list: list of [B, k_i] similarity scores; ids_list: matching
    global doc ids. Concatenate + re-top-k — O(B * sum k_i) and fully
    on-device, no host round-trip.
    """
    scores = jnp.concatenate(scores_list, axis=1)
    ids = jnp.concatenate(ids_list, axis=1)
    k = min(k, scores.shape[1])
    top_scores, pos = jax.lax.top_k(scores, k)
    return top_scores, jnp.take_along_axis(ids, pos, axis=1)

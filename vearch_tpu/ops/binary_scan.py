"""Packed 1-bit stage-0 scan + progressive three-stage refinement.

The capacity tier below int4 (ROADMAP item 2; reference:
index/impl/gamma_index_ivfrabitq.cc wrapping faiss RaBitQ — estimator
scan over 1-bit codes, then rerank). A row quantizes to its sign bits
plus a per-row magnitude scale (the RaBitQ estimator's first-order
form): row ~= scale * sign(row), stored as a packed bit plane of
`ceil(d/8)` bytes — 8x denser than the int8 mirror's row payload, the
representation that fits billion-scale corpora in HBM.

TPU-native scoring (same departure from the reference as ops/ivf.py's
ADC note): no XOR/popcount loops — those lower to VPU-serial scalar
ops. The kernel unpacks bit planes to ±1 bf16 tiles and feeds one MXU
matmul:  q . (scale * sign(row)) = scale * (q . (2*bits - 1)).
The unpack is transient work the matmul absorbs (exactly like
ops/ivf.py unpack_int4); only the packed planes are HBM-resident.

Progressive refinement chains three representations of the SAME rows:

    stage 0  binary scan over the whole partition      -> top r0
    stage 1  int8/int4 mirror rescore of the r0 rows   -> top r1
    stage 2  exact rerank against the raw base         -> top k

For a RAM store all three stages fuse into ONE jitted program
(`binary_refine_rerank`); a disk store runs stages 0-1 on device
(`binary_refine_candidates`) and gathers stage-2 rows through the mmap
+ readahead path (index/_store_paths.rerank_against_store), exactly
like the int8 disk path. Byte/footprint models live in
ops/perf_model.py (binary_plane_bytes / binary_footprint_bytes); the
dispatch rows are DOCUMENTED_DISPATCHES["ivfrabitq_three_stage*"].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.types import MetricType
from vearch_tpu.ops.distance import sqnorms
from vearch_tpu.ops.ivf import NEG_INF, _select_topk, unpack_int4
from vearch_tpu.ops.perf_model import register_jit
from vearch_tpu.tools import lockcheck


def pack_sign_rows(
    rows: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack float rows to sign-bit planes with per-row scale/offset.

    Returns (planes [n, ceil(d/8)] uint8, scale [n] f32, vsq [n] f32)
    where the stored approximation is ``scale * (2*bit - 1)`` per dim
    and vsq = ||approx||^2 = d * scale^2 (sign^2 == 1) — the offset
    term of the L2 score decomposition, so the scan kernel needs no
    extra per-row column beyond (scale, vsq). Dimensions pad up to a
    byte boundary with 0-bits; queries pad with zeros, so pad dims
    contribute nothing to the dot product.
    """
    rows = np.asarray(rows, dtype=np.float32)
    d = rows.shape[1]
    scale = np.maximum(
        np.abs(rows).mean(axis=1), 1e-12
    ).astype(np.float32)
    planes = np.packbits(rows > 0.0, axis=1)  # MSB-first, byte-padded
    vsq = (float(d) * scale * scale).astype(np.float32)
    return planes, scale, vsq


def unpack_bits_pm1(planes: jax.Array) -> jax.Array:
    """[N, d/8] uint8 bit planes -> [N, d] bf16 values in {-1, +1}.

    Layout contract (pack_sign_rows / np.packbits default): bit 7 (MSB)
    of byte j is dimension 8*j — two cheap vector ops and a reshape
    that XLA fuses into the consuming matmul, no per-element loops.
    """
    n, nb = planes.shape
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (planes[:, :, None] >> shifts) & jnp.uint8(1)
    return bits.reshape(n, nb * 8).astype(jnp.bfloat16) * 2 - 1


def _pad_queries(queries: jax.Array, d_pad: int) -> jax.Array:
    """Zero-pad [B, d] queries to the bit plane's byte-padded width."""
    d = queries.shape[1]
    if d == d_pad:
        return queries
    return jnp.pad(queries, ((0, 0), (0, d_pad - d)))


def _binary_scores(
    queries: jax.Array,    # [B, d] f32
    planes: jax.Array,     # [N_pad, d/8] uint8
    row_scale: jax.Array,  # [N_pad] f32
    row_vsq: jax.Array,    # [N_pad] f32
    valid: jax.Array,      # [N_pad] bool
    metric: MetricType,
) -> jax.Array:
    signs = unpack_bits_pm1(planes)  # [N, d_pad] bf16 (transient)
    qp = _pad_queries(queries, signs.shape[1])
    dots = jax.lax.dot_general(
        qp.astype(jnp.bfloat16), signs, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * row_scale[None, :]
    if metric is MetricType.L2:
        scores = -(sqnorms(queries)[:, None] - 2.0 * dots
                   + row_vsq[None, :])
    else:
        scores = dots
    return jnp.where(valid[None, :], scores, NEG_INF)


@functools.partial(jax.jit, static_argnames=("r", "metric", "topk_mode"))
def binary_scan_candidates(
    queries: jax.Array,    # [B, d] f32
    planes: jax.Array,     # [N_pad, d/8] uint8 packed sign planes
    row_scale: jax.Array,  # [N_pad] f32 per-row magnitude scale
    row_vsq: jax.Array,    # [N_pad] f32 ||approx||^2 (= d * scale^2)
    valid: jax.Array,      # [N_pad] bool
    r: int,
    metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """Stage-0 binary full scan: one unpack+matmul + fused top-r.

    Scores are the RaBitQ-style first-order estimate — selection-grade,
    not ranking-grade; downstream stages restore ordering. Shares the
    block-max selection machinery with the int8 scan."""
    scores = _binary_scores(queries, planes, row_scale, row_vsq, valid,
                            metric)
    return _select_topk(scores, r, topk_mode)


def _mirror_rescore(
    queries: jax.Array,   # [B, d] f32
    cand_i: jax.Array,    # [B, r0] i32 (-1 padding)
    approx8: jax.Array,   # [N_pad, d] int8 / [N_pad, d/2] packed int4
    m_scale: jax.Array,   # [N_pad] f32
    m_vsq: jax.Array,     # [N_pad] f32
    r1: int,
    metric: MetricType,
    storage: str,
) -> tuple[jax.Array, jax.Array]:
    """Stage 1: rescore the stage-0 candidates against the int8/int4
    mirror rows (gather + batched matvec) and keep the top r1."""
    safe = jnp.clip(cand_i, 0, approx8.shape[0] - 1)
    rows = approx8[safe]  # [B, r0, w]
    vals = rows.astype(jnp.bfloat16) if storage == "int8" \
        else unpack_int4(rows)
    dots = jax.lax.dot_general(
        queries.astype(jnp.bfloat16), vals, (((1,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * m_scale[safe]
    if metric is MetricType.L2:
        scores = -(sqnorms(queries)[:, None] - 2.0 * dots + m_vsq[safe])
    else:
        scores = dots
    scores = jnp.where(cand_i >= 0, scores, NEG_INF)
    r1 = min(r1, scores.shape[1])
    top_s, pos = jax.lax.top_k(scores, r1)
    ids = jnp.take_along_axis(cand_i, pos, axis=1)
    return top_s, jnp.where(jnp.isfinite(top_s), ids, -1)


@functools.partial(
    jax.jit, static_argnames=("r0", "r1", "metric", "topk_mode", "storage")
)
def binary_refine_candidates(
    queries: jax.Array,    # [B, d] f32
    planes: jax.Array,     # [N_pad, d/8] uint8
    row_scale: jax.Array,  # [N_pad] f32
    row_vsq: jax.Array,    # [N_pad] f32
    approx8: jax.Array,    # [N_pad, d] int8 / [N_pad, d/2] int4-packed
    m_scale: jax.Array,    # [N_pad] f32 mirror dequant scale
    m_vsq: jax.Array,      # [N_pad] f32 mirror ||approx||^2
    valid: jax.Array,      # [N_pad] bool
    r0: int,
    r1: int,
    metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
    storage: str = "int8",
) -> tuple[jax.Array, jax.Array]:
    """Stages 0+1 as ONE program: binary scan -> top r0 -> int8/int4
    mirror rescore -> top r1. The disk-store entry point: the returned
    candidates feed a host mmap gather + exact_rerank_gathered
    (index/_store_paths.rerank_against_store), the same stage-2 shape
    the int8 disk path already pays."""
    _, cand_i = binary_scan_candidates(
        queries, planes, row_scale, row_vsq, valid, r0, metric, topk_mode
    )
    return _mirror_rescore(
        queries, cand_i, approx8, m_scale, m_vsq, r1, metric, storage
    )


@functools.partial(
    jax.jit,
    static_argnames=("r0", "r1", "k", "scan_metric", "rerank_metric",
                     "topk_mode", "storage"),
)
def binary_refine_rerank(
    queries: jax.Array,      # [B, d] f32
    planes: jax.Array,       # [N_pad, d/8] uint8
    row_scale: jax.Array,    # [N_pad] f32
    row_vsq: jax.Array,      # [N_pad] f32
    approx8: jax.Array,      # [N_pad, d] int8 / [N_pad, d/2] int4-packed
    m_scale: jax.Array,      # [N_pad] f32
    m_vsq: jax.Array,        # [N_pad] f32
    valid: jax.Array,        # [N_pad] bool
    base: jax.Array,         # [capacity, d] raw store buffer
    base_sqnorm: jax.Array,  # [capacity] f32
    r0: int,
    r1: int,
    k: int,
    scan_metric: MetricType = MetricType.L2,
    rerank_metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
    storage: str = "int8",
) -> tuple[jax.Array, jax.Array]:
    """The fused three-stage program: binary scan -> int8/int4 rescore
    -> exact rerank, ONE dispatch for a RAM store (same rationale as
    ops/ivf.py int8_scan_rerank — every extra dispatch pays launch +
    tunnel latency, and the [B, r0]/[B, r1] candidate sets never leave
    the device). Only the final [B, k] pair is fetched."""
    from vearch_tpu.ops.ivf import exact_rerank

    _, cand_i = binary_refine_candidates(
        queries, planes, row_scale, row_vsq, approx8, m_scale, m_vsq,
        valid, r0, r1, scan_metric, topk_mode, storage,
    )
    return exact_rerank(queries.astype(base.dtype), cand_i, base,
                        base_sqnorm, k, rerank_metric)


# -- per-stage serving counters ----------------------------------------------
#
# Process-wide totals of three-stage serving work, rendered by the PS
# as zero-filled fixed-label metrics (vearch_ps_refine_searches_total /
# vearch_ps_refine_stage_rows_total) — fixed topology from the first
# scrape, so the cardinality soak stays flat while traffic warms the
# path mid-soak. Same single-module accumulator pattern as
# perf_model's h2d byte counter.

#: serving shapes of the three-stage chain (fixed metric label set)
REFINE_PATHS: tuple[str, ...] = ("fused", "disk", "mesh")
#: refinement stages (fixed metric label set)
REFINE_STAGES: tuple[str, ...] = ("binary", "int8", "exact")

_stage_lock = lockcheck.make_lock("binary_refine_stats")
_refine_searches: dict[str, int] = {p: 0 for p in REFINE_PATHS}
_refine_stage_rows: dict[str, int] = {s: 0 for s in REFINE_STAGES}


def note_refine_search(path: str, n_rows: int, r0: int, r1: int,
                       k: int, batch: int) -> None:
    """Account one three-stage search: serving shape + rows each stage
    scored (stage 0 scans the partition, stage 1 rescores r0, stage 2
    reranks r1 — all times the query batch)."""
    with _stage_lock:
        _refine_searches[path] = _refine_searches.get(path, 0) + 1
        _refine_stage_rows["binary"] += int(n_rows) * int(batch)
        _refine_stage_rows["int8"] += int(r0) * int(batch)
        _refine_stage_rows["exact"] += int(r1) * int(batch)


def refine_search_counts() -> dict[str, int]:
    with _stage_lock:
        return dict(_refine_searches)


def refine_stage_rows() -> dict[str, int]:
    with _stage_lock:
        return dict(_refine_stage_rows)


# compiled-program tracking (ops/perf_model.py): same rebind idiom as
# ops/ivf.py — the module globals become observing proxies so the
# compile-audit flight recorder sees cache growth on live calls.
for _name, _fn in (
    ("binary.scan_candidates", binary_scan_candidates),
    ("binary.refine_candidates", binary_refine_candidates),
    ("binary.refine_rerank", binary_refine_rerank),
):
    globals()[_name.split(".", 1)[1]] = register_jit(_name, _fn)

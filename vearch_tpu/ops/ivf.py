"""IVF probe-scan search kernels.

TPU-native re-design of the reference's IVF list scanning (reference:
index/impl/gamma_index_ivfflat.cc:198, gamma_index_ivfpq.h:1258 — there a
per-query CPU loop over inverted lists; here one jit'd program per query
batch). Layout contract (built by index/ivf.py on publish):

    centroids    [nlist, d]       coarse quantizer
    bucket_ids   [nlist, cap] i32 docid per slot, -1 = padding
    bucket_vecs  [nlist, cap, d]  (IVFFLAT) vectors grouped by cluster
    bucket_codes [nlist, cap, m]  (IVFPQ) uint8 PQ codes of residuals

Search structure: coarse top-nprobe as one matmul + top_k, then a
`lax.scan` over probe ranks. Each step gathers one bucket row per query
([B, cap, ...] — contiguous row DMA, the gather XLA handles well), scores
it (matvec batch on MXU for IVFFLAT; LUT gather for IVFPQ), masks
padding/deleted slots, and folds into a running [B, r] top-k via
concat + top_k. Candidates then get an exact rerank against the raw
device buffer — TPU keeps raw vectors resident anyway, so rerank is one
more gather+matmul and buys back the PQ recall loss (the reference's
fine-grained rerank via raw vectors, gamma_index_ivfpq.h).

Everything is static-shaped: nprobe/k/cap are trace-time constants;
per-request nprobe changes recompile once per distinct value (cached).
"""

from __future__ import annotations

import functools
import threading
import time

import jax
import jax.numpy as jnp

from vearch_tpu.engine.types import MetricType
from vearch_tpu.ops.distance import dot_precision, sqnorms
from vearch_tpu.ops.perf_model import register_jit

NEG_INF = float("-inf")

# Optional dispatch ledger: when a list (or ops/perf_model.PerfLedger)
# is installed here, index call sites append one tag per device-program
# launch. Lets tests prove the fused hot path really is ONE program
# where the unfused path is two (r4 review next-1: each dispatch pays
# tunnel RTT + scheduling; the CPU-backend trace test demonstrates the
# reduction when no TPU is reachable). The perf-model layer
# (ops/perf_model.py) aggregates these into the CI-asserted
# DOCUMENTED_DISPATCHES gate.
_dispatch_ledger: list | None = None


def set_dispatch_ledger(ledger: list | None) -> None:
    global _dispatch_ledger
    _dispatch_ledger = ledger


# Optional dispatch observer (obs/accounting installs one): called as
# observer(tag) from the SAME note_dispatch call that feeds the ledger
# and the per-request capture, so per-tenant dispatch counts reconcile
# with the global ledger exactly — same single-slot contract as
# perf_model.set_compile_observer.
_dispatch_observer = None


def set_dispatch_observer(fn) -> None:
    """Install (or clear, with None) the process-wide dispatch observer."""
    global _dispatch_observer
    _dispatch_observer = fn


# Per-request dispatch capture (observability tentpole): a thread-local
# recorder layered on top of the process-global ledger. The engine
# installs one per search so the profile/trace surface can report which
# device programs THIS request launched and roughly how long each took,
# without touching the index call sites (they keep calling
# note_dispatch). A tag's wall window closes at the next note_dispatch
# or at an explicit capture_mark()/end_capture() — on the CPU backend
# the blocking device_get sits inside that window, so the times are
# host-observed per-dispatch costs, not pure kernel times.
_capture_tls = threading.local()


class DispatchCapture:
    __slots__ = ("events", "mesh_phases", "tier_phases", "stage_phases")

    def __init__(self) -> None:
        # [tag, start_monotonic_s, end_monotonic_s | None] — consumers
        # (engine._record_dispatch_trace) anchor to the epoch via
        # utils.mono_us when emitting spans
        self.events: list[list] = []
        # (name, start_monotonic_s, end_monotonic_s) host-side windows
        # of the mesh serving path (shard placement, mask upload, ...)
        # — replayed by the engine as mesh.{name} phase spans
        self.mesh_phases: list[tuple[str, float, float]] = []
        # (name, start_monotonic_s, end_monotonic_s) host-side windows
        # of the tiered-storage path (demand fetch, prefetch schedule,
        # pin-set change) — replayed as tier.{name} phase spans
        self.tier_phases: list[tuple[str, float, float]] = []
        # (name, start_monotonic_s, end_monotonic_s) host-side windows
        # of the progressive-refinement path (bit-plane/mirror flush,
        # the fused refine dispatch, the disk stage-2 gather+rerank) —
        # replayed as stage.{name} phase spans
        self.stage_phases: list[tuple[str, float, float]] = []

    def note(self, tag: str) -> None:
        now = time.monotonic()
        if self.events and self.events[-1][2] is None:
            self.events[-1][2] = now
        self.events.append([tag, now, None])

    def mark(self) -> None:
        """Close the open dispatch window (call when device work for the
        current index.search has completed)."""
        if self.events and self.events[-1][2] is None:
            self.events[-1][2] = time.monotonic()

    @property
    def tags(self) -> list[str]:
        return [e[0] for e in self.events]


def begin_capture() -> DispatchCapture:
    cap = DispatchCapture()
    _capture_tls.capture = cap
    return cap


def capture_mark() -> None:
    cap = getattr(_capture_tls, "capture", None)
    if cap is not None:
        cap.mark()


def end_capture() -> DispatchCapture | None:
    cap = getattr(_capture_tls, "capture", None)
    _capture_tls.capture = None
    if cap is not None:
        cap.mark()
    return cap


def note_dispatch(tag: str) -> None:
    if _dispatch_ledger is not None:
        _dispatch_ledger.append(tag)
    obs = _dispatch_observer
    if obs is not None:
        obs(tag)
    cap = getattr(_capture_tls, "capture", None)
    if cap is not None:
        cap.note(tag)


def note_mesh_phase(name: str, t0: float, t1: float) -> None:
    """Record a host-side window of the mesh serving path (per-shard
    placement, mask upload) on the current request's capture — shows up
    as a mesh.{name} phase span next to the kernel.* dispatch spans."""
    cap = getattr(_capture_tls, "capture", None)
    if cap is not None:
        cap.mesh_phases.append((name, t0, t1))


def note_tier_phase(name: str, t0: float, t1: float) -> None:
    """Record a host-side window of the tiered-storage serving path
    (demand slab fetch, prefetch scheduling, pin-set recompute) on the
    current request's capture — shows up as a tier.{name} phase span
    next to the kernel.* dispatch spans. No-op off the request thread
    (the async prefetch worker has no capture installed)."""
    cap = getattr(_capture_tls, "capture", None)
    if cap is not None:
        cap.tier_phases.append((name, t0, t1))


def note_stage_phase(name: str, t0: float, t1: float) -> None:
    """Record a host-side window of the progressive-refinement serving
    path (index/binary.py three-stage chain) on the current request's
    capture — shows up as a stage.{name} phase span next to the
    kernel.* dispatch spans. No-op without an installed capture."""
    cap = getattr(_capture_tls, "capture", None)
    if cap is not None:
        cap.stage_phases.append((name, t0, t1))


def _coarse_probes(
    queries: jax.Array, centroids: jax.Array, nprobe: int
) -> jax.Array:
    """Top-nprobe cluster ids per query [B, nprobe]."""
    dots = jax.lax.dot_general(
        queries, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    # coarse assignment is L2 geometry for every metric (IP/cosine data is
    # normalized upstream, so nearest-centroid is still the right probe)
    scores = 2.0 * dots - sqnorms(centroids)[None, :]
    _, probes = jax.lax.top_k(scores, nprobe)
    return probes


def _fold_topk(
    best: tuple[jax.Array, jax.Array],
    scores: jax.Array,
    ids: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Fold a new [B, c] candidate block into the running [B, r] top list."""
    best_s, best_i = best
    s_cat = jnp.concatenate([best_s, scores], axis=1)
    i_cat = jnp.concatenate([best_i, ids], axis=1)
    top_s, pos = jax.lax.top_k(s_cat, best_s.shape[1])
    return top_s, jnp.take_along_axis(i_cat, pos, axis=1)


@functools.partial(jax.jit, static_argnames=("nprobe", "r", "metric"))
def ivfflat_candidates(
    queries: jax.Array,      # [B, d] (store dtype)
    centroids: jax.Array,    # [nlist, d] f32
    bucket_vecs: jax.Array,  # [nlist, cap, d] store dtype
    bucket_sqnorm: jax.Array,  # [nlist, cap] f32
    bucket_ids: jax.Array,   # [nlist, cap] i32
    valid: jax.Array,        # [n_pad] bool (docid-indexed)
    nprobe: int,
    r: int,
    metric: MetricType = MetricType.L2,
    probes: jax.Array | None = None,  # [B, nprobe] i32 (precomputed)
) -> tuple[jax.Array, jax.Array]:
    """Scan nprobe buckets per query; return top-r (scores, docids).

    `probes` overrides the in-kernel matmul selection — the HNSW coarse
    quantizer computes them on host (quantizer_type=hnsw)."""
    b = queries.shape[0]
    if probes is None:
        probes = _coarse_probes(
            queries.astype(jnp.float32), centroids, nprobe
        )  # [B, nprobe]
    nprobe = int(probes.shape[1])
    q_sq = sqnorms(queries)  # [B]

    init = (
        jnp.full((b, r), NEG_INF, jnp.float32),
        jnp.full((b, r), -1, jnp.int32),
    )

    def step(best, pr):
        c = probes[:, pr]  # [B]
        # c == -1 marks a padded probe slot (host HNSW selection came up
        # short): scan cell 0 for shape but mask every hit — scanning a
        # real cell twice would DUPLICATE its docids in the top-k
        cell_ok = c >= 0
        c = jnp.maximum(c, 0)
        vecs = bucket_vecs[c]  # [B, cap, d]
        ids = bucket_ids[c]  # [B, cap]
        vsq = bucket_sqnorm[c]  # [B, cap]
        dots = jax.lax.dot_general(
            queries, vecs, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
            precision=dot_precision(queries, vecs),
        )  # [B, cap]
        if metric is MetricType.L2:
            scores = -(q_sq[:, None] - 2.0 * dots + vsq)
        else:
            scores = dots
        ok = (ids >= 0) & valid[jnp.maximum(ids, 0)] & cell_ok[:, None]
        scores = jnp.where(ok, scores, NEG_INF)
        return _fold_topk(best, scores, ids), None

    (best_s, best_i), _ = jax.lax.scan(step, init, jnp.arange(nprobe))
    # masked slots keep -inf scores; null their ids so rerank skips them
    return best_s, jnp.where(jnp.isfinite(best_s), best_i, -1)


@functools.partial(jax.jit, static_argnames=("nprobe", "r", "metric"))
def ivfpq_candidates(
    queries: jax.Array,        # [B, d] f32
    centroids: jax.Array,      # [nlist, d] f32
    bucket_resid8: jax.Array,  # [nlist, cap, d] int8 (quantized PQ-decoded residuals)
    bucket_scale: jax.Array,   # [nlist] f32 per-cluster dequant scale
    bucket_vsq: jax.Array,     # [nlist, cap] f32 ||approx vector||^2
    bucket_ids: jax.Array,     # [nlist, cap] i32
    valid: jax.Array,          # [n_pad] bool
    nprobe: int,
    r: int,
    metric: MetricType = MetricType.L2,
    probes: jax.Array | None = None,  # [B, nprobe] i32 (precomputed)
) -> tuple[jax.Array, jax.Array]:
    """MXU-native IVFPQ scan.

    Design note (the one real departure from the reference's ADC): faiss's
    per-query LUT gather is a CPU-cache trick — on TPU it lowers to ~1e8
    scalar VPU gathers per batch and runs ~1000x slower than matmul
    (measured: 31s/batch at SIFT1M scale). The TPU-native formulation
    (cf. ScaNN's accelerator backends) decodes the PQ codes ONCE at
    publish time into int8-quantized residuals and scores buckets with an
    int8->bf16 matmul, which the MXU eats. PQ (m x nbits) remains the
    quantizer — recall characteristics match ADC; int8 is storage of the
    decoded approximation (quantization error ~1/254 of residual range,
    far below PQ error).

    Score decomposition per probed cluster c with approx vector
    v = cent_c + s_c * r8:
        q.v      = q.cent_c + s_c * (q.r8)
        L2 score = -(||q||^2 - 2 q.v + ||v||^2)   (||v||^2 precomputed)
        IP score = q.v
    """
    b = queries.shape[0]
    if probes is None:
        probes = _coarse_probes(queries, centroids, nprobe)  # [B, nprobe]
    nprobe = int(probes.shape[1])
    q_sq = sqnorms(queries)
    qb = queries.astype(jnp.bfloat16)

    init = (
        jnp.full((b, r), NEG_INF, jnp.float32),
        jnp.full((b, r), -1, jnp.int32),
    )

    def step(best, pr):
        c = probes[:, pr]  # [B]
        # padded probe slots (c == -1) scan cell 0 fully masked — see
        # the ivfflat step for why duplicates would otherwise leak
        cell_ok = c >= 0
        c = jnp.maximum(c, 0)
        cent = centroids[c]  # [B, d] f32
        resid8 = bucket_resid8[c]  # [B, cap, d] int8
        ids = bucket_ids[c]  # [B, cap]
        vsq = bucket_vsq[c]  # [B, cap]
        dot8 = jax.lax.dot_general(
            qb, resid8.astype(jnp.bfloat16), (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [B, cap]
        qc = jnp.sum(queries * cent, axis=1)  # [B]
        dots = qc[:, None] + bucket_scale[c][:, None] * dot8
        if metric is MetricType.L2:
            scores = -(q_sq[:, None] - 2.0 * dots + vsq)
        else:
            scores = dots
        ok = (ids >= 0) & valid[jnp.maximum(ids, 0)] & cell_ok[:, None]
        scores = jnp.where(ok, scores, NEG_INF)
        return _fold_topk(best, scores, ids), None

    (best_s, best_i), _ = jax.lax.scan(step, init, jnp.arange(nprobe))
    return best_s, jnp.where(jnp.isfinite(best_s), best_i, -1)


BLOCK = 512  # score-row block for the two-stage top-k (lane-aligned)


@functools.partial(jax.jit, static_argnames=("r", "metric", "topk_mode"))
def int8_scan_candidates(
    queries: jax.Array,    # [B, d] f32
    approx8: jax.Array,    # [N_pad, d] int8 docid-ordered quantized vectors
    row_scale: jax.Array,  # [N_pad] f32 per-row dequant scale
    row_vsq: jax.Array,    # [N_pad] f32 ||approx||^2
    valid: jax.Array,      # [N_pad] bool
    r: int,
    metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """Compressed full scan: one [B, d] x [d, N] int8 matmul + top-r.

    The default IVFPQ scan path: one big MXU matmul beats the per-query
    probe scan >10x at SIFT1M scale while reading 4x less HBM than the
    bf16 raw buffer.

    Top-r selection is two-stage "block-max" by default (topk_mode
    "auto"/"blockmax"; "exact" forces plain lax.top_k): a full
    lax.top_k over [B, 1M] f32 is a giant multi-pass sort (measured
    482ms of a 511ms scan at B=1024 on v5e — 94% of the kernel). Stage
    1 reduces each 512-wide block to its max (single pass over bf16
    scores) and picks candidate blocks per query; stage 2 sorts only
    the gathered blocks. Measured: 96ms vs 482ms at [1024, 1M], 5x.
    Candidates are approximate in the same sense as ADC itself (a doc
    shadowed by stronger block-maxes can drop out); the exact rerank
    stage restores ordering.

    PRECISION (r2 bench regression, recall 0.98 -> 0.70 on v5e): L2
    scores at SIFT-like magnitudes are ~1e3 with neighbor gaps of a few
    units; bf16's 8-bit mantissa rounds them to ±4, which is fine for
    *choosing blocks* but catastrophic for ranking candidates (XLA CPU
    constant-folds the bf16 round-trip away, so the loss only shows on
    real TPU). Stage 1 therefore stays bf16 (bandwidth-bound pass over
    the whole matrix) but over-selects 2x+8 blocks as rounding margin,
    and stage 2 gathers the chosen blocks from the f32 score matrix so
    final candidate ranking is exact.

    NOTE(perf): a chunked (scan-over-blocks) top-k was tried in r1 and
    measured WORSE (543ms -> 1227ms): many small matmul steps are
    dispatch-bound, and chunk padding copied the 4GB score matrix. The
    shape here keeps the single fused matmul and only restructures the
    selection.
    """
    dots8 = jax.lax.dot_general(
        queries.astype(jnp.bfloat16), approx8.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, N]
    dots = dots8 * row_scale[None, :]
    if metric is MetricType.L2:
        scores = -(sqnorms(queries)[:, None] - 2.0 * dots + row_vsq[None, :])
    else:
        scores = dots
    scores = jnp.where(valid[None, :], scores, NEG_INF)
    return _select_topk(scores, r, topk_mode)


def _select_topk(
    scores: jax.Array, r: int, topk_mode: str
) -> tuple[jax.Array, jax.Array]:
    """Shared block-max / exact top-r selection over a [B, N] score
    matrix (see int8_scan_candidates docstring for the design note)."""
    b, n_pad = scores.shape
    r = min(r, n_pad)
    nb = max(32, r // 4)
    nblk = n_pad // BLOCK
    use_block = (
        n_pad % BLOCK == 0
        and nblk >= 1
        and (topk_mode == "blockmax"
             or (topk_mode == "auto" and nblk >= nb * 4))
    )
    if not use_block:
        top_s, ids = jax.lax.top_k(scores, r)
    else:
        # 2x + 8 over-selection absorbs bf16 rounding of the block maxima
        nb = min(2 * nb + 8, nblk)
        s3f = scores.reshape(b, nblk, BLOCK)
        bmax = jnp.max(
            s3f.astype(jnp.bfloat16), axis=2
        ).astype(jnp.float32)  # [B, nblk]
        _, top_blocks = jax.lax.top_k(bmax, nb)  # [B, nb]
        # gather the chosen blocks at FULL precision for the final rank
        gathered = jnp.take_along_axis(s3f, top_blocks[:, :, None], axis=1)
        flat = gathered.reshape(b, nb * BLOCK)
        top_s, pos = jax.lax.top_k(flat, min(r, nb * BLOCK))
        ids = top_blocks[jnp.arange(b)[:, None], pos // BLOCK] * BLOCK \
            + pos % BLOCK
        ids = ids.astype(jnp.int32)
    # candidates that are really masked slots (filtered/deleted/padding)
    # carry -inf scores — mark their ids -1 so downstream rerank cannot
    # resurrect them with genuine similarity scores (bf16 stage scores
    # are selection-only; the rerank stage recomputes exact scores)
    return top_s, jnp.where(jnp.isfinite(top_s), ids, -1)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """[N, d/2] uint8 nibble-packed -> [N, d] bf16 signed values.

    Layout contract (index/int8_mirror.py quantize_rows_int4): dims
    [0, d/2) live in the LOW nibble, dims [d/2, d) in the HIGH nibble —
    a concat, not an interleave, so the unpack is two cheap vector ops
    and one concatenate that XLA fuses into the consuming matmul.
    """
    lo = (packed & 0xF).astype(jnp.int8)
    lo = lo - ((lo > 7) * jnp.int8(16))
    hi = (packed >> 4).astype(jnp.int8)
    hi = hi - ((hi > 7) * jnp.int8(16))
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("r", "metric", "topk_mode"))
def int4_scan_candidates(
    queries: jax.Array,    # [B, d] f32
    packed4: jax.Array,    # [N_pad, d/2] uint8 nibble-packed int4 rows
    row_scale: jax.Array,  # [N_pad] f32 per-row dequant scale
    row_vsq: jax.Array,    # [N_pad] f32 ||approx||^2
    valid: jax.Array,      # [N_pad] bool
    r: int,
    metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """int4 compressed full scan: the capacity tier of the int8 mirror.

    Halves the RESIDENT HBM footprint of the scan structure (the usual
    rows-per-chip limiter) at ~15-level quantization; the unpack to
    bf16 is transient work the MXU matmul absorbs, and the exact rerank
    stage recovers ordering exactly as it does for int8.
    """
    a = unpack_int4(packed4)  # [N, d] bf16
    dots4 = jax.lax.dot_general(
        queries.astype(jnp.bfloat16), a,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, N]
    dots = dots4 * row_scale[None, :]
    if metric is MetricType.L2:
        scores = -(sqnorms(queries)[:, None] - 2.0 * dots + row_vsq[None, :])
    else:
        scores = dots
    scores = jnp.where(valid[None, :], scores, NEG_INF)
    return _select_topk(scores, r, topk_mode)


@functools.partial(jax.jit, static_argnames=("r", "metric"))
def cached_bucket_scan(
    queries: jax.Array,     # [B, d] f32
    pool8: jax.Array,       # [slots, cap, d] int8 (HBM bucket cache)
    pool_scale: jax.Array,  # [slots, cap] f32 per-row dequant scale
    pool_vsq: jax.Array,    # [slots, cap] f32 ||approx||^2
    pool_ids: jax.Array,    # [slots, cap] i32 docids (-1 padding)
    probe_slots: jax.Array,  # [B, nprobe] i32 cache slot per probe
    valid: jax.Array,       # [n_pad] bool (docid-indexed)
    r: int,
    metric: MetricType = MetricType.L2,
) -> tuple[jax.Array, jax.Array]:
    """Probe scan over the HBM bucket cache (disk-tier search path).

    Identical math to `int8_scan_candidates` restricted to the probed
    slabs: rows are per-row-scaled int8 approximations of FULL vectors
    (not residuals), so score = f(q . row) with no centroid term. The
    slot indirection was resolved on host by HbmBucketCache; the kernel
    only ever sees static shapes [slots, cap, d], so one compile serves
    the whole life of a cache generation.
    """
    b = queries.shape[0]
    nprobe = probe_slots.shape[1]
    q_sq = sqnorms(queries)
    qb = queries.astype(jnp.bfloat16)

    init = (
        jnp.full((b, r), NEG_INF, jnp.float32),
        jnp.full((b, r), -1, jnp.int32),
    )

    def step(best, pr):
        s = probe_slots[:, pr]  # [B]
        # slot -1 marks a probe deferred to another fixed-shape pass
        # (multi-pass resolve when the probe set exceeds cache slots):
        # clamp the gather and mask the whole slab out of the fold
        slot_ok = s >= 0  # [B]
        s = jnp.maximum(s, 0)
        slab8 = pool8[s]  # [B, cap, d]
        ids = pool_ids[s]  # [B, cap]
        vsq = pool_vsq[s]
        dot8 = jax.lax.dot_general(
            qb, slab8.astype(jnp.bfloat16), (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [B, cap]
        dots = pool_scale[s] * dot8
        if metric is MetricType.L2:
            scores = -(q_sq[:, None] - 2.0 * dots + vsq)
        else:
            scores = dots
        ok = (ids >= 0) & valid[jnp.maximum(ids, 0)] & slot_ok[:, None]
        scores = jnp.where(ok, scores, NEG_INF)
        return _fold_topk(best, scores, ids), None

    (best_s, best_i), _ = jax.lax.scan(step, init, jnp.arange(nprobe))
    return best_s, jnp.where(jnp.isfinite(best_s), best_i, -1)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def exact_rerank_gathered(
    queries: jax.Array,    # [B, d] f32
    cand_ids: jax.Array,   # [B, r] i32 (-1 padding)
    cand_vecs: jax.Array,  # [B, r, d] f32 (host-gathered raw rows)
    k: int,
    metric: MetricType = MetricType.L2,
) -> tuple[jax.Array, jax.Array]:
    """Exact rerank when the raw base lives on disk: candidate rows were
    gathered host-side (mmap page faults) and ride up as one [B, r, d]
    blob — the only H2D traffic the disk tier pays per query batch."""
    dots = jax.lax.dot_general(
        queries, cand_vecs, (((1,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=dot_precision(queries, cand_vecs),
    )  # [B, r]
    vsq = jnp.sum(
        cand_vecs.astype(jnp.float32) ** 2, axis=2
    )
    if metric is MetricType.L2:
        scores = -(sqnorms(queries)[:, None] - 2.0 * dots + vsq)
    elif metric is MetricType.COSINE:
        qn = jnp.sqrt(jnp.maximum(sqnorms(queries), 1e-30))[:, None]
        vn = jnp.sqrt(jnp.maximum(vsq, 1e-30))
        scores = dots / (qn * vn)
    else:
        scores = dots
    scores = jnp.where(cand_ids >= 0, scores, NEG_INF)
    k = min(k, scores.shape[1])
    top_s, pos = jax.lax.top_k(scores, k)
    return top_s, jnp.take_along_axis(cand_ids, pos, axis=1)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def exact_rerank(
    queries: jax.Array,     # [B, d] (store dtype)
    cand_ids: jax.Array,    # [B, r] i32 (-1 padding)
    base: jax.Array,        # [capacity, d] store dtype (raw vector buffer)
    base_sqnorm: jax.Array,  # [capacity] f32
    k: int,
    metric: MetricType = MetricType.L2,
) -> tuple[jax.Array, jax.Array]:
    """Exact re-scoring of candidate docids against the raw device buffer.

    One row gather + batched matvec; recovers exact ordering (and exact
    user-facing scores) on top of ADC approximations.
    """
    safe = jnp.maximum(cand_ids, 0)
    vecs = base[safe]  # [B, r, d]
    vsq = base_sqnorm[safe]  # [B, r]
    dots = jax.lax.dot_general(
        queries, vecs, (((1,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=dot_precision(queries, vecs),
    )  # [B, r]
    if metric is MetricType.L2:
        scores = -(sqnorms(queries)[:, None] - 2.0 * dots + vsq)
    elif metric is MetricType.COSINE:
        qn = jnp.sqrt(jnp.maximum(sqnorms(queries), 1e-30))[:, None]
        vn = jnp.sqrt(jnp.maximum(vsq, 1e-30))
        scores = dots / (qn * vn)
    else:
        scores = dots
    scores = jnp.where(cand_ids >= 0, scores, NEG_INF)
    k = min(k, scores.shape[1])
    top_s, pos = jax.lax.top_k(scores, k)
    return top_s, jnp.take_along_axis(cand_ids, pos, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("r", "k", "scan_metric", "rerank_metric",
                     "topk_mode", "storage"),
)
def int8_scan_rerank(
    queries: jax.Array,      # [B, d] f32
    approx8: jax.Array,      # [N_pad, d] int8 (or [N_pad, d/2] int4-packed)
    row_scale: jax.Array,    # [N_pad] f32
    row_vsq: jax.Array,      # [N_pad] f32
    valid: jax.Array,        # [N_pad] bool
    base: jax.Array,         # [capacity, d] raw store buffer
    base_sqnorm: jax.Array,  # [capacity] f32
    r: int,
    k: int,
    scan_metric: MetricType = MetricType.L2,
    rerank_metric: MetricType = MetricType.L2,
    topk_mode: str = "auto",
    storage: str = "int8",
) -> tuple[jax.Array, jax.Array]:
    """Fused compressed scan + exact rerank: ONE device program per
    search instead of two (r4 review next-1 — each dispatch pays launch
    scheduling, and over the axon tunnel tens of ms of RTT; fusing also
    keeps the [B, r] candidate set entirely on device and lets XLA
    schedule the rerank gather against the scan's top-k tail).

    scan_metric is the compressed-domain metric (cosine scans as IP on
    pre-normalized rows); rerank_metric the user-facing one. Only the
    final [B, k] pair ever leaves the device."""
    scan = (int8_scan_candidates if storage == "int8"
            else int4_scan_candidates)
    _, cand_i = scan(queries, approx8, row_scale, row_vsq, valid,
                     r, scan_metric, topk_mode)
    return exact_rerank(queries.astype(base.dtype), cand_i, base,
                        base_sqnorm, k, rerank_metric)


# compiled-program tracking (ops/perf_model.py): every jitted search
# entry point registers here so tests can assert that repeated
# same-shape searches add ZERO new compiled programs — the retrace /
# compile-stall regression gate. The module global is rebound to the
# returned observing proxy so the compile-audit flight recorder sees
# cache growth on live calls (importers bind the proxy too: this runs
# before any `from ... import` of these names executes).
for _name, _fn in (
    ("ivf.ivfflat_candidates", ivfflat_candidates),
    ("ivf.ivfpq_candidates", ivfpq_candidates),
    ("ivf.int8_scan_candidates", int8_scan_candidates),
    ("ivf.int4_scan_candidates", int4_scan_candidates),
    ("ivf.cached_bucket_scan", cached_bucket_scan),
    ("ivf.exact_rerank", exact_rerank),
    ("ivf.exact_rerank_gathered", exact_rerank_gathered),
    ("ivf.int8_scan_rerank", int8_scan_rerank),
):
    globals()[_name.split(".", 1)[1]] = register_jit(_name, _fn)

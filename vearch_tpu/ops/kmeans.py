"""jit'd Lloyd's k-means for IVF coarse quantizer / PQ codebook training.

TPU-native replacement for faiss's Clustering used by the reference's IVF
index training (reference: engine.cc:1106 Indexing -> TrainIndex; faiss
kmeans). Design:

- assignment is a [chunk, k] distance matmul (MXU) + argmax;
- centroid update accumulates one-hot^T @ x per chunk inside a `lax.scan`
  so the full [n, k] distance matrix never materialises in HBM;
- empty clusters are reseeded from a fixed random sample of the data
  (faiss splits the largest cluster; reseeding is cheaper and jit-friendly);
- the whole training loop is one `lax.scan` over iterations: a single
  compiled program, no host round-trips.

`train_kmeans_sharded` (parallel/sharded.py) wraps `kmeans_step` in
shard_map with a psum over partial sums — the multi-chip training path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vearch_tpu.ops.distance import sqnorms


def _pad_to_multiple(x: jax.Array, multiple: int) -> tuple[jax.Array, jax.Array]:
    """Pad rows to a multiple; returns (padded, valid_mask)."""
    n = x.shape[0]
    rem = (-n) % multiple
    valid = jnp.arange(n + rem) < n
    if rem:
        x = jnp.concatenate([x, jnp.zeros((rem,) + x.shape[1:], x.dtype)], axis=0)
    return x, valid


@functools.partial(jax.jit, static_argnames=("chunk",))
def kmeans_partials(
    x: jax.Array,
    valid: jax.Array,
    centroids: jax.Array,
    chunk: int = 16384,
) -> tuple[jax.Array, jax.Array]:
    """One assignment pass: returns (sums [k, d], counts [k]) partial stats.

    x: [n, d] (n a multiple of `chunk`), valid: [n] bool mask for padding.
    Scanning chunks keeps peak memory at chunk*k f32.
    """
    k, d = centroids.shape
    n = x.shape[0]
    assert n % chunk == 0, "caller pads to chunk multiple"
    c_sq = sqnorms(centroids)  # [k]

    def body(carry, inp):
        sums, counts = carry
        xc, vc = inp
        # bf16 operands, f32 accumulation: assignment only needs to rank
        # centroids, and single-pass bf16 is ~6x faster than the HIGHEST
        # multi-pass f32 emulation at training scale; centroid *updates*
        # stay full f32 below
        dots = jax.lax.dot_general(
            xc.astype(jnp.bfloat16),
            centroids.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [chunk, k]
        # rank by -(||x||^2 - 2x.c + ||c||^2); ||x||^2 constant per row
        assign = jnp.argmax(2.0 * dots - c_sq[None, :], axis=1)  # [chunk]
        onehot = jax.nn.one_hot(assign, k, dtype=jnp.float32)
        onehot = onehot * vc[:, None].astype(jnp.float32)
        sums = sums + jax.lax.dot_general(
            onehot, xc.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
        )
        counts = counts + jnp.sum(onehot, axis=0)
        return (sums, counts), None

    init = (jnp.zeros((k, d), jnp.float32), jnp.zeros((k,), jnp.float32))
    xs = (x.reshape(n // chunk, chunk, d), valid.reshape(n // chunk, chunk))
    (sums, counts), _ = jax.lax.scan(body, init, xs)
    return sums, counts


def centroids_from_partials(
    sums: jax.Array, counts: jax.Array, reseed: jax.Array
) -> jax.Array:
    """New centroids from (psum'd) partial stats; empty clusters take a
    reseed row (a sampled data point) instead of collapsing to zero."""
    empty = counts < 0.5
    safe = jnp.maximum(counts, 1.0)[:, None]
    new = sums / safe
    return jnp.where(empty[:, None], reseed, new).astype(reseed.dtype)


@functools.partial(jax.jit, static_argnames=("k",))
def kmeanspp_init(key: jax.Array, x: jax.Array, k: int) -> jax.Array:
    """k-means++ seeding as a `lax.scan` over k draws.

    Each step samples the next centroid with probability proportional to the
    squared distance to the nearest already-chosen centroid — O(n*d) per
    step, one fused program, no host loop. Avoids the duplicated-seed local
    minima that plain random-subset init falls into.
    """
    n, d = x.shape
    xf = x.astype(jnp.float32)
    x_sq = sqnorms(xf)
    i0 = jax.random.randint(key, (), 0, n)
    c0 = xf[i0]
    min_d2 = jnp.maximum(x_sq - 2.0 * xf @ c0 + jnp.sum(c0 * c0), 0.0)
    cents0 = jnp.zeros((k, d), jnp.float32).at[0].set(c0)
    if k == 1:
        return cents0

    def body(carry, key_i):
        cents, min_d2, i = carry
        logits = jnp.log(jnp.maximum(min_d2, 1e-12))
        idx = jax.random.categorical(key_i, logits)
        c = xf[idx]
        cents = jax.lax.dynamic_update_index_in_dim(cents, c, i, axis=0)
        d2 = jnp.maximum(x_sq - 2.0 * xf @ c + jnp.sum(c * c), 0.0)
        return (cents, jnp.minimum(min_d2, d2), i + 1), None

    keys = jax.random.split(jax.random.fold_in(key, 7), k - 1)
    (cents, _, _), _ = jax.lax.scan(body, (cents0, min_d2, 1), keys)
    return cents


@functools.partial(jax.jit, static_argnames=("k", "iters", "chunk"))
def train_kmeans(
    x: jax.Array,
    k: int,
    iters: int = 10,
    seed: int = 0,
    chunk: int = 16384,
) -> jax.Array:
    """Full single-device k-means: returns centroids [k, d].

    k-means++ init, then `iters` Lloyd rounds in one `lax.scan`.
    Empty clusters reseed from a fixed random sample of the data.
    """
    n, d = x.shape
    x = x.astype(jnp.float32)
    key = jax.random.PRNGKey(seed)
    centroids = kmeanspp_init(key, x, k)

    chunk = min(chunk, max(256, n))
    xp, valid = _pad_to_multiple(x, chunk)

    reseed_perm = jax.random.choice(jax.random.fold_in(key, 1), n, shape=(k,),
                                    replace=n < k)
    reseed = x[reseed_perm]

    def step(c, _):
        sums, counts = kmeans_partials(xp, valid, c, chunk=chunk)
        return centroids_from_partials(sums, counts, reseed), None

    centroids, _ = jax.lax.scan(step, centroids, None, length=iters)
    return centroids


@functools.partial(jax.jit, static_argnames=("chunk",))
def assign_clusters(x: jax.Array, centroids: jax.Array, chunk: int = 16384) -> jax.Array:
    """Nearest-centroid assignment [n] (L2). The IVF coarse 'add' path
    (reference: IVFPQ add -> quantizer->assign)."""
    n, d = x.shape
    c_sq = sqnorms(centroids)
    chunk = min(chunk, max(256, n))
    xp, _ = _pad_to_multiple(x, chunk)

    def body(_, xc):
        dots = jax.lax.dot_general(
            xc.astype(jnp.bfloat16),
            centroids.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return None, jnp.argmax(2.0 * dots - c_sq[None, :], axis=1)

    _, assign = jax.lax.scan(body, None, xp.reshape(-1, chunk, d))
    return assign.reshape(-1)[:n]

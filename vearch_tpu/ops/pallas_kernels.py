"""Pallas TPU kernels for the hot data-dependent ops.

The jit'd XLA paths in ops/ivf.py cover the dense-scan regimes; what XLA
cannot do well is *data-dependent* block movement — e.g. the IVF probe
scan, where each (query, probe-rank) step needs a different bucket row
from HBM. XLA lowers that to a batched gather + batched matvec that
materialises [B, cap, d] per probe step (measured 905 ms / 256-query
batch at SIFT1M scale). The Pallas kernel here instead uses
`PrefetchScalarGridSpec`: the probe table is scalar-prefetched, the
bucket block index_map reads it to DMA exactly the probed bucket into
VMEM (double-buffered across grid steps by the pallas pipeline), and the
MXU scores it — one pass over exactly the probed data.

Falls back to interpret mode off-TPU (the CPU test mesh), so the same
code path is exercised everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _probe_dots_kernel(probes_ref, q_ref, bucket_ref, out_ref):
    """One grid step (i=query, j=probe rank): score query i against its
    j-th probed bucket.

    probes_ref: scalar-prefetched [B, nprobe] i32 (consumed by the
    index_maps; unused in the body). q_ref: [1, 1, d] (query i's row);
    bucket_ref: [1, cap, d] int8 (the DMA'd probed bucket);
    out_ref: [1, nprobe, cap] f32 (query i's output row, persistent across
    the inner j steps).
    """
    j = pl.program_id(1)
    q = q_ref[0]  # [1, d] bf16
    bucket = bucket_ref[0]  # [cap, d] int8
    dots = jax.lax.dot_general(
        q, bucket.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [1, cap]
    out_ref[0, pl.ds(j, 1), :] = dots


@functools.partial(jax.jit, static_argnames=())
def ivf_probe_dots(
    queries: jax.Array,        # [B, d] bf16/f32
    probes: jax.Array,         # [B, nprobe] i32
    bucket_resid8: jax.Array,  # [nlist, cap, d] int8
) -> jax.Array:
    """Raw dot products q . resid8 for every probed bucket: [B, nprobe, cap].

    Score assembly (dequant scale, centroid term, norms, masking, top-k)
    stays in XLA — it's elementwise over the output and fuses fine; the
    kernel exists purely to make the data-dependent bucket reads
    pipeline-DMA instead of a materialised gather.
    """
    b, d = queries.shape
    nprobe = probes.shape[1]
    nlist, cap, _ = bucket_resid8.shape
    qb = queries.astype(jnp.bfloat16)[:, None, :]  # [B, 1, d]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nprobe),
        in_specs=[
            # query i's row; (1, 1, d) keeps Mosaic's tile alignment happy
            pl.BlockSpec((1, 1, d), lambda i, j, probes_ref: (i, 0, 0)),
            # data-dependent block: DMA the bucket this (query, rank)
            # step probes — the whole point of the scalar prefetch
            pl.BlockSpec(
                (1, cap, d),
                lambda i, j, probes_ref: (probes_ref[i, j], 0, 0),
            ),
        ],
        # one output row per query, persistent across the inner j loop.
        # A slimmer (1, 1, cap) per-step block does not compile: Mosaic
        # requires the second-to-last block dim to divide 8 or equal the
        # array dim, and this nprobe-row block is the smallest legal one.
        out_specs=pl.BlockSpec(
            (1, nprobe, cap), lambda i, j, probes_ref: (i, 0, 0)
        ),
    )
    return pl.pallas_call(
        _probe_dots_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nprobe, cap), jnp.float32),
        interpret=_interpret(),
    )(probes, qb, bucket_resid8)


@functools.partial(jax.jit, static_argnames=("nprobe", "r", "l2"))
def ivfpq_probe_search_pallas(
    queries: jax.Array,        # [B, d] f32
    centroids: jax.Array,      # [nlist, d] f32
    bucket_resid8: jax.Array,  # [nlist, cap, d] int8
    bucket_scale: jax.Array,   # [nlist] f32
    bucket_vsq: jax.Array,     # [nlist, cap] f32
    bucket_ids: jax.Array,     # [nlist, cap] i32
    valid: jax.Array,          # [n_pad] bool
    nprobe: int,
    r: int,
    l2: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Full probe-mode IVFPQ search: coarse probe selection + pallas bucket
    scoring + top-k, one jitted program.

    The [B, nlist] query-centroid dot matrix is computed once and reused
    for both probe selection and the q.cent_c score term.

    Score decomposition per probed cluster c (approx v = cent_c + s_c*r8):
        q.v = q.cent_c + s_c * (q.r8);  L2 = -(|q|^2 - 2 q.v + |v|^2)
    """
    from vearch_tpu.ops.distance import sqnorms

    b, d = queries.shape
    qc = jax.lax.dot_general(
        queries, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [B, nlist]
    # coarse assignment is L2 geometry (see ops/ivf.py _coarse_probes)
    coarse = 2.0 * qc - sqnorms(centroids)[None, :]
    _, probes = jax.lax.top_k(coarse, nprobe)  # [B, nprobe]
    dots8 = ivf_probe_dots(queries, probes, bucket_resid8)  # [B, np, cap]
    qc_p = jnp.take_along_axis(qc, probes, axis=1)  # [B, nprobe]
    scale_p = bucket_scale[probes]  # [B, nprobe]
    dots = qc_p[:, :, None] + scale_p[:, :, None] * dots8
    vsq_p = bucket_vsq[probes]  # [B, nprobe, cap]
    ids_p = bucket_ids[probes]  # [B, nprobe, cap]
    if l2:
        scores = -(sqnorms(queries)[:, None, None] - 2.0 * dots + vsq_p)
    else:
        scores = dots
    ok = (ids_p >= 0) & valid[jnp.maximum(ids_p, 0)]
    scores = jnp.where(ok, scores, -jnp.inf)
    flat_s = scores.reshape(b, nprobe * bucket_resid8.shape[1])
    flat_i = ids_p.reshape(b, nprobe * bucket_resid8.shape[1])
    r = min(r, flat_s.shape[1])
    top_s, pos = jax.lax.top_k(flat_s, r)
    return top_s, jnp.take_along_axis(flat_i, pos, axis=1)

"""Pallas TPU kernels for the hot data-dependent ops.

The jit'd XLA paths in ops/ivf.py cover the dense-scan regimes; what XLA
cannot do well is *data-dependent* block movement — e.g. the IVF probe
scan, where each (query, probe-rank) step needs a different bucket row
from HBM. XLA lowers that to a batched gather + batched matvec that
materialises [B, cap, d] per probe step (measured 905 ms / 256-query
batch at SIFT1M scale). The Pallas kernel here instead uses
`PrefetchScalarGridSpec`: the probe table is scalar-prefetched, the
bucket block index_map reads it to DMA exactly the probed bucket into
VMEM (double-buffered across grid steps by the pallas pipeline), and the
MXU scores it — one pass over exactly the probed data.

Falls back to interpret mode off-TPU (the CPU test mesh), so the same
code path is exercised everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _probe_dots_kernel(probes_ref, q_ref, bucket_ref, out_ref):
    """One grid step (i=query, j=probe rank): score query i against its
    j-th probed bucket.

    probes_ref: scalar-prefetched [B, nprobe] i32 (consumed by the
    index_maps; unused in the body). q_ref: [1, 1, d] (query i's row);
    bucket_ref: [1, cap, d] int8 (the DMA'd probed bucket);
    out_ref: [1, nprobe, cap] f32 (query i's output row, persistent across
    the inner j steps).
    """
    j = pl.program_id(1)
    q = q_ref[0]  # [1, d] bf16
    bucket = bucket_ref[0]  # [cap, d] int8
    dots = jax.lax.dot_general(
        q, bucket.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [1, cap]
    out_ref[0, pl.ds(j, 1), :] = dots


@functools.partial(jax.jit, static_argnames=())
def ivf_probe_dots(
    queries: jax.Array,        # [B, d] bf16/f32
    probes: jax.Array,         # [B, nprobe] i32
    bucket_resid8: jax.Array,  # [nlist, cap, d] int8
) -> jax.Array:
    """Raw dot products q . resid8 for every probed bucket: [B, nprobe, cap].

    Score assembly (dequant scale, centroid term, norms, masking, top-k)
    stays in XLA — it's elementwise over the output and fuses fine; the
    kernel exists purely to make the data-dependent bucket reads
    pipeline-DMA instead of a materialised gather.
    """
    b, d = queries.shape
    nprobe = probes.shape[1]
    nlist, cap, _ = bucket_resid8.shape
    qb = queries.astype(jnp.bfloat16)[:, None, :]  # [B, 1, d]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nprobe),
        in_specs=[
            # query i's row; (1, 1, d) keeps Mosaic's tile alignment happy
            pl.BlockSpec((1, 1, d), lambda i, j, probes_ref: (i, 0, 0)),
            # data-dependent block: DMA the bucket this (query, rank)
            # step probes — the whole point of the scalar prefetch
            pl.BlockSpec(
                (1, cap, d),
                lambda i, j, probes_ref: (probes_ref[i, j], 0, 0),
            ),
        ],
        # one output row per query, persistent across the inner j loop.
        # A slimmer (1, 1, cap) per-step block does not compile: Mosaic
        # requires the second-to-last block dim to divide 8 or equal the
        # array dim, and this nprobe-row block is the smallest legal one.
        out_specs=pl.BlockSpec(
            (1, nprobe, cap), lambda i, j, probes_ref: (i, 0, 0)
        ),
    )
    return pl.pallas_call(
        _probe_dots_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nprobe, cap), jnp.float32),
        interpret=_interpret(),
    )(probes, qb, bucket_resid8)


@functools.partial(jax.jit, static_argnames=("nprobe", "r", "l2"))
def ivfpq_probe_search_pallas(
    queries: jax.Array,        # [B, d] f32
    centroids: jax.Array,      # [nlist, d] f32
    bucket_resid8: jax.Array,  # [nlist, cap, d] int8
    bucket_scale: jax.Array,   # [nlist] f32
    bucket_vsq: jax.Array,     # [nlist, cap] f32
    bucket_ids: jax.Array,     # [nlist, cap] i32
    valid: jax.Array,          # [n_pad] bool
    nprobe: int,
    r: int,
    l2: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Full probe-mode IVFPQ search: coarse probe selection + pallas bucket
    scoring + top-k, one jitted program.

    The [B, nlist] query-centroid dot matrix is computed once and reused
    for both probe selection and the q.cent_c score term.

    Score decomposition per probed cluster c (approx v = cent_c + s_c*r8):
        q.v = q.cent_c + s_c * (q.r8);  L2 = -(|q|^2 - 2 q.v + |v|^2)
    """
    from vearch_tpu.ops.distance import sqnorms

    b, d = queries.shape
    qc = jax.lax.dot_general(
        queries, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [B, nlist]
    # coarse assignment is L2 geometry (see ops/ivf.py _coarse_probes)
    coarse = 2.0 * qc - sqnorms(centroids)[None, :]
    _, probes = jax.lax.top_k(coarse, nprobe)  # [B, nprobe]
    dots8 = ivf_probe_dots(queries, probes, bucket_resid8)  # [B, np, cap]
    qc_p = jnp.take_along_axis(qc, probes, axis=1)  # [B, nprobe]
    scale_p = bucket_scale[probes]  # [B, nprobe]
    dots = qc_p[:, :, None] + scale_p[:, :, None] * dots8
    vsq_p = bucket_vsq[probes]  # [B, nprobe, cap]
    ids_p = bucket_ids[probes]  # [B, nprobe, cap]
    if l2:
        scores = -(sqnorms(queries)[:, None, None] - 2.0 * dots + vsq_p)
    else:
        scores = dots
    ok = (ids_p >= 0) & valid[jnp.maximum(ids_p, 0)]
    scores = jnp.where(ok, scores, -jnp.inf)
    flat_s = scores.reshape(b, nprobe * bucket_resid8.shape[1])
    flat_i = ids_p.reshape(b, nprobe * bucket_resid8.shape[1])
    r = min(r, flat_s.shape[1])
    top_s, pos = jax.lax.top_k(flat_s, r)
    return top_s, jnp.take_along_axis(flat_i, pos, axis=1)


# -- fused block-max int8 full scan (r4 review next-7) -----------------------
#
# The XLA full-scan path (ops/ivf.py int8_scan_candidates) materialises
# the [B, N] f32 score matrix in HBM (4 GB at 1024 x 1M), then re-reads
# it for the block-max stage-1 and again for the stage-2 gather. This
# kernel computes scores tile-by-tile in VMEM and writes ONLY the
# [B, N/512] block maxima — one pass over the int8 rows, no score
# matrix. Stage 2 (XLA, same jit) re-scores just the chosen blocks at
# f32 — identical candidate semantics to the XLA block-max path.
# Gated behind IndexParams scan_kernel="pallas" for hardware A/B
# (scripts/benchmarks/pallas_ab.py is the microbench hook).

_SCAN_TB = 8      # query rows per tile (pads B up; small batches stay cheap)
_SCAN_TN = 2048   # db rows per tile (int8 tile = TN*d bytes in VMEM)
_SCAN_BLOCK = 512  # must match ops/ivf.py BLOCK


def _blockmax_kernel(q_ref, rows_ref, scale_ref, vsq_ref, valid_ref,
                     qsq_ref, bmax_ref, l2: bool):
    """One (query-tile, row-tile) grid step: score [TB, TN] in VMEM,
    reduce to per-512-block maxima [TB, TN/512]."""
    q = q_ref[...]          # [TB, d] bf16
    rows = rows_ref[...]    # [TN, d] int8
    dots = jax.lax.dot_general(
        q, rows.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TB, TN]
    dots = dots * scale_ref[...][None, :]
    if l2:
        scores = -(qsq_ref[...][:, None] - 2.0 * dots
                   + vsq_ref[...][None, :])
    else:
        scores = dots
    scores = jnp.where(valid_ref[...][None, :] != 0, scores,
                       jnp.float32(-3.4e38))
    tb = scores.shape[0]
    nb = scores.shape[1] // _SCAN_BLOCK
    # bf16 block maxima — same precision contract as the XLA stage 1
    # (selection-only; stage 2 re-ranks at f32)
    bmax = jnp.max(
        scores.reshape(tb, nb, _SCAN_BLOCK).astype(jnp.bfloat16), axis=2
    ).astype(jnp.float32)
    bmax_ref[...] = bmax


@functools.partial(
    jax.jit, static_argnames=("r", "l2", "interpret_override")
)
def int8_blockmax_scan_pallas(
    queries: jax.Array,    # [B, d] f32
    approx8: jax.Array,    # [N_pad, d] int8, N_pad % 512 == 0
    row_scale: jax.Array,  # [N_pad] f32
    row_vsq: jax.Array,    # [N_pad] f32
    valid: jax.Array,      # [N_pad] bool
    r: int,
    l2: bool = True,
    interpret_override: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused one-pass block-max int8 scan + top-r candidates.

    Semantics match ops/ivf.py _select_topk's block-max mode: bf16
    block maxima with 2x+8 over-selection choose candidate blocks, the
    chosen blocks re-rank at f32. Returns ([B, r] scores, [B, r] ids;
    -1 for masked)."""
    b, d = queries.shape
    n_pad = approx8.shape[0]
    assert n_pad % _SCAN_BLOCK == 0, n_pad
    nblk = n_pad // _SCAN_BLOCK
    tb = _SCAN_TB
    b_pad = -(-b // tb) * tb
    qf = queries.astype(jnp.float32)
    if b_pad != b:
        qf = jnp.pad(qf, ((0, b_pad - b), (0, 0)))
    qsq = jnp.sum(qf * qf, axis=1)
    # lane alignment: Mosaic requires the last block dim to be a
    # 128-multiple on real TPU; d=100 (glove regime) would fail to
    # compile. Zero-pad the feature dim for the KERNEL inputs only —
    # zeros contribute nothing to the dots, and stage 2 gathers from
    # the original unpadded mirror.
    d_pad = -(-d // 128) * 128
    qk = qf
    a8k = approx8
    if d_pad != d:
        qk = jnp.pad(qf, ((0, 0), (0, d_pad - d)))
        a8k = jnp.pad(approx8, ((0, 0), (0, d_pad - d)))
    # tn must DIVIDE n_pad or the grid truncates (rows past the last
    # full tile never scanned, their bmax columns uninitialized — review
    # r5). Mirror capacity is 512-aligned, so 512 always divides; prefer
    # the largest power-of-two tile that fits.
    tn = _SCAN_BLOCK
    for cand in (_SCAN_TN, _SCAN_TN // 2, _SCAN_TN // 4):
        if cand <= n_pad and n_pad % cand == 0:
            tn = cand
            break
    interp = _interpret() if interpret_override is None \
        else interpret_override

    grid = (b_pad // tb, n_pad // tn)
    bmax = pl.pallas_call(
        functools.partial(_blockmax_kernel, l2=l2),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, d_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((tn, d_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((tn,), lambda i, j: (j,)),
            pl.BlockSpec((tn,), lambda i, j: (j,)),
            pl.BlockSpec((tn,), lambda i, j: (j,)),
            pl.BlockSpec((tb,), lambda i, j: (i,)),
        ],
        out_specs=pl.BlockSpec(
            (tb, tn // _SCAN_BLOCK), lambda i, j: (i, j)
        ),
        out_shape=jax.ShapeDtypeStruct((b_pad, nblk), jnp.float32),
        interpret=interp,
    )(qk.astype(jnp.bfloat16), a8k, row_scale, row_vsq,
      valid.astype(jnp.int8), qsq)
    bmax = bmax[:b]

    # -- stage 2 (XLA): over-select blocks, re-score them at f32.
    # Chunked over queries: the [chunk, S, d] int8 gather is the peak
    # HBM consumer (review r5 — at B=1024/r=128/d=128 an unchunked
    # gather is ~4.8 GB, defeating the kernel's memory win); 32-query
    # chunks bound it to ~150 MB while total traffic is unchanged. The
    # chunk loop is a lax.scan, NOT an unrolled Python loop: unrolled,
    # the program size and compile time grew linearly with batch
    # (32 chunk bodies at B=1024 — VERDICT weak #7); the scan compiles
    # ONE chunk body regardless of B. The kernel's sweet spot is
    # small-to-mid batches — at very large B the XLA path's
    # materialized score matrix amortizes better; that is exactly what
    # the pallas_ab.py hardware A/B decides.
    r_eff = min(r, n_pad)
    nb_sel = max(32, r_eff // 4)
    nb_sel = min(2 * nb_sel + 8, nblk)
    _, top_blocks = jax.lax.top_k(bmax, nb_sel)  # [B, nb_sel]
    qsq_b = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1)
    chunk = min(32, b)
    rr = min(r_eff, nb_sel * _SCAN_BLOCK)
    # pad B up to a chunk multiple: every row's math is independent
    # (batched matvec + row-wise top_k), so the padded rows change
    # nothing for real rows and are sliced off below
    b2 = -(-b // chunk) * chunk
    qs2 = queries.astype(jnp.float32)
    if b2 != b:
        qs2 = jnp.pad(qs2, ((0, b2 - b), (0, 0)))
        qsq_b = jnp.pad(qsq_b, (0, b2 - b))
        top_blocks = jnp.pad(top_blocks, ((0, b2 - b), (0, 0)))
    offs = jnp.arange(_SCAN_BLOCK, dtype=top_blocks.dtype)

    def _stage2(carry, inp):
        q_c, qsq_c, blocks_c = inp  # [chunk, d], [chunk], [chunk, nb_sel]
        idx = (blocks_c[:, :, None] * _SCAN_BLOCK
               + offs[None, None, :]).reshape(
                   chunk, nb_sel * _SCAN_BLOCK)
        vecs = approx8[idx]          # [chunk, S, d] int8
        dots = jax.lax.dot_general(
            q_c.astype(jnp.bfloat16), vecs.astype(jnp.bfloat16),
            (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [chunk, S]
        dots = dots * row_scale[idx]
        if l2:
            scores = -(qsq_c[:, None] - 2.0 * dots + row_vsq[idx])
        else:
            scores = dots
        scores = jnp.where(valid[idx], scores, -jnp.inf)
        top_s, pos = jax.lax.top_k(scores, rr)
        return carry, (top_s, jnp.take_along_axis(idx, pos, axis=1))

    nchunks = b2 // chunk
    _, (top_s, ids) = jax.lax.scan(
        _stage2, None,
        (qs2.reshape(nchunks, chunk, d),
         qsq_b.reshape(nchunks, chunk),
         top_blocks.reshape(nchunks, chunk, nb_sel)),
    )
    top_s = top_s.reshape(b2, rr)[:b]
    ids = ids.reshape(b2, rr)[:b].astype(jnp.int32)
    return top_s, jnp.where(jnp.isfinite(top_s), ids, -1)

"""Product quantization: codebook training, encoding, ADC lookup.

TPU-native replacement for faiss ProductQuantizer as used by the reference's
IVFPQ index (reference: index/impl/gamma_index_ivfpq.h:1258 GammaIVFPQIndex).

Layout choices for TPU:
- codebooks: [m, ksub, dsub] f32 — trained by a vmap'd k-means (all m
  subquantizers train in one compiled program);
- codes: [n, m] uint8 — 16-32x HBM traffic reduction vs raw f32 vectors,
  which is the entire point on a bandwidth-bound chip;
- ADC: per-query lookup tables [B, m, ksub], scores via take_along_axis
  gather + sum over m. XLA lowers the gather to dynamic-slice-friendly
  code; candidate sets come from IVF probing so n_candidates stays in the
  tens of thousands, keeping the gather cheap relative to the LUT matmul.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vearch_tpu.ops import kmeans as km
from vearch_tpu.ops.distance import sqnorms


def train_pq(
    x: jax.Array, m: int, ksub: int = 256, iters: int = 10, seed: int = 0
) -> jax.Array:
    """Train m subquantizer codebooks on x [n, d]; returns [m, ksub, dsub].

    vmap over subspaces: one XLA program trains all m codebooks.
    """
    n, d = x.shape
    assert d % m == 0, f"dim {d} not divisible by m={m}"
    assert 2 <= ksub <= 256, f"ksub={ksub} must fit uint8 codes"
    dsub = d // m
    sub = jnp.moveaxis(x.reshape(n, m, dsub), 1, 0)  # [m, n, dsub]
    train = functools.partial(km.train_kmeans, k=ksub, iters=iters, seed=seed)
    return jax.vmap(train)(sub)


@jax.jit
def encode_pq(x: jax.Array, codebooks: jax.Array) -> jax.Array:
    """Encode x [n, d] -> codes [n, m] uint8."""
    n, d = x.shape
    m, ksub, dsub = codebooks.shape
    assert ksub <= 256, f"ksub={ksub} would wrap around in uint8 codes"
    sub = jnp.moveaxis(x.reshape(n, m, dsub), 1, 0)  # [m, n, dsub]
    assign = jax.vmap(km.assign_clusters)(sub, codebooks)  # [m, n]
    return assign.T.astype(jnp.uint8)


def decode_pq_np(codes: "np.ndarray", codebooks) -> "np.ndarray":
    """Numpy PQ decode for host-side paths (absorb/publish): avoids a
    jit dispatch + recompile per distinct batch shape and a device
    round trip per call — the codebook gather is tiny on host."""
    import numpy as np

    cb = np.asarray(codebooks)  # [m, ksub, dsub]
    m = cb.shape[0]
    return cb[
        np.arange(m)[None, :], np.asarray(codes).astype(np.int64), :
    ].reshape(codes.shape[0], -1)


@jax.jit
def decode_pq(codes: jax.Array, codebooks: jax.Array) -> jax.Array:
    """Reconstruct [n, d] from codes [n, m] (for rerank / tests)."""
    m, ksub, dsub = codebooks.shape
    picked = jnp.take_along_axis(
        codebooks[None],  # [1, m, ksub, dsub]
        codes.astype(jnp.int32)[:, :, None, None],  # [n, m, 1, 1]
        axis=2,
    )  # [n, m, 1, dsub]
    return picked.reshape(codes.shape[0], m * dsub)


@jax.jit
def adc_lut_l2(queries: jax.Array, codebooks: jax.Array) -> jax.Array:
    """Squared-L2 lookup tables [B, m, ksub] for ADC.

    lut[b, j, c] = || q_b[sub j] - codebooks[j, c] ||^2, computed as a
    batched matmul over subspaces (MXU) + norms.
    """
    b, d = queries.shape
    m, ksub, dsub = codebooks.shape
    qsub = jnp.moveaxis(queries.reshape(b, m, dsub), 1, 0)  # [m, b, dsub]
    dots = jax.lax.dot_general(
        qsub.astype(jnp.float32), codebooks.astype(jnp.float32),
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [m, b, ksub]
    q_sq = sqnorms(qsub)  # [m, b]
    c_sq = sqnorms(codebooks)  # [m, ksub]
    lut = q_sq[:, :, None] - 2.0 * dots + c_sq[:, None, :]
    return jnp.moveaxis(lut, 0, 1)  # [B, m, ksub]


@jax.jit
def adc_lut_ip(queries: jax.Array, codebooks: jax.Array) -> jax.Array:
    """Inner-product lookup tables [B, m, ksub] (higher = better)."""
    b, d = queries.shape
    m, ksub, dsub = codebooks.shape
    qsub = jnp.moveaxis(queries.reshape(b, m, dsub), 1, 0)
    dots = jax.lax.dot_general(
        qsub.astype(jnp.float32), codebooks.astype(jnp.float32),
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return jnp.moveaxis(dots, 0, 1)


@jax.jit
def adc_scores(lut: jax.Array, codes: jax.Array) -> jax.Array:
    """ADC distances from per-query LUTs.

    lut: [B, m, ksub]; codes: [..., m] uint8 — either [N, m] (shared
    candidate set) or [B, N, m] (per-query candidates from IVF probing).
    Returns [B, N] summed table values in the LUT's own orientation:
    L2 distances (lower = better) for `adc_lut_l2`, raw inner products
    (higher = better) for `adc_lut_ip`.
    """
    c = codes.astype(jnp.int32)
    if c.ndim == 2:
        c = c[None]  # shared candidate set broadcasts over queries
    picked = jnp.take_along_axis(
        lut[:, None, :, :],  # [B, 1, m, ksub]
        c[:, :, :, None],  # [B|1, N, m, 1]
        axis=3,
    )[..., 0]
    return jnp.sum(picked, axis=-1)  # [B, N]

"""Score-aware (anisotropic) product quantization — the ScaNN technique.

The reference ships SCANN as the `VEARCH` index type wrapping Google's
ScaNN library (reference: index/impl/scann/gamma_index_vearch.cc:20
REGISTER_MODEL(VEARCH, ...), scann_api.h), whose core idea is the
anisotropic quantization loss of Guo et al. 2020: for MIPS, quantization
error *parallel* to the datapoint costs recall far more than orthogonal
error, because high-scoring queries point along the datapoint. So instead
of plain reconstruction MSE, codebooks minimise

    l(x, x~) = h_par * ||P_x (x - x~)||^2 + h_orth * ||(I - P_x)(x - x~)||^2

with eta = h_par / h_orth derived from the noise-shaping threshold T as
eta = (d - 1) T^2 / (1 - T^2) (paper Thm 3.2; the reference exposes T as
`ns_threshold`, default 0.2).

This is an independent TPU-native implementation, not a ScaNN wrap:
everything is batched matmuls + segment-sums under jit, trained by block
coordinate descent over subspaces. The coupling term (the parallel
component mixes all subspaces) is carried as two running scalars per
point — S = ||x - x~||^2 and a = (x - x~) . u — so each subspace pass
costs one [n, ksub] matmul pair, and the codeword update is a batched
[dsub, dsub] linear solve with a per-codeword direction scatter matrix.

Downstream is untouched: anisotropic codebooks drop into the same
decode -> int8 mirror -> MXU scan -> exact rerank path as IVFPQ.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vearch_tpu.ops import pq as pq_ops


def eta_from_threshold(t: float, d: int) -> float:
    """Anisotropic weight ratio h_par/h_orth from noise-shaping
    threshold T (reference default ns_threshold=0.2)."""
    t = float(t)
    if t <= 0.0:
        return 1.0  # degenerates to plain reconstruction MSE
    t = min(t, 0.999)
    return (d - 1) * t * t / (1.0 - t * t)


def _split(x: jax.Array, m: int) -> jax.Array:
    n, d = x.shape
    return x.reshape(n, m, d // m)


@functools.partial(jax.jit, static_argnames=("passes",))
def _assign_anisotropic(
    xs: jax.Array,  # [n, m, dsub] residual subvectors
    us: jax.Array,  # [n, m, dsub] unit-direction subvectors
    codebooks: jax.Array,  # [m, ksub, dsub]
    codes0: jax.Array,  # [n, m] int32 warm start
    eta: jax.Array,  # scalar
    passes: int = 1,
) -> jax.Array:
    """Coordinate-descent assignment under the anisotropic loss.

    For subspace j with the other subspaces fixed, candidate c's loss is
        (S_out + ||x_j - c||^2) + (eta - 1) * (a_out + (x_j - c).u_j)^2
    (h_orth normalised to 1). S_out/a_out are maintained incrementally.
    """
    n, m, dsub = xs.shape
    c_sq = jnp.sum(codebooks * codebooks, axis=-1)  # [m, ksub]

    def sub_terms(codes):
        dec = jnp.take_along_axis(
            codebooks[None],  # [1, m, ksub, dsub]
            codes[:, :, None, None], axis=2,
        )[:, :, 0, :]  # [n, m, dsub]
        r = xs - dec
        s_j = jnp.sum(r * r, axis=-1)  # [n, m]
        a_j = jnp.sum(r * us, axis=-1)  # [n, m]
        return s_j, a_j

    def one_pass(_, carry):
        codes, s_j, a_j = carry
        s_tot = jnp.sum(s_j, axis=1)  # [n]
        a_tot = jnp.sum(a_j, axis=1)  # [n]

        def subspace(j, inner):
            codes, s_j, a_j, s_tot, a_tot = inner
            s_out = s_tot - s_j[:, j]
            a_out = a_tot - a_j[:, j]
            xj, uj, cj = xs[:, j], us[:, j], codebooks[j]
            # ||x_j - c||^2 and (x_j - c).u_j for every candidate: matmuls
            x_sq = jnp.sum(xj * xj, axis=-1)  # [n]
            xc = xj @ cj.T  # [n, ksub]
            cand_sq = x_sq[:, None] - 2.0 * xc + c_sq[j][None, :]
            xu = jnp.sum(xj * uj, axis=-1)  # [n]
            cand_dot = xu[:, None] - uj @ cj.T  # [n, ksub]
            par = a_out[:, None] + cand_dot
            loss = (s_out[:, None] + cand_sq) + (eta - 1.0) * par * par
            best = jnp.argmin(loss, axis=1).astype(jnp.int32)  # [n]
            new_sq = jnp.take_along_axis(
                cand_sq, best[:, None], axis=1
            )[:, 0]
            new_dot = jnp.take_along_axis(
                cand_dot, best[:, None], axis=1
            )[:, 0]
            s_tot = s_out + new_sq
            a_tot = a_out + new_dot
            codes = codes.at[:, j].set(best)
            s_j = s_j.at[:, j].set(new_sq)
            a_j = a_j.at[:, j].set(new_dot)
            return codes, s_j, a_j, s_tot, a_tot

        codes, s_j, a_j, _, _ = jax.lax.fori_loop(
            0, m, subspace, (codes, s_j, a_j, s_tot, a_tot)
        )
        return codes, s_j, a_j

    s_j, a_j = sub_terms(codes0)
    codes, _, _ = jax.lax.fori_loop(
        0, passes, one_pass, (codes0, s_j, a_j)
    )
    return codes


@functools.partial(jax.jit, static_argnames=("ksub",))
def _update_codebooks(
    xs: jax.Array,  # [n, m, dsub]
    us: jax.Array,  # [n, m, dsub]
    codebooks: jax.Array,  # [m, ksub, dsub]
    codes: jax.Array,  # [n, m] int32
    eta: jax.Array,
    ksub: int,
) -> jax.Array:
    """Closed-form codeword update: minimising the anisotropic loss over
    codeword c with assignments fixed solves, per (subspace, codeword),

        [n_c I + (eta-1) sum_i u_i u_i^T] c
            = sum_i x_i + (eta-1) sum_i (a_out_i + x_i . u_i) u_i

    — a batched [dsub, dsub] solve (m * ksub tiny SPD systems)."""
    n, m, dsub = xs.shape
    dec = jnp.take_along_axis(
        codebooks[None], codes[:, :, None, None], axis=2
    )[:, :, 0, :]
    r = xs - dec
    a_j = jnp.sum(r * us, axis=-1)  # [n, m]
    a_out = jnp.sum(a_j, axis=1, keepdims=True) - a_j  # [n, m]

    def per_subspace(j_codes, xj, uj, a_out_j):
        # j_codes [n], xj/uj [n, dsub], a_out_j [n]
        counts = jax.ops.segment_sum(
            jnp.ones((n,), jnp.float32), j_codes, num_segments=ksub
        )  # [ksub]
        sum_x = jax.ops.segment_sum(xj, j_codes, num_segments=ksub)
        uu = uj[:, :, None] * uj[:, None, :]  # [n, dsub, dsub]
        sum_uu = jax.ops.segment_sum(uu, j_codes, num_segments=ksub)
        w = a_out_j + jnp.sum(xj * uj, axis=-1)  # [n]
        sum_wu = jax.ops.segment_sum(w[:, None] * uj, j_codes,
                                     num_segments=ksub)
        lhs = (
            counts[:, None, None] * jnp.eye(dsub, dtype=jnp.float32)[None]
            + (eta - 1.0) * sum_uu
        )  # [ksub, dsub, dsub]
        rhs = sum_x + (eta - 1.0) * sum_wu  # [ksub, dsub]
        # empty codewords get a singular-ish system; regularise and keep
        # the old codeword for them below
        lhs = lhs + 1e-6 * jnp.eye(dsub, dtype=jnp.float32)[None]
        sol = jnp.linalg.solve(lhs, rhs[:, :, None])[:, :, 0]
        return jnp.where(counts[:, None] > 0, sol, jnp.nan)

    # lax.map (not vmap): subspaces update sequentially so the [n, dsub,
    # dsub] outer-product intermediate exists for ONE subspace at a time —
    # vmap would materialize all m at once (~n*d*dsub floats, HBM-hostile
    # at large d/dsub with the default 262k training sample)
    new = jax.lax.map(
        lambda t: per_subspace(*t),
        (codes.T, jnp.moveaxis(xs, 1, 0), jnp.moveaxis(us, 1, 0),
         a_out.T),
    )  # [m, ksub, dsub]
    return jnp.where(jnp.isnan(new), codebooks, new)


def train_anisotropic_pq(
    x: jax.Array,  # [n, d] residuals to quantize
    u: jax.Array,  # [n, d] unit direction of the ORIGINAL datapoint
    m: int,
    ksub: int = 256,
    eta: float = 5.29,
    iters: int = 8,
    init_iters: int = 4,
    seed: int = 0,
) -> jax.Array:
    """Train anisotropic codebooks [m, ksub, dsub] by alternating the
    coordinate-descent assignment with the closed-form update, warm
    started from plain (MSE) PQ."""
    x = jnp.asarray(x, jnp.float32)
    u = jnp.asarray(u, jnp.float32)
    codebooks = pq_ops.train_pq(x, m=m, ksub=ksub, iters=init_iters,
                                seed=seed)
    xs, us = _split(x, m), _split(u, m)
    codes = pq_ops.encode_pq(x, codebooks).astype(jnp.int32)
    eta_arr = jnp.float32(eta)
    for _ in range(iters):
        codes = _assign_anisotropic(xs, us, codebooks, codes, eta_arr,
                                    passes=1)
        codebooks = _update_codebooks(xs, us, codebooks, codes, eta_arr,
                                      ksub=ksub)
    return codebooks


def encode_anisotropic(
    x: jax.Array,  # [n, d] residuals
    u: jax.Array,  # [n, d] unit directions of the original points
    codebooks: jax.Array,
    eta: float,
    passes: int = 2,
) -> jax.Array:
    """Encode under the anisotropic loss (codes [n, m] uint8): plain
    nearest-codeword warm start + `passes` coordinate refinements."""
    x = jnp.asarray(x, jnp.float32)
    m = codebooks.shape[0]
    codes = pq_ops.encode_pq(x, codebooks).astype(jnp.int32)
    codes = _assign_anisotropic(
        _split(x, m), _split(jnp.asarray(u, jnp.float32), m),
        codebooks, codes, jnp.float32(eta), passes=passes,
    )
    return codes.astype(jnp.uint8)


def anisotropic_loss(
    x, u, x_dec, eta: float
) -> float:
    """Mean score-aware loss (h_orth=1) — used by tests to verify the
    trainer actually optimises the right objective."""
    import numpy as np

    x = np.asarray(x, np.float64)
    u = np.asarray(u, np.float64)
    r = x - np.asarray(x_dec, np.float64)
    par = np.sum(r * u, axis=-1)
    tot = np.sum(r * r, axis=-1)
    return float(np.mean(tot + (eta - 1.0) * par * par))

"""Hardware-independent performance model + regression gates.

The only real TPU capture so far (BENCH_r01) was ~100x off the int8-MXU
roofline, dominated by dispatch and host overhead — and every capture
since returned nothing because the TPU tunnel was down. This module
makes the perf properties of the serving path *provable on the CPU
backend*, the way recall is gated in CI: every dispatch-count win,
compile-cache hit, and bytes-materialized saving is modeled here and
asserted in tests/test_perf_gates.py, so a regression is caught before
the one hardware run that counts.

Four layers:

1. `PerfLedger` — drop-in for the plain-list dispatch ledger
   (ops/ivf.py set_dispatch_ledger): call sites append one tag per
   device-program launch; the ledger aggregates per-search counts.
2. jit registry — every jitted search entry point registers itself via
   `register_jit`; `compiled_program_counts()` reads each function's
   live jit-cache size, so a test can assert that repeated same-shape
   searches add ZERO new compiled programs (no silent retrace).
3. bytes-materialized model — peak intermediate HBM bytes per scan
   path, mirroring the real kernel constants (ops/ivf.py BLOCK,
   pallas_kernels chunking). The block-max path's whole reason to exist
   is never materializing the [B, N] f32 score matrix; the model makes
   that advantage a number tests can compare.
4. HBM-footprint model — resident device bytes per index type
   (index.device_footprint_bytes() feeds these helpers), the
   rows-per-chip capacity planner.

Everything here is arithmetic over shapes — no device access — so the
gates run identically with and without a TPU.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable

# must match ops/ivf.py BLOCK and pallas_kernels._SCAN_BLOCK
BLOCK = 512
# stage-2 query chunk of the fused block-max kernel
# (pallas_kernels int8_blockmax_scan_pallas)
BLOCKMAX_STAGE2_CHUNK = 32

F32 = 4
I32 = 4


# -- 1. dispatch ledger ------------------------------------------------------


class PerfLedger:
    """Dispatch ledger with per-search aggregation.

    Compatible with the plain ``list`` contract of
    ops/ivf.py ``set_dispatch_ledger`` (call sites only ever
    ``append(tag)``); adds search boundaries and count summaries on top.
    """

    def __init__(self) -> None:
        self.tags: list[str] = []
        self._marks: list[int] = []

    # list-compat surface used by note_dispatch call sites
    def append(self, tag: str) -> None:
        self.tags.append(tag)

    def __iter__(self):
        return iter(self.tags)

    def __len__(self) -> int:
        return len(self.tags)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PerfLedger):
            return self.tags == other.tags
        return self.tags == other

    def mark_search(self) -> None:
        """Record a search boundary: tags appended after this call
        belong to the next search."""
        self._marks.append(len(self.tags))

    def per_search(self) -> list[list[str]]:
        """Tags grouped by the mark_search() boundaries."""
        bounds = sorted({0, *self._marks, len(self.tags)})
        return [self.tags[a:b] for a, b in zip(bounds, bounds[1:])]

    def dispatch_count(self) -> int:
        return len(self.tags)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for t in self.tags:
            out[t] = out.get(t, 0) + 1
        return out


#: documented device-program launches per engine-level search, by path.
#: tests/test_perf_gates.py asserts the live ledger against this table;
#: docs/PERF.md renders it. A new dispatch on a serving path MUST bump
#: this table in the same PR — that is the regression gate.
DOCUMENTED_DISPATCHES: dict[str, list[str]] = {
    # IVFPQ full-scan, fused scan+rerank (default hot path): ONE program
    "ivfpq_full_fused": ["fused_scan_rerank"],
    # IVFPQ full-scan with fused_rerank=false (A/B escape hatch)
    "ivfpq_full_unfused": ["scan", "rerank"],
    # IVFPQ full-scan via the fused block-max pallas kernel
    "ivfpq_full_pallas": ["pallas_blockmax_scan", "rerank"],
    # IVFPQ probe mode: bucket scan + exact rerank
    "ivfpq_probe": ["probe_scan", "rerank"],
    # IVFFLAT probe scan (scores already exact — no rerank)
    "ivfflat": ["ivfflat_scan"],
    # FLAT exact scan: one fused matmul+topk program
    "flat": ["flat_scan"],
    # served from a result cache (router or PS tier): the whole point
    # is ZERO device programs — the cache perf gates assert an empty
    # ledger for hits and exactly one documented set per coalesced group
    "cache_hit": [],
    # mesh serving (parallel/sharded.py): probe gate + shard scan +
    # all_gather merge + exact rerank + pmax merge, ONE shard_map program
    "ivfpq_mesh_fused": ["sharded_fused_scan_rerank"],
    # mesh serving with fused_rerank=false (A/B escape hatch)
    "ivfpq_mesh_unfused": ["sharded_scan", "sharded_rerank"],
    # mesh serving with exact rerank disabled: scan+merge only
    "ivfpq_mesh_scan": ["sharded_scan"],
    # probe regime under the mesh: the fused program gated to the
    # probed coarse cells (nprobe > 0) — past the full-scan cliff a
    # mesh partition no longer falls back to one chip
    "ivfpq_mesh_probe": ["sharded_probe_scan_rerank"],
    # FLAT over the mesh: one fused scan+all_gather+re-top-k program
    "flat_sharded": ["sharded_flat_scan"],
    # progressive three-stage refinement (IVFRABITQ, RAM store): binary
    # stage-0 scan + int8 rescore + exact rerank fused into ONE program
    "ivfrabitq_three_stage": ["binary_refine_rerank"],
    # three-stage over a disk store: stages 0-1 on device, stage-2 rows
    # host-gathered through the mmap + readahead path (same rerank
    # dispatch the int8 disk path pays)
    "ivfrabitq_three_stage_disk": ["binary_refine_scan", "rerank"],
    # three-stage over the mesh: per-shard stages 0-1, one all_gather
    # candidate merge, sharded exact rerank + pmax — ONE shard_map
    # program (parallel/sharded.py sharded_binary_refine)
    "ivfrabitq_mesh_three_stage": ["sharded_binary_refine_rerank"],
}


def path_for_dispatches(tags: list[str]) -> str | None:
    """Reverse lookup: which documented serving path launched exactly
    this dispatch sequence? None when the sequence matches no documented
    path (e.g. a multi-field search concatenates several paths) — the
    profile surface reports that as drift instead of guessing."""
    seq = list(tags)
    for path, doc in DOCUMENTED_DISPATCHES.items():
        if seq == doc:
            return path
    return None


# -- padded shape buckets ----------------------------------------------------
#
# Every distinct (rows, k) pair handed to a jitted search program is a
# separate XLA specialisation: rows changes the traced shape, k is a
# static arg. Free-form traffic therefore compiles an unbounded program
# set and co-batching is limited to exact-(k) matches. The serving path
# instead quantizes BOTH axes to a small declared grid:
#
#   rows    padded up to the next ROW_BUCKETS tier (results sliced
#           back to the caller's row count host-side),
#   fetch-k padded up to the next FETCH_K_TIERS tier (the engine's
#           _shape_results already trims each caller to its own k).
#
# The compiled-program universe per scan path is then at most
# len(ROW_BUCKETS) * len(FETCH_K_TIERS) — warmable in full, which is
# what makes the zero-retrace perf gate assertable — and requests with
# differing k become co-batchable because every member scans at the
# bucket's tier and slices to its own depth on the host. vearch-lint
# VL103 pins serving code to these constants (this module is the single
# source of truth); tests/test_perf_gates.py asserts the dispatch bound.

#: declared row tiers for batched serving dispatches
ROW_BUCKETS: tuple[int, ...] = (8, 64, 256, 1024)
#: declared fetch-k tiers (candidate depth handed to the index)
FETCH_K_TIERS: tuple[int, ...] = (16, 64, 256, 1024)
#: declared recall-estimator depths (obs/quality.py shadow sampling):
#: head correctness, the common serving page, and candidate-set health.
#: Declared here with the other tier grids so VL103 keeps quality code
#: off ad-hoc depth literals.
RECALL_K_TIERS: tuple[int, ...] = (1, 10, 100)


def bucket_rows(b: int) -> int:
    """Smallest declared row tier holding `b` rows. Above the top tier
    returns `b` unchanged — a caller-supplied mega-batch is already one
    dispatch and padding it further would only waste HBM."""
    for t in ROW_BUCKETS:
        if b <= t:
            return t
    return int(b)


def bucket_fetch_k(k: int) -> int:
    """Smallest declared fetch-k tier covering depth `k`; above the top
    tier returns `k` unchanged (out-of-bucket, documented as such)."""
    for t in FETCH_K_TIERS:
        if k <= t:
            return t
    return int(k)


def bucket_program_bound(row_tiers: int | None = None,
                         k_tiers: int | None = None) -> int:
    """Upper bound on compiled specialisations per scan path once both
    axes are quantized: the full declared grid."""
    r = len(ROW_BUCKETS) if row_tiers is None else int(row_tiers)
    k = len(FETCH_K_TIERS) if k_tiers is None else int(k_tiers)
    return r * k


def bucket_dispatch_bound(n_requests: int, bucket_capacity: int) -> int:
    """Max device dispatches a continuous-batching scheduler may issue
    for `n_requests` single-row requests sharing one bucket key:
    ceil(requests / capacity). The perf gate asserts the live ledger
    against this."""
    return -(-int(n_requests) // max(int(bucket_capacity), 1))


def padding_waste_bytes(real_rows: int, padded_rows: int, d: int,
                        itemsize: int = F32) -> int:
    """Query bytes a padded dispatch moves for nobody: the pad rows of
    the [padded_rows, d] query block. The scheduler accumulates this per
    dispatch; the doctor flags sustained waste > 50%."""
    return max(int(padded_rows) - int(real_rows), 0) * int(d) * int(itemsize)


# -- 2. compiled-program tracking -------------------------------------------

_JIT_REGISTRY: dict[str, Any] = {}

# Optional compile observer (the obs/ flight recorder installs one):
# called as observer(program_name, shape_signature, elapsed_ms) whenever
# a *call* of a registered program grew its jit cache — i.e. XLA
# compiled a new specialisation on what should be a warmed path.
_compile_observer: Any = None


def set_compile_observer(fn: Any) -> None:
    """Install (or clear, with None) the process-wide compile observer."""
    global _compile_observer
    _compile_observer = fn


def _sig_of(v: Any) -> str:
    """One arg's contribution to a call signature: dtype+shape for
    array-likes, the VALUE for plain scalars (static args specialise on
    value — two calls differing only in a static ``k`` are different
    programs and must not collapse to the same signature), type name
    for everything else."""
    shp = getattr(v, "shape", None)
    if shp is not None:
        dt = getattr(v, "dtype", None)
        return f"{getattr(dt, 'name', dt)}{tuple(shp)}"
    if isinstance(v, (bool, int, float, str)) or v is None:
        return repr(v)
    if isinstance(v, enum.Enum):
        return str(v)
    return type(v).__name__


def _shape_signature(args: tuple, kwargs: dict) -> str:
    """Compact abstract signature of a call: per-arg dtype+shape for
    array-likes, value for static-able scalars. This is what XLA
    specialises on, so it names the compile cause in flight-recorder
    events."""
    parts = [_sig_of(a) for a in args]
    parts += [f"{k}={_sig_of(kwargs[k])}" for k in sorted(kwargs)]
    return "|".join(parts)


class _ObservedJit:
    """Callable proxy over a registered jit entry point.

    Detects jit-cache growth around each call — the only reliable
    compile signal the public JAX API exposes — and notifies the
    installed observer with the call's shape signature and wall time.
    With no observer installed the call passes straight through; every
    attribute access (``_cache_size``, ``lower``, ...) delegates to the
    wrapped function, so the proxy is drop-in for existing callers.
    """

    __slots__ = ("_vearch_name", "_vearch_fn")

    def __init__(self, name: str, fn: Any):
        self._vearch_name = name
        self._vearch_fn = fn

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        obs = _compile_observer
        fn = self._vearch_fn
        if obs is None:
            return fn(*args, **kwargs)
        try:
            before = int(fn._cache_size())
        except Exception:
            before = -1
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if before >= 0:
            try:
                grew = int(fn._cache_size()) > before
            except Exception:
                grew = False
            if grew:
                obs(
                    self._vearch_name,
                    _shape_signature(args, kwargs),
                    (time.perf_counter() - t0) * 1000.0,
                )
        return out

    def __getattr__(self, item: str) -> Any:
        return getattr(self._vearch_fn, item)


def register_jit(name: str, fn: Any) -> Any:
    """Register a jitted search entry point for compile tracking.

    Returns an observing proxy of `fn` so modules can write
    ``fn = register_jit("name", jax.jit(...))``; the raw function stays
    in the registry so :func:`compiled_program_counts` reads the jit
    cache directly.
    """
    _JIT_REGISTRY[name] = fn
    return _ObservedJit(name, fn)


def compiled_program_counts() -> dict[str, int]:
    """Live jit-cache entry count per registered search program.

    Each entry is one (shape, static-args) specialisation XLA compiled.
    Stable counts across repeated searches == no retrace on the hot
    path; growth with every request is the compile-stall regression the
    warmup + persistent-cache work exists to prevent.
    """
    out: dict[str, int] = {}
    for name, fn in _JIT_REGISTRY.items():
        try:
            out[name] = int(fn._cache_size())
        except Exception:
            out[name] = -1  # jit internals moved; surface loudly
    return out


def total_compiled_programs() -> int:
    return sum(max(v, 0) for v in compiled_program_counts().values())


# Process-wide host->device transfer accounting. The mesh row caches
# and the engine device_put sites already count their own H2D bytes
# per instance; this accumulator is the cross-instance total the
# device-runtime sampler exports as vearch_ps_h2d_bytes_total. A
# counter (not a gauge over instances) survives engine close/reopen.
_h2d_lock = threading.Lock()
_h2d_bytes_total = 0

# Optional H2D observer (obs/accounting installs one): called with the
# byte count from the SAME note_h2d_bytes call that feeds the process
# total, so per-tenant byte meters reconcile with h2d_bytes_total
# exactly — same single-slot contract as set_compile_observer.
_h2d_observer: Any = None


def set_h2d_observer(fn: Any) -> None:
    """Install (or clear, with None) the process-wide H2D byte observer."""
    global _h2d_observer
    _h2d_observer = fn


def note_h2d_bytes(n: int) -> None:
    """Record `n` bytes copied host->device (call at device_put sites)."""
    global _h2d_bytes_total
    with _h2d_lock:
        _h2d_bytes_total += int(n)
    obs = _h2d_observer
    if obs is not None:
        obs(int(n))


def h2d_bytes_total() -> int:
    with _h2d_lock:
        return _h2d_bytes_total


# -- bytes-over-PCIe model (tiered storage engine) --------------------------
#
# The disk tier's only per-query H2D traffic with a warm cache is ZERO:
# a hit serves entirely from the resident HBM slab pools. A miss pays
# exactly one slab upload — four arrays of fixed shape [cap, ...]:
#
#     int8 rows   cap * d   bytes
#     scale f32   cap * 4
#     vsq   f32   cap * 4
#     docids i32  cap * 4
#
# so slab_bytes(cap, d) = cap * (d + 12), and a resolve with `m` misses
# moves tier_h2d_bytes(m, cap, d) = m * slab_bytes over PCIe (the slot
# index vector rides in the dispatch, not the ledger). HbmBucketCache
# notes the actual uploaded nbytes through note_h2d_bytes, and
# tests/test_perf_gates.py asserts ledger delta == model exactly:
# zero for a warmed hot working set, m * slab_bytes on cold misses.


def slab_bytes(cap: int, d: int) -> int:
    """H2D bytes one bucket-slab upload moves (int8 rows + scale + vsq
    + docids at the cache's fixed row capacity `cap`)."""
    return int(cap) * (int(d) + 12)


def tier_h2d_bytes(misses: int, cap: int, d: int) -> int:
    """Modeled PCIe bytes for a resolve with `misses` slab misses —
    zero on a full hit, one slab_bytes per missed bucket otherwise."""
    return int(misses) * slab_bytes(cap, d)


# -- 3. bytes-materialized model --------------------------------------------


def blockmax_selected_blocks(r: int, n_pad: int) -> int:
    """Candidate blocks stage 2 re-scores — mirrors the 2x+8
    over-selection in ops/ivf.py _select_topk and the pallas kernel."""
    nblk = max(n_pad // BLOCK, 1)
    nb = max(32, min(r, n_pad) // 4)
    return min(2 * nb + 8, nblk)


def scan_peak_bytes(
    b: int, n_pad: int, d: int, r: int, path: str
) -> int:
    """Peak intermediate HBM bytes one search materializes, per scan
    path. This is PEAK (resident at once), not total traffic — the
    chunked stage 2 deliberately trades re-gathers for a bounded
    working set.

    Paths:
    - "xla_full": the default XLA scan materializes the [B, N] f32
      score matrix (block-max selection then re-reads it).
    - "pallas_blockmax": the fused kernel writes only [B, N/BLOCK] f32
      block maxima; stage 2 holds one query-chunk's gathered blocks
      (int8 rows + f32 scores + i32 ids).
    """
    if path == "xla_full":
        return b * n_pad * F32
    if path == "pallas_blockmax":
        nblk = max(n_pad // BLOCK, 1)
        nb_sel = blockmax_selected_blocks(r, n_pad)
        s = nb_sel * BLOCK
        chunk = min(BLOCKMAX_STAGE2_CHUNK, b)
        bmax = b * nblk * F32
        stage2 = chunk * s * (d + F32 + I32)  # int8 vecs + scores + ids
        return bmax + stage2
    raise ValueError(f"unknown scan path {path!r}")


def scan_traffic_bytes(b: int, n_pad: int, d: int, path: str) -> int:
    """HBM bytes READ by the stage-1 pass over the database — the
    bandwidth-bound term of the roofline. int8 mirror rows dominate;
    both paths read them exactly once."""
    del b, path
    return n_pad * d  # int8: one byte per dim


# -- 4. HBM footprint model --------------------------------------------------


def mirror_footprint_bytes(n_cap: int, d: int, storage: str = "int8") -> int:
    """Resident device bytes of the docid-ordered compressed mirror:
    rows + per-row scale + per-row ||v||^2 (index/int8_mirror.py)."""
    width = d if storage == "int8" else (d + 1) // 2
    return n_cap * width + 2 * n_cap * F32


def binary_plane_bytes(n_cap: int, d: int) -> int:
    """Row PAYLOAD of the packed bit-plane mirror: ceil(d/8) bytes per
    row at the 512-aligned capacity. This — not the total — is the
    8x-density gate against the int8 mirror: the per-row aux columns
    (scale + offset, 8 bytes) ride identically on BOTH tiers, so the
    honest density claim compares payloads:
    8 * binary_plane_bytes <= mirror_footprint_bytes holds for every d
    (the int8 total is d + 8 bytes/row vs the plane's d/8), while the
    TOTAL ratio (d/8 + 8) / (d + 8) only approaches 1/8 as d grows —
    tests/test_perf_gates.py gates the payload form and PERF.md Tier 8
    states both numbers."""
    return int(n_cap) * (-(-int(d) // 8))


def binary_footprint_bytes(n_cap: int, d: int) -> int:
    """Resident device bytes of the flushed bit-plane mirror: packed
    sign planes + per-row scale + per-row ||approx||^2 — what
    Int8Mirror(storage="bits").device_bytes() reports and the device
    sampler must agree with."""
    return binary_plane_bytes(n_cap, d) + 2 * int(n_cap) * F32


def binary_scan_traffic_bytes(n_pad: int, d: int) -> int:
    """HBM bytes the stage-0 pass READS per query batch: each packed
    plane exactly once — 1/8 of the int8 scan's traffic term, the
    bandwidth headroom that makes stage 0 worth a third stage."""
    return int(n_pad) * (-(-int(d) // 8))


def refine_depths(k: int, n: int) -> tuple[int, int]:
    """Auto defaults for the three-stage candidate depths (r0, r1).

    Stage 0's sign estimator is selection-grade only, so its survivor
    set must be generous: r0 = 32x the int8 default's 10x-k rule,
    floored at 512 (one block-max block) — still ~1e-3 of a 1M-row
    partition. Stage 1 then funnels to the proven int8 rerank depth
    r1 = max(10k, 128). Both clamp to the row count; both are
    runtime-tunable per request / via /ps/engine/config ("r0"/"r1"
    index params) with these as the documented fallback."""
    n = max(int(n), 1)
    r1 = min(max(10 * int(k), 128), n)
    r0 = min(max(32 * r1 // 10, 512), n)
    return max(r0, r1), r1


def raw_store_footprint_bytes(
    capacity: int, d: int, itemsize: int
) -> int:
    """Raw device buffer + sqnorm column (engine/raw_vector.py)."""
    return capacity * d * itemsize + capacity * F32


def per_device_bytes(
    sharded_bytes: int, replicated_bytes: int, n_shards: int
) -> int:
    """Resident HBM on EACH chip of a mesh placement: row-sharded state
    divides across the "data" axis (ceil: padded slabs), replicated
    state (coarse centroids, bucket tensors) rides whole on every chip.
    With n_shards == 1 this degenerates to the single-device footprint."""
    return replicated_bytes + -(-sharded_bytes // max(n_shards, 1))


def ivf_bucket_footprint_bytes(nlist: int, cap: int, d: int) -> int:
    """Probe-mode IVFPQ device state: [nlist, cap, d] int8 residuals +
    per-cluster scale + [nlist, cap] vsq + ids (index/ivf.py
    _publish_locked)."""
    return nlist * cap * d + nlist * F32 + 2 * nlist * cap * F32


def roofline_qps(
    n: int, d: int, peak_int8_ops: float, rerank_r: int = 0
) -> float:
    """Compute-roofline QPS for the int8 full scan: one [1, d] x [d, N]
    int8 matmul per query (2 ops per MAC) plus the optional exact-rerank
    matvec. The denominator bench.py prints so a capture reads "X% of
    roofline" instead of a bare QPS."""
    ops_per_query = 2.0 * n * d + 2.0 * rerank_r * d
    return peak_int8_ops / max(ops_per_query, 1.0)


#: per-chip peak int8 MXU throughput (ops/s). Public figures; the bench
#: labels which row it used and falls back to DEFAULT_CHIP when no TPU
#: is reachable so the denominator is always printed.
INT8_PEAK_OPS: dict[str, float] = {
    "TPU v4": 275e12,       # bf16 figure; v4 has no int8 doubling
    "TPU v5 lite": 394.7e12,
    "TPU v5e": 394.7e12,
    "TPU v5": 918.8e12,     # v5p
    "TPU v5p": 918.8e12,
    "TPU v6 lite": 1836.0e12,  # trillium
    "TPU v6e": 1836.0e12,
}
DEFAULT_CHIP = "TPU v5e"


def effective_qps(
    cold_qps: float, hit_rate: float, hit_cost_frac: float = 0.0
) -> float:
    """Amdahl-style serving throughput under a result cache: a hit
    costs ``hit_cost_frac`` of a cold query (0 = free hash lookup),
    a miss costs a full cold query. bench.py's cache-effectiveness
    phase reports this next to the measured effective QPS so the
    model and the measurement can be compared directly."""
    hit_rate = min(max(hit_rate, 0.0), 1.0)
    denom = hit_rate * max(hit_cost_frac, 0.0) + (1.0 - hit_rate)
    return cold_qps / max(denom, 1e-12)


def peak_int8_ops(device_kind: str | None) -> tuple[str, float]:
    """(label, ops/s) for a device kind; prefix-matches so platform
    suffixes ("TPU v5 lite chip") still resolve. Unknown/absent kinds
    fall back to DEFAULT_CHIP with an 'assumed' label."""
    if device_kind:
        for k in sorted(INT8_PEAK_OPS, key=len, reverse=True):
            if device_kind.lower().startswith(k.lower()):
                return k, INT8_PEAK_OPS[k]
    return f"{DEFAULT_CHIP} (assumed)", INT8_PEAK_OPS[DEFAULT_CHIP]

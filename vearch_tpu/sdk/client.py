"""Python SDK.

Mirrors pyvearch's surface (reference: sdk/python/vearch/core/vearch.py:33
`Vearch`, core/space.py:30 `Space` — create_database/create_space/upsert/
search/query/delete against the router+master REST API).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from vearch_tpu.cluster import rpc


class VearchClient:
    def __init__(self, router_addr: str, master_addr: str | None = None):
        self.addr = router_addr.replace("http://", "")
        # elastic/admin verbs (split/migrate/rebalance/drain) hit the
        # master directly — they reshape the cluster, not one request
        self.master_addr = (master_addr.replace("http://", "")
                            if master_addr else None)

    def _master(self) -> str:
        if self.master_addr is None:
            raise ValueError(
                "elastic operations need VearchClient(master_addr=...)")
        return self.master_addr

    # -- admin (proxied to master) -------------------------------------------

    def create_database(self, db_name: str) -> dict:
        return rpc.call(self.addr, "POST", f"/dbs/{db_name}")

    def drop_database(self, db_name: str) -> dict:
        return rpc.call(self.addr, "DELETE", f"/dbs/{db_name}")

    def list_databases(self) -> list[dict]:
        return rpc.call(self.addr, "GET", "/dbs")["dbs"]

    def create_space(self, db_name: str, space_config: dict) -> dict:
        """space_config: {name, fields: [...], partition_num, replica_num}
        with fields in TableSchema.to_dict() form."""
        return rpc.call(self.addr, "POST", f"/dbs/{db_name}/spaces", space_config)

    def drop_space(self, db_name: str, space_name: str) -> dict:
        return rpc.call(self.addr, "DELETE", f"/dbs/{db_name}/spaces/{space_name}")

    def get_space(self, db_name: str, space_name: str,
                  detail: bool = False) -> dict:
        if detail:
            # per-partition doc/size/status (reference: ?detail=true)
            return rpc.call(
                self.addr, "GET",
                f"/dbs/{db_name}/spaces/{space_name}?detail=true")
        return self._get_space_plain(db_name, space_name)

    def _get_space_plain(self, db_name: str, space_name: str) -> dict:
        return rpc.call(self.addr, "GET", f"/dbs/{db_name}/spaces/{space_name}")

    def list_spaces(self, db_name: str) -> list[dict]:
        return rpc.call(self.addr, "GET", f"/dbs/{db_name}/spaces")["spaces"]

    def is_live(self) -> bool:
        try:
            rpc.call(self.addr, "GET", "/cluster/health")
            return True
        except rpc.RpcError:
            return False

    # -- documents -----------------------------------------------------------

    # overload backoff for the document verbs: a 429 shed from admission
    # control carries the server's Retry-After hint; honor it with
    # capped, jittered sleeps and a bounded retry count so a saturated
    # cluster sees polite clients, not a retry storm
    max_retries_429 = 3
    backoff_cap_s = 3.0

    def _doc_call(self, method: str, path: str, body: dict | None = None):
        """rpc.call with 429 backoff. Only 429 retries here: terminal
        kills (499 request_killed) and every other error propagate
        immediately — the kill exists to shed that exact work, and
        failover retries already live in the router."""
        import random
        import time

        attempt = 0
        while True:
            try:
                return rpc.call(self.addr, method, path, body)
            except rpc.RpcError as e:
                if e.code != 429 or attempt >= self.max_retries_429:
                    raise
                attempt += 1
                base = (float(e.retry_after) if e.retry_after
                        else 0.1 * attempt)
                time.sleep(min(self.backoff_cap_s,
                               base * random.uniform(0.5, 1.5)))

    def upsert(self, db_name: str, space_name: str, documents: list[dict],
               profile: bool = False) -> dict:
        """Upsert documents. With ``profile=True`` the response carries a
        router-merged write-side phase breakdown (propose-wait, WAL
        append+fsync, commit-wait, engine apply) per partition — the
        mutation-plane mirror of ``search(profile=True)``."""
        documents = [
            {k: (v.tolist() if isinstance(v, np.ndarray) else v)
             for k, v in d.items()}
            for d in documents
        ]
        body = {
            "db_name": db_name, "space_name": space_name,
            "documents": documents,
        }
        if profile:
            body["profile"] = True
        return self._doc_call("POST", "/document/upsert", body)

    def search(
        self,
        db_name: str,
        space_name: str,
        vectors: list[dict[str, Any]],
        limit: int = 10,
        filters: dict | None = None,
        fields: list[str] | None = None,
        index_params: dict | None = None,
        ranker: dict | None = None,
        # None defers to the router's configured read routing (leader,
        # or least-loaded replica when replica_read is on); an explicit
        # mode always wins
        load_balance: str | None = None,
        columnar: bool = False,
        sort: Any = None,
        page_size: int | None = None,
        page_num: int | None = None,
        profile: bool = False,
        deadline_ms: float | None = None,
        cache: bool = True,
    ) -> list[list[dict]] | dict:
        """Search `space_name`; returns per-query hit lists.

        With ``profile=True`` the full response dict comes back instead:
        ``documents`` plus a router-merged ``profile`` breakdown —
        per-partition phase timings, measured dispatch tags vs the perf
        model's documented prediction, and router merge cost (schema in
        docs/OBSERVABILITY.md).

        ``cache=False`` bypasses the router and partition result
        caches for this request — correctness-sensitive callers and
        cold benchmarks always hit the engines; the profile reports
        ``cache: bypass``."""
        # features ride as ndarrays: the RPC layer's binary tensor codec
        # ships a [b*d] f32 buffer instead of tens of thousands of JSON
        # floats (a large-batch query upload was ~30% of e2e latency)
        vectors = [
            {**v, "feature": np.asarray(
                v["feature"], dtype=np.float32).ravel()}
            for v in vectors
        ]
        body = {
            "db_name": db_name, "space_name": space_name,
            "vectors": vectors, "limit": limit,
        }
        if load_balance:
            body["load_balance"] = load_balance
        if filters:
            body["filters"] = filters
        if fields is not None:
            body["fields"] = fields
        if index_params:
            body["index_params"] = index_params
        if ranker:
            body["ranker"] = ranker
        if sort is not None:
            body["sort"] = sort
        if page_size is not None:
            body["page_size"] = page_size
        if page_num is not None:
            body["page_num"] = page_num
        if deadline_ms is not None:
            # per-request execution budget: each partition server arms a
            # kill between device dispatches; an expired request fails
            # with a terminal request_killed error (never retried)
            body["deadline_ms"] = deadline_ms
        if not cache:
            body["cache"] = False
        if profile:
            body["profile"] = True
            return self._doc_call("POST", "/document/search", body)
        if columnar and fields == []:
            # fields-free throughput mode: scores ride as ONE binary f32
            # buffer instead of b*k JSON dicts; reshaped here so the
            # return type is identical
            body["columnar"] = True
            out = self._doc_call("POST", "/document/search", body)
            if out.get("columnar"):
                flat = np.asarray(out["scores"]).tolist()
                res, pos = [], 0
                for ks in out["keys"]:
                    res.append([
                        {"_id": k, "_score": flat[pos + i]}
                        for i, k in enumerate(ks)
                    ])
                    pos += len(ks)
                return res
            return out["documents"]
        return self._doc_call("POST", "/document/search", body)["documents"]

    def query(
        self,
        db_name: str,
        space_name: str,
        document_ids: list[str] | None = None,
        filters: dict | None = None,
        limit: int = 50,
        offset: int = 0,
        fields: list[str] | None = None,
        vector_value: bool = False,
        sort: Any = None,
    ) -> list[dict]:
        body: dict[str, Any] = {"db_name": db_name, "space_name": space_name,
                                "limit": limit, "offset": offset,
                                "vector_value": vector_value}
        if document_ids:
            body["document_ids"] = document_ids
        if filters:
            body["filters"] = filters
        if fields is not None:
            body["fields"] = fields
        if sort is not None:
            body["sort"] = sort
        return self._doc_call("POST", "/document/query", body)["documents"]

    def delete(
        self,
        db_name: str,
        space_name: str,
        document_ids: list[str] | None = None,
        filters: dict | None = None,
        limit: int | None = None,
    ) -> int:
        body: dict[str, Any] = {"db_name": db_name, "space_name": space_name}
        if document_ids:
            body["document_ids"] = document_ids
        if filters:
            body["filters"] = filters
        if limit is not None:
            body["limit"] = limit
        return self._doc_call("POST", "/document/delete", body)["total"]

    def flush(self, db_name: str, space_name: str) -> dict:
        return rpc.call(self.addr, "POST", "/index/flush",
                        {"db_name": db_name, "space_name": space_name})

    def forcemerge(self, db_name: str, space_name: str) -> dict:
        return rpc.call(self.addr, "POST", "/index/forcemerge",
                        {"db_name": db_name, "space_name": space_name})

    def rebuild(self, db_name: str, space_name: str) -> dict:
        return rpc.call(self.addr, "POST", "/index/rebuild",
                        {"db_name": db_name, "space_name": space_name})

    def update_space(self, db_name: str, space_name: str,
                     config: dict) -> dict:
        """Online space update (reference: UpdateSpace): expand
        partition_num, or add new scalar fields via {"fields": [...]}."""
        return rpc.call(self.addr, "PUT",
                        f"/dbs/{db_name}/spaces/{space_name}", config)

    def add_field_index(
        self, db_name: str, space_name: str, field: str,
        index_type: str = "INVERTED", background: bool = True,
    ) -> dict:
        """Build a scalar index on a live field (reference:
        AddFieldIndexWithParams, c_api/gamma_api.h:166)."""
        return rpc.call(self.addr, "POST", "/field_index", {
            "db_name": db_name, "space_name": space_name, "field": field,
            "operator_type": "ADD", "index_type": index_type,
            "background": background,
        })

    def remove_field_index(
        self, db_name: str, space_name: str, field: str
    ) -> dict:
        """Drop a field's scalar index (reference: RemoveFieldIndex,
        c_api/gamma_api.h:181)."""
        return rpc.call(self.addr, "POST", "/field_index", {
            "db_name": db_name, "space_name": space_name, "field": field,
            "operator_type": "DROP",
        })

    # -- elasticity (master-side; see docs/ELASTICITY.md) --------------------

    def split_partition(self, db_name: str, space_name: str,
                        partition_id: int,
                        timeout_s: float = 600.0) -> dict:
        """Start an online split of `partition_id` into two hash-range
        children. Returns {"job_id", "status"}; poll with
        ``elastic_job`` / ``wait_elastic_job``."""
        return rpc.call(self._master(), "POST", "/partitions/split", {
            "db_name": db_name, "space_name": space_name,
            "partition_id": partition_id, "timeout_s": timeout_s,
        })

    def migrate_partition(self, partition_id: int, to_node: int,
                          from_node: int | None = None,
                          timeout_s: float = 600.0) -> dict:
        """Move one replica of `partition_id` onto PS `to_node` via
        snapshot-streamed catch-up, then retire the source replica."""
        body: dict[str, Any] = {"partition_id": partition_id,
                                "to_node": to_node, "timeout_s": timeout_s}
        if from_node is not None:
            body["from_node"] = from_node
        return rpc.call(self._master(), "POST", "/partitions/migrate",
                        body)

    def rebalance(self, apply: bool = False, max_moves: int = 4) -> dict:
        """Compute (and with ``apply=True`` execute) a load-leveling
        plan of replica moves; the plan rides back either way."""
        return rpc.call(self._master(), "POST", "/cluster/rebalance",
                        {"apply": apply, "max_moves": max_moves})

    def drain(self, node_id: int, apply: bool = False) -> dict:
        """Plan (and with ``apply=True`` execute) moving every replica
        off PS `node_id`, so it can be decommissioned."""
        return rpc.call(self._master(), "POST", "/cluster/drain",
                        {"node_id": node_id, "apply": apply})

    def cluster_plan(self) -> dict:
        return rpc.call(self._master(), "GET", "/cluster/plan")

    def elastic_job(self, job_id: str) -> dict:
        return rpc.call(self._master(), "GET", f"/cluster/jobs/{job_id}")

    def elastic_jobs(self) -> list[dict]:
        return rpc.call(self._master(), "GET", "/cluster/jobs")["jobs"]

    def wait_elastic_job(self, job_id: str,
                         timeout_s: float = 600.0) -> dict:
        """Block until the job leaves "running" (or `timeout_s` runs
        out). Raises TimeoutError on the deadline, RuntimeError when
        the job finishes in error."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while True:
            job = self.elastic_job(job_id)
            if job["status"] != "running":
                if job["status"] == "error":
                    raise RuntimeError(
                        f"elastic job {job_id} failed: {job.get('error')}")
                return job
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic job {job_id} still running after "
                    f"{timeout_s}s (phase {job.get('phase')})")
            _time.sleep(0.2)

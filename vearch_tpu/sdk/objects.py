"""pyvearch-shaped object model over the flat client (reference:
sdk/python/vearch/core/vearch.py:33 Vearch / core/db.py Database /
core/space.py:30 Space — users migrating from the reference SDK keep
their call shapes: vc.database(name).space(name).search(...)).

Original thin veneer: every method delegates to
vearch_tpu.sdk.client.VearchClient; no request/response shapes of its
own."""

from __future__ import annotations

from typing import Any

from vearch_tpu.cluster.rpc import RpcError
from vearch_tpu.sdk.client import VearchClient


class Vearch:
    """Entry point (reference: core/vearch.py Vearch(Config)). Accepts a
    router address string or anything with a `.host` attribute."""

    def __init__(self, config):
        addr = getattr(config, "host", config)
        self.client = VearchClient(str(addr))  # client normalizes URLs

    def database(self, database_name: str) -> "Database":
        return Database(database_name, self.client)

    def list_databases(self) -> list["Database"]:
        return [Database(d["name"], self.client)
                for d in self.client.list_databases()]

    def create_database(self, database_name: str) -> "Database":
        self.client.create_database(database_name)
        return Database(database_name, self.client)

    def is_database_exist(self, database_name: str) -> bool:
        return self.database(database_name).exist()

    def drop_database(self, database_name: str) -> None:
        self.client.drop_database(database_name)

    def space(self, database_name: str, space_name: str) -> "Space":
        return Space(database_name, space_name, self.client)

    def list_spaces(self, database_name: str) -> list["Space"]:
        return [Space(database_name, s["name"], self.client)
                for s in self.client.list_spaces(database_name)]

    def create_space(self, database_name: str, schema: dict) -> "Space":
        self.client.create_space(database_name, schema)
        return Space(database_name, schema["name"], self.client)

    def drop_space(self, database_name: str, space_name: str) -> None:
        self.client.drop_space(database_name, space_name)

    def is_live(self) -> bool:
        return self.client.is_live()


class Database:
    def __init__(self, name: str, client: VearchClient):
        self.name = name
        self.client = client

    def exist(self) -> bool:
        return any(d["name"] == self.name
                   for d in self.client.list_databases())

    def create(self) -> "Database":
        self.client.create_database(self.name)
        return self

    def drop(self) -> None:
        self.client.drop_database(self.name)

    def space(self, space_name: str) -> "Space":
        return Space(self.name, space_name, self.client)

    def list_spaces(self) -> list["Space"]:
        return [Space(self.name, s["name"], self.client)
                for s in self.client.list_spaces(self.name)]


class Space:
    def __init__(self, db_name: str, space_name: str,
                 client: VearchClient):
        self.db_name = db_name
        self.name = space_name
        self.client = client

    def create(self, schema: dict) -> "Space":
        self.client.create_space(self.db_name, {**schema,
                                                "name": self.name})
        return self

    def drop(self) -> None:
        self.client.drop_space(self.db_name, self.name)

    def exist(self) -> tuple[bool, dict | None]:
        try:
            return True, self.client.get_space(self.db_name, self.name)
        except RpcError as e:
            if e.code == 404:
                return False, None
            raise

    def describe(self, detail: bool = False) -> dict:
        return self.client.get_space(self.db_name, self.name,
                                     detail=detail)

    def create_index(self, field: str,
                     index_type: str = "INVERTED") -> dict:
        """Scalar field index (reference: Space.create_index)."""
        return self.client.add_field_index(self.db_name, self.name,
                                           field, index_type)

    def upsert(self, data: list[dict]) -> list[str]:
        out = self.client.upsert(self.db_name, self.name, data)
        return out["document_ids"]

    def search(self, vectors: list[dict], limit: int = 10,
               **kw) -> list[list[dict]]:
        return self.client.search(self.db_name, self.name, vectors,
                                  limit=limit, **kw)

    def query(self, document_ids: list[str] | None = None,
              filters: dict | None = None, **kw) -> list[dict]:
        return self.client.query(self.db_name, self.name,
                                 document_ids=document_ids,
                                 filters=filters, **kw)

    def delete(self, document_ids: list[str] | None = None,
               filters: dict | None = None, **kw) -> int:
        return self.client.delete(self.db_name, self.name,
                                  document_ids=document_ids,
                                  filters=filters, **kw)

    def flush(self) -> Any:
        return self.client.flush(self.db_name, self.name)

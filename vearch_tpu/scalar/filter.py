"""Scalar filter AST + evaluation.

Mirrors the reference's filter surface exactly (reference:
internal/router/document/doc_query.go:85 parseFilter — JSON
`{"operator": "AND"|"OR", "conditions": [{"field", "operator", "value"}]}`
with range ops < <= > >= = != <> and term ops IN / NOT IN), evaluated
TPU-first: conditions compile to a host boolean mask over the docid space
(vectorised numpy on columnar fields, scalar-index lookups when one
exists), which the engine ANDs with the deletion bitmap and applies
*inside* the top-k kernel. That is the reference's "filter first" strategy
(reference: scalar_index_manager.h FilterIndexPair planning); masking
in-kernel replaces its candidate-set intersection since TPU scans are
matmuls over everything anyway.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

RANGE_OPS = {"<", "<=", ">", ">=", "=", "!=", "<>"}
TERM_OPS = {"IN", "NOT IN"}


@dataclass
class Condition:
    field: str
    operator: str  # one of RANGE_OPS | TERM_OPS
    value: Any

    def __post_init__(self):
        if self.operator not in RANGE_OPS | TERM_OPS:
            raise ValueError(f"unsupported filter operator: {self.operator}")


@dataclass
class Filter:
    operator: str = "AND"  # AND | OR over conditions
    conditions: list[Condition] = field(default_factory=list)

    def __post_init__(self):
        if self.operator not in ("AND", "OR"):
            raise ValueError(f"unsupported filter combinator: {self.operator}")

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Filter":
        return cls(
            operator=d.get("operator", "AND"),
            conditions=[
                Condition(c["field"], c["operator"], c.get("value"))
                for c in d.get("conditions", [])
            ],
        )


def _eval_fixed(col: np.ndarray, cond: Condition) -> np.ndarray:
    op, v = cond.operator, cond.value
    if op == "<":
        return col < v
    if op == "<=":
        return col <= v
    if op == ">":
        return col > v
    if op == ">=":
        return col >= v
    if op == "=":
        return col == v
    if op in ("!=", "<>"):
        return col != v
    values = v if isinstance(v, (list, tuple)) else [v]
    mask = np.isin(col, np.asarray(values, dtype=col.dtype))
    return ~mask if op == "NOT IN" else mask


def _eval_strings(rows: list[Any], cond: Condition, n: int) -> np.ndarray:
    op, v = cond.operator, cond.value
    values = set(v) if isinstance(v, (list, tuple)) else {v}
    out = np.zeros(n, dtype=bool)
    for i in range(n):
        cell = rows[i]
        if isinstance(cell, (list, tuple)):  # string arrays: any-match
            hit = bool(values & set(cell))
        else:
            hit = cell in values
        out[i] = hit
    if op == "NOT IN":
        out = ~out
    elif op == "=":
        pass
    elif op in ("!=", "<>"):
        out = ~out
    elif op not in ("IN",):
        raise ValueError(f"operator {op} unsupported on string field {cond.field}")
    return out


def evaluate_condition(cond: Condition, engine, n: int) -> np.ndarray:
    """[n] bool mask for one condition; prefers a scalar index."""
    mgr = engine._scalar_manager
    if mgr is not None:
        mask = mgr.query_if_indexed(cond, n)
        if mask is not None:
            return mask
    schema_field = engine.schema.field(cond.field)
    table = engine.table
    try:
        col = table.column(cond.field)[:n]
        return _eval_fixed(col, cond)
    except KeyError:
        rows = table.string_column(cond.field)
        return _eval_strings(rows, cond, n)


def evaluate_filter(flt, engine, n: int) -> np.ndarray:
    """Evaluate a Filter (or its dict form) to an [n] bool mask.

    Planning: an AND filter whose equality conditions exactly cover a
    declared composite index resolves those in one composite lookup
    (reference: scalar_index_manager.h composite strategy); all other
    conditions evaluate per-field and combine.
    """
    if isinstance(flt, dict):
        flt = Filter.from_dict(flt)
    if not flt.conditions:
        return np.ones(n, dtype=bool)

    conditions = list(flt.conditions)
    masks: list[np.ndarray] = []
    mgr = engine._scalar_manager
    if flt.operator == "AND" and mgr is not None:
        # composite planning (reference: composite-key semantics): the
        # best composite serves the longest '=' prefix of its member
        # fields plus at most one range condition on the field right
        # after the prefix; leftover conditions evaluate per-field
        eq_by_field = {c.field: c for c in conditions if c.operator == "="}
        range_by_field: dict[str, Condition] = {}
        for c in conditions:
            if c.operator in ("<", "<=", ">", ">="):
                range_by_field.setdefault(c.field, c)
        best = None  # (covered_count, ci, prefix_fields, range_cond)
        for ci in mgr.composites():
            prefix = []
            for f in ci.fields:
                if f in eq_by_field:
                    prefix.append(f)
                else:
                    break
            rc = None
            if len(prefix) < len(ci.fields):
                rc = range_by_field.get(ci.fields[len(prefix)])
            covered = len(prefix) + (1 if rc is not None else 0)
            if covered and (best is None or covered > best[0]):
                best = (covered, ci, prefix, rc)
        if best is not None:
            _, ci, prefix, rc = best
            masks.append(ci.query_prefix(
                tuple(eq_by_field[f].value for f in prefix), rc, n
            ))
            consumed_ids = {id(eq_by_field[f]) for f in prefix}
            if rc is not None:
                consumed_ids.add(id(rc))
            conditions = [c for c in conditions
                          if id(c) not in consumed_ids]

    masks.extend(evaluate_condition(c, engine, n) for c in conditions)
    out = masks[0].copy()
    for m in masks[1:]:
        if flt.operator == "AND":
            out &= m
        else:
            out |= m
    return out

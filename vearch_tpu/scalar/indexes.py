"""Scalar index implementations.

TPU-native re-design of the reference's scalar index family (reference:
internal/engine/table/scalar_index.h:28 `ScalarIndex` ABC;
inverted_index.h:24 RocksDB (field,value,docid) keys with range scan;
bitmap_index.h:23 roaring bitmaps). RocksDB key scans become sorted numpy
arrays with `searchsorted` range slicing; roaring bitmaps become packed
numpy bool arrays — both produce the docid masks the search kernel consumes
directly.

All indexes are append-only over docids (updates soft-delete the old row,
so stale entries are masked by the deletion bitmap downstream — no index
maintenance on delete, same as the vector side).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from vearch_tpu.scalar.filter import Condition, _eval_fixed


class InvertedScalarIndex:
    """Sorted (value, docid) pairs with lazy re-sort; range + term queries.

    The numpy analogue of the reference's RocksDB inverted index
    (reference: table/inverted_index.h:24): ordered key scan ->
    searchsorted slice over a value-sorted array.
    """

    def __init__(self, dtype: np.dtype):
        self.dtype = dtype
        self._values = np.zeros(0, dtype=dtype)
        self._docids = np.zeros(0, dtype=np.int64)
        self._pending_values: list[Any] = []
        self._pending_docids: list[int] = []
        self._sorted = True

    def add(self, value: Any, docid: int) -> None:
        self._pending_values.append(value)
        self._pending_docids.append(docid)

    def _ensure_sorted(self) -> None:
        if self._pending_values:
            v = np.asarray(self._pending_values, dtype=self.dtype)
            d = np.asarray(self._pending_docids, dtype=np.int64)
            self._values = np.concatenate([self._values, v])
            self._docids = np.concatenate([self._docids, d])
            self._pending_values.clear()
            self._pending_docids.clear()
            self._sorted = False
        if not self._sorted:
            order = np.argsort(self._values, kind="stable")
            self._values = self._values[order]
            self._docids = self._docids[order]
            self._sorted = True

    def query(self, cond: Condition, n: int) -> np.ndarray:
        self._ensure_sorted()
        op, v = cond.operator, cond.value
        vals, docs = self._values, self._docids
        if op in ("IN", "NOT IN"):
            wanted = v if isinstance(v, (list, tuple)) else [v]
            hits: list[np.ndarray] = []
            for w in wanted:
                lo = np.searchsorted(vals, w, side="left")
                hi = np.searchsorted(vals, w, side="right")
                hits.append(docs[lo:hi])
            ids = np.concatenate(hits) if hits else np.zeros(0, np.int64)
            mask = np.zeros(n, dtype=bool)
            mask[ids[ids < n]] = True
            return ~mask if op == "NOT IN" else mask
        if op == "<":
            sel = docs[: np.searchsorted(vals, v, side="left")]
        elif op == "<=":
            sel = docs[: np.searchsorted(vals, v, side="right")]
        elif op == ">":
            sel = docs[np.searchsorted(vals, v, side="right"):]
        elif op == ">=":
            sel = docs[np.searchsorted(vals, v, side="left"):]
        elif op == "=":
            lo = np.searchsorted(vals, v, side="left")
            hi = np.searchsorted(vals, v, side="right")
            sel = docs[lo:hi]
        else:  # != / <>
            lo = np.searchsorted(vals, v, side="left")
            hi = np.searchsorted(vals, v, side="right")
            sel = np.concatenate([docs[:lo], docs[hi:]])
        mask = np.zeros(n, dtype=bool)
        mask[sel[sel < n]] = True
        return mask


class CompositeScalarIndex:
    """Multi-column index for conjunctive equality filters (reference:
    table/composite_index.h:38 — multi-column RocksDB keys; the manager's
    composite strategy, scalar_index_manager.h:27).

    Keyed by the tuple of the member fields' values: an AND filter whose
    equality conditions cover exactly the member fields resolves in one
    dict lookup instead of intersecting per-field masks. Range/term
    conditions fall back to the per-field path.
    """

    def __init__(self, fields: list[str]):
        self.fields = list(fields)
        self._index: dict[tuple, list[int]] = {}

    def add(self, values: tuple, docid: int) -> None:
        self._index.setdefault(tuple(values), []).append(docid)

    def query_equalities(self, values: tuple, n: int) -> np.ndarray:
        mask = np.zeros(n, dtype=bool)
        ids = np.asarray(self._index.get(tuple(values), []), dtype=np.int64)
        mask[ids[ids < n]] = True
        return mask


class BitmapScalarIndex:
    """Per-distinct-value packed bitmap — for low-cardinality fields
    (reference: table/bitmap_index.h:23 roaring bitmaps)."""

    def __init__(self):
        self._bitmaps: dict[Any, np.ndarray] = {}
        self._size = 0

    def add(self, value: Any, docid: int) -> None:
        values = value if isinstance(value, (list, tuple)) else [value]
        need = docid + 1
        for v in values:
            bm = self._bitmaps.get(v)
            if bm is None or bm.shape[0] < need:
                grown = np.zeros(max(need, 1024, 2 * (bm.shape[0] if bm is not None else 0)), dtype=bool)
                if bm is not None:
                    grown[: bm.shape[0]] = bm
                self._bitmaps[v] = grown
                bm = grown
            bm[docid] = True
        self._size = max(self._size, need)

    def query(self, cond: Condition, n: int) -> np.ndarray:
        op, v = cond.operator, cond.value
        if op in ("<", "<=", ">", ">="):
            # range over the distinct values we know
            keys = [k for k in self._bitmaps if _eval_fixed(np.asarray([k]), cond)[0]]
        elif op in ("=", "IN"):
            keys = v if isinstance(v, (list, tuple)) else [v]
        elif op in ("!=", "<>", "NOT IN"):
            excl = set(v) if isinstance(v, (list, tuple)) else {v}
            keys = [k for k in self._bitmaps if k not in excl]
        else:
            raise ValueError(f"unsupported operator {op} on bitmap index")
        mask = np.zeros(n, dtype=bool)
        for k in keys:
            bm = self._bitmaps.get(k)
            if bm is not None:
                ln = min(n, bm.shape[0])
                mask[:ln] |= bm[:ln]
        return mask

"""Scalar index implementations.

TPU-native re-design of the reference's scalar index family (reference:
internal/engine/table/scalar_index.h:28 `ScalarIndex` ABC;
inverted_index.h:24 RocksDB (field,value,docid) keys with range scan;
bitmap_index.h:23 roaring bitmaps). RocksDB key scans become sorted numpy
arrays with `searchsorted` range slicing; roaring bitmaps become packed
numpy bool arrays — both produce the docid masks the search kernel consumes
directly.

All indexes are append-only over docids (updates soft-delete the old row,
so stale entries are masked by the deletion bitmap downstream — no index
maintenance on delete, same as the vector side).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from vearch_tpu.scalar.filter import Condition, _eval_fixed


class InvertedScalarIndex:
    """Sorted (value, docid) pairs with lazy re-sort; range + term queries.

    The numpy analogue of the reference's RocksDB inverted index
    (reference: table/inverted_index.h:24): ordered key scan ->
    searchsorted slice over a value-sorted array.
    """

    def __init__(self, dtype: np.dtype):
        import threading

        self.dtype = dtype
        self._values = np.zeros(0, dtype=dtype)
        self._docids = np.zeros(0, dtype=np.int64)
        self._pending_values: list[Any] = []
        self._pending_docids: list[int] = []
        self._sorted = True
        # lazy sorting mutates at QUERY time: concurrent searches /
        # upserts must not interleave with the re-sort
        self._sort_lock = threading.Lock()

    def add(self, value: Any, docid: int) -> None:
        with self._sort_lock:
            self._pending_values.append(value)
            self._pending_docids.append(docid)

    def _ensure_sorted(self) -> None:
        with self._sort_lock:
            if self._pending_values:
                v = np.asarray(self._pending_values, dtype=self.dtype)
                d = np.asarray(self._pending_docids, dtype=np.int64)
                self._values = np.concatenate([self._values, v])
                self._docids = np.concatenate([self._docids, d])
                self._pending_values.clear()
                self._pending_docids.clear()
                self._sorted = False
            if not self._sorted:
                order = np.argsort(self._values, kind="stable")
                self._values = self._values[order]
                self._docids = self._docids[order]
                self._sorted = True

    def query(self, cond: Condition, n: int) -> np.ndarray:
        self._ensure_sorted()
        op, v = cond.operator, cond.value
        vals, docs = self._values, self._docids
        if op in ("IN", "NOT IN"):
            wanted = v if isinstance(v, (list, tuple)) else [v]
            hits: list[np.ndarray] = []
            for w in wanted:
                lo = np.searchsorted(vals, w, side="left")
                hi = np.searchsorted(vals, w, side="right")
                hits.append(docs[lo:hi])
            ids = np.concatenate(hits) if hits else np.zeros(0, np.int64)
            mask = np.zeros(n, dtype=bool)
            mask[ids[ids < n]] = True
            return ~mask if op == "NOT IN" else mask
        if op == "<":
            sel = docs[: np.searchsorted(vals, v, side="left")]
        elif op == "<=":
            sel = docs[: np.searchsorted(vals, v, side="right")]
        elif op == ">":
            sel = docs[np.searchsorted(vals, v, side="right"):]
        elif op == ">=":
            sel = docs[np.searchsorted(vals, v, side="left"):]
        elif op == "=":
            lo = np.searchsorted(vals, v, side="left")
            hi = np.searchsorted(vals, v, side="right")
            sel = docs[lo:hi]
        else:  # != / <>
            lo = np.searchsorted(vals, v, side="left")
            hi = np.searchsorted(vals, v, side="right")
            sel = np.concatenate([docs[:lo], docs[hi:]])
        mask = np.zeros(n, dtype=bool)
        mask[sel[sel < n]] = True
        return mask


class CompositeScalarIndex:
    """Multi-column index over sorted composite keys (reference:
    table/composite_index.h:38 — multi-column RocksDB keys; the manager's
    composite strategy, scalar_index_manager.h:27).

    Rows sort lexicographically by the member fields' values, so — like
    an ordered RocksDB key scan — one lookup serves:
    - equality on any PREFIX of the member fields, and
    - optionally one range condition on the NEXT field after the prefix
    (classic composite-key semantics). Everything else falls back to the
    per-field path in the planner.
    """

    def __init__(self, fields: list[str]):
        import threading

        self.fields = list(fields)
        self._rows: list[tuple] = []  # (v1, ..., vk, docid)
        self._sorted = True
        # the lazy sort mutates _rows at QUERY time; list.sort detaches
        # the list mid-sort, so an unsynchronized concurrent search
        # would silently see an empty index and a concurrent add would
        # raise "list modified during sort"
        self._sort_lock = threading.Lock()

    def add(self, values: tuple, docid: int) -> None:
        with self._sort_lock:
            self._rows.append(tuple(values) + (docid,))
            self._sorted = False

    def _ensure_sorted(self) -> None:
        with self._sort_lock:
            if not self._sorted:
                self._rows.sort(key=lambda t: t[:-1])
                self._sorted = True

    def _prefix_bounds(self, lo: int, hi: int, col: int, value,
                       side_left: bool) -> int:
        """Binary search within rows[lo:hi] on column `col` (rows are
        sorted on that column inside an equal prefix)."""
        rows = self._rows
        while lo < hi:
            mid = (lo + hi) // 2
            v = rows[mid][col]
            if v < value or (not side_left and v == value):
                lo = mid + 1
            else:
                hi = mid
        return lo

    def query_prefix(self, eq_values: tuple, range_cond: "Condition | None",
                     n: int) -> np.ndarray:
        """Mask for (field1 = v1 AND ... AND fieldp = vp [AND
        field{p+1} <op> w]) with p = len(eq_values). A probe value whose
        type cannot be compared with the stored values matches nothing
        (the dict-index behavior this replaces), never crashes."""
        self._ensure_sorted()
        mask = np.zeros(n, dtype=bool)
        lo, hi = 0, len(self._rows)
        try:
            for col, v in enumerate(eq_values):
                lo = self._prefix_bounds(lo, hi, col, v, side_left=True)
                hi = self._prefix_bounds(lo, hi, col, v, side_left=False)
            if range_cond is not None and lo < hi:
                col = len(eq_values)
                op, w = range_cond.operator, range_cond.value
                if op == "<":
                    hi = self._prefix_bounds(lo, hi, col, w, side_left=True)
                elif op == "<=":
                    hi = self._prefix_bounds(lo, hi, col, w, side_left=False)
                elif op == ">":
                    lo = self._prefix_bounds(lo, hi, col, w, side_left=False)
                elif op == ">=":
                    lo = self._prefix_bounds(lo, hi, col, w, side_left=True)
                else:
                    raise ValueError(
                        f"composite range does not support {op!r}"
                    )
        except TypeError:
            return mask  # incomparable probe value: no matches
        if lo < hi:
            ids = np.fromiter(
                (t[-1] for t in self._rows[lo:hi]), dtype=np.int64,
                count=hi - lo,
            )
            mask[ids[ids < n]] = True
        return mask


class BitmapScalarIndex:
    """Per-distinct-value packed bitmap — for low-cardinality fields
    (reference: table/bitmap_index.h:23 roaring bitmaps)."""

    def __init__(self):
        self._bitmaps: dict[Any, np.ndarray] = {}
        self._size = 0

    def add(self, value: Any, docid: int) -> None:
        values = value if isinstance(value, (list, tuple)) else [value]
        need = docid + 1
        for v in values:
            bm = self._bitmaps.get(v)
            if bm is None or bm.shape[0] < need:
                grown = np.zeros(max(need, 1024, 2 * (bm.shape[0] if bm is not None else 0)), dtype=bool)
                if bm is not None:
                    grown[: bm.shape[0]] = bm
                self._bitmaps[v] = grown
                bm = grown
            bm[docid] = True
        self._size = max(self._size, need)

    def query(self, cond: Condition, n: int) -> np.ndarray:
        op, v = cond.operator, cond.value
        if op in ("<", "<=", ">", ">="):
            # range over the distinct values we know
            keys = [k for k in self._bitmaps if _eval_fixed(np.asarray([k]), cond)[0]]
        elif op in ("=", "IN"):
            keys = v if isinstance(v, (list, tuple)) else [v]
        elif op in ("!=", "<>", "NOT IN"):
            excl = set(v) if isinstance(v, (list, tuple)) else {v}
            keys = [k for k in self._bitmaps if k not in excl]
        else:
            raise ValueError(f"unsupported operator {op} on bitmap index")
        mask = np.zeros(n, dtype=bool)
        for k in keys:
            bm = self._bitmaps.get(k)
            if bm is not None:
                ln = min(n, bm.shape[0])
                mask[:ln] |= bm[:ln]
        return mask

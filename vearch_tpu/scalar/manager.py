"""Scalar index manager: routes filter conditions to per-field indexes.

TPU-native re-design of the reference's ScalarIndexManager (reference:
table/scalar_index_manager.h:27-43 — plans filter execution across
inverted/bitmap/composite indexes). Here the plan is simpler because
every index yields a docid *mask* and combination is vectorised AND/OR;
fields without an index fall back to a columnar numpy scan in
scalar/filter.py.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from vearch_tpu.engine.types import DataType, ScalarIndexType, TableSchema
from vearch_tpu.scalar.filter import Condition
from vearch_tpu.scalar.indexes import BitmapScalarIndex, InvertedScalarIndex

_NUMERIC = {
    DataType.INT: np.int64,
    DataType.LONG: np.int64,
    DataType.FLOAT: np.float64,
    DataType.DOUBLE: np.float64,
    DataType.DATE: np.int64,
}


class ScalarIndexManager:
    def __init__(self, schema: TableSchema,
                 composite: list[list[str]] | None = None):
        self.schema = schema
        self._indexes: dict[str, Any] = {}
        for f in schema.scalar_fields():
            if f.scalar_index is ScalarIndexType.INVERTED:
                dtype = _NUMERIC.get(f.data_type)
                self._indexes[f.name] = InvertedScalarIndex(
                    np.dtype(dtype) if dtype else np.dtype(object)
                )
            elif f.scalar_index is ScalarIndexType.BITMAP:
                self._indexes[f.name] = BitmapScalarIndex()
        from vearch_tpu.scalar.indexes import CompositeScalarIndex

        self._composites: list[CompositeScalarIndex] = [
            CompositeScalarIndex(fields)
            for fields in (composite or getattr(schema, "composite_indexes",
                                                None) or [])
        ]

    def has_index(self, field: str) -> bool:
        return field in self._indexes

    def query_if_indexed(self, cond: Condition, n: int):
        """Mask from the field's index, or None when the field has no
        index — tolerant of a concurrent remove_field between the
        caller's has_index check and the lookup (online index drop,
        reference: RemoveFieldIndex gamma_api.h:181)."""
        index = self._indexes.get(cond.field)
        return None if index is None else index.query(cond, n)

    def add_field(self, name: str, index) -> None:
        """Publish a (fully built) per-field index atomically."""
        self._indexes[name] = index

    def remove_field(self, name: str) -> None:
        self._indexes.pop(name, None)

    def composites(self) -> list:
        """Declared composite indexes, for the filter planner
        (reference: scalar_index_manager.h FilterIndexPair)."""
        return list(self._composites)

    def composite_for(self, fields: set[str]):
        """A composite index whose member set equals `fields`, if any."""
        for ci in self._composites:
            if set(ci.fields) == fields:
                return ci
        return None

    def add_docs(self, docs: list[dict[str, Any]], base_docid: int) -> None:
        for name, index in self._indexes.items():
            for i, doc in enumerate(docs):
                # None == unset (matches the engine's partial-update and
                # presence conventions); a None in a numeric inverted
                # index would TypeError later inside a filtered search
                if doc.get(name) is not None:
                    index.add(doc[name], base_docid + i)
        for ci in self._composites:
            for i, doc in enumerate(docs):
                # None members are unorderable in the sorted composite
                # rows — skip them, like the reference skips docs
                # missing composite member columns
                if all(doc.get(f) is not None for f in ci.fields):
                    ci.add(tuple(doc[f] for f in ci.fields), base_docid + i)

    def query(self, cond: Condition, n: int) -> np.ndarray:
        return self._indexes[cond.field].query(cond, n)

    def rebuild_from_table(self, table) -> None:
        """Re-derive indexes from the table after Engine.load (indexes are
        rebuildable state; the table is durable — reference: index
        rebuildable, raw data durable)."""
        def column_rows(name):
            try:
                return list(table.column(name))
            except KeyError:
                return table.string_column(name)

        for name, index in self._indexes.items():
            for docid, value in enumerate(column_rows(name)):
                # presence-gated: fixed columns materialize 0-defaults
                # for never-set fields; indexing those would make docs
                # match filters on values they never had
                if value is not None and name in table.set_fields_of(docid):
                    index.add(value, docid)
        for ci in self._composites:
            cols = {f: column_rows(f) for f in ci.fields}
            count = min(len(v) for v in cols.values()) if cols else 0
            for docid in range(count):
                values = tuple(cols[f][docid] for f in ci.fields)
                if all(v is not None for v in values):  # match add_docs
                    ci.add(values, docid)

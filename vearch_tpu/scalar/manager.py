"""Scalar index manager: routes filter conditions to per-field indexes.

TPU-native re-design of the reference's ScalarIndexManager (reference:
table/scalar_index_manager.h:27-43 — plans filter execution across
inverted/bitmap/composite indexes). Here the plan is simpler because
every index yields a docid *mask* and combination is vectorised AND/OR;
fields without an index fall back to a columnar numpy scan in
scalar/filter.py.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from vearch_tpu.engine.types import DataType, ScalarIndexType, TableSchema
from vearch_tpu.scalar.filter import Condition
from vearch_tpu.scalar.indexes import BitmapScalarIndex, InvertedScalarIndex

_NUMERIC = {
    DataType.INT: np.int64,
    DataType.LONG: np.int64,
    DataType.FLOAT: np.float64,
    DataType.DOUBLE: np.float64,
    DataType.DATE: np.int64,
}


class ScalarIndexManager:
    def __init__(self, schema: TableSchema):
        self.schema = schema
        self._indexes: dict[str, Any] = {}
        for f in schema.scalar_fields():
            if f.scalar_index is ScalarIndexType.INVERTED:
                dtype = _NUMERIC.get(f.data_type)
                self._indexes[f.name] = InvertedScalarIndex(
                    np.dtype(dtype) if dtype else np.dtype(object)
                )
            elif f.scalar_index is ScalarIndexType.BITMAP:
                self._indexes[f.name] = BitmapScalarIndex()

    def has_index(self, field: str) -> bool:
        return field in self._indexes

    def add_docs(self, docs: list[dict[str, Any]], base_docid: int) -> None:
        for name, index in self._indexes.items():
            for i, doc in enumerate(docs):
                if name in doc:
                    index.add(doc[name], base_docid + i)

    def query(self, cond: Condition, n: int) -> np.ndarray:
        return self._indexes[cond.field].query(cond, n)

    def rebuild_from_table(self, table) -> None:
        """Re-derive indexes from the table after Engine.load (indexes are
        rebuildable state; the table is durable — reference: index
        rebuildable, raw data durable)."""
        for name, index in self._indexes.items():
            try:
                col = table.column(name)
                rows = list(col)
            except KeyError:
                rows = table.string_column(name)
            for docid, value in enumerate(rows):
                if value is not None:
                    index.add(value, docid)

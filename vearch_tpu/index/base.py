"""Pluggable vector index framework.

TPU-native re-design of the reference's IndexModel ABC + Reflector registry
(reference: internal/engine/index/index_model.h:236 `IndexModel`,
reflector.h:26,67 `REGISTER_INDEX`). The reference's GPU index types
(index/impl/gpu/) are the precedent: an accelerator backend behind the same
plugin seam. Here every index runs its dense math as jit'd JAX programs.

Contract differences from the reference, driven by TPU semantics:
- `add` is append-only with docid == row id; updates/deletes are handled
  by the engine's soft-delete bitmap, indexes never mutate rows in place;
- `search` takes a host validity mask (deletions + scalar filter) and must
  apply it *inside* the kernel (masked top-k), not post-filter, so k valid
  results survive;
- `train`/`build` may be called from a background thread (reference:
  engine.cc:1106 Indexing loop); implementations keep host-side state
  swaps atomic (build new arrays, then publish by reference assignment).
"""

from __future__ import annotations

import abc
from typing import Any

import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.tools import lockcheck


class VectorIndex(abc.ABC):
    """Base class for all vector index types."""

    #: whether train() must run before the index can serve (IVF family)
    needs_training: bool = False

    def __init__(self, params: IndexParams, store: RawVectorStore):
        self.params = params
        self.store = store
        self.metric: MetricType = params.metric_type
        self.trained = not self.needs_training
        self.indexed_count = 0  # rows absorbed into the index structure
        # serialises concurrent absorb() from search threads / the
        # background build thread (reference: engine.cc CAS state machine);
        # minted via lockcheck so VEARCH_LOCKCHECK=1 stress runs verify
        # the narrowed search critical sections hold no surprise orders
        self._absorb_lock = lockcheck.make_lock("index_absorb")

    @property
    def input_dim(self) -> int:
        """Wire-format vector length (binary indexes pack 8 bits/byte —
        reference: faiss binary vectors are d/8 uint8)."""
        return self.store.dimension

    def decode_input(self, batch: np.ndarray) -> np.ndarray:
        """Decode wire-format vectors [b, input_dim] into the stored
        representation [b, dimension] (identity for float indexes)."""
        return np.asarray(batch, dtype=np.float32)

    @abc.abstractmethod
    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Batch search. queries [B, d] f32; valid_mask [n] bool or None.

        `params` carries per-request overrides (nprobe, rerank, ...) —
        the reference's request-level index_params (doc_query.go
        index_params riding each search request).

        Returns (scores [B, k] similarity-oriented (higher=better),
        docids [B, k] int; -1 and -inf pad missing results).
        """

    def train(self, sample: np.ndarray) -> None:
        """Train quantizers on a sample (no-op for non-trained indexes)."""
        self.trained = True

    def absorb(self, upto: int) -> None:
        """Absorb raw-vector rows [indexed_count, upto) into the index
        structure (realtime ingest pump; reference: vector_manager.h:76
        AddRTVecsToIndex). FLAT-style indexes that search the raw store
        directly just advance the counter."""
        self.indexed_count = upto

    def device_footprint_bytes(self) -> int:
        """Modeled resident HBM bytes of this index's device state
        (ops/perf_model.py — the rows-per-chip capacity planner input).
        Default covers indexes that search the raw store directly; index
        types with extra device state (mirrors, bucket tensors) add it."""
        from vearch_tpu.ops import perf_model

        return perf_model.raw_store_footprint_bytes(
            self.store.capacity,
            self.store.dimension,
            self.store.store_dtype.itemsize,
        )

    def device_footprint_per_device_bytes(self) -> int:
        """Modeled resident HBM bytes on EACH chip. Single-device
        indexes hold everything on one chip; mesh-serving indexes
        override with the sharded/replicated split
        (ops/perf_model.per_device_bytes)."""
        return self.device_footprint_bytes()

    def mesh_info(self) -> dict[str, Any] | None:
        """Mesh data-plane placement summary, None when this index is
        not mesh-serving (single device)."""
        return None

    def tiering_info(self) -> dict[str, Any] | None:
        """Tiered-storage summary (per-tier hit/miss/pin counters,
        residency bytes — see docs/TIERING.md), None when this index
        serves entirely from device memory."""
        return None

    # -- index-health drift gauges (obs/quality.py collect_health) -------

    def cell_populations(self) -> list[int] | None:
        """Per-cell member counts for population-imbalance gauges, None
        for index types without a coarse partitioning (FLAT)."""
        return None

    def reconstruction_error(self, sample: int = 256,
                             seed: int = 0) -> float | None:
        """Mean relative reconstruction error ‖x − dequant(quant(x))‖ /
        ‖x‖ over `sample` STORED rows (the codes actually scored at
        serve time, not a fresh re-encode — so stale codebooks and
        corrupt scales both move the gauge). None when the index stores
        rows exactly or is untrained. Host-side only: implementations
        must not dispatch device programs (this runs on the quality
        monitor's background cadence)."""
        return None

    def close(self) -> None:
        """Release background resources (prefetch workers, mmaps).
        Idempotent; default is a no-op for in-memory indexes."""

    # -- persistence (index-specific state only; raw vectors are dumped by
    #    the engine — reference: index is rebuildable, vectors are durable)

    def dump_state(self) -> dict[str, Any]:
        return {}

    def load_state(self, state: dict[str, Any]) -> None:
        pass

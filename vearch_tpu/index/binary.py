"""BINARYIVF and IVFRABITQ index types.

BINARYIVF (reference: index/impl/gamma_index_binary_ivf.cc:62 — faiss
binary IVF with Hamming distance): binary vectors arrive packed as
`dimension/8` uint8 bytes. TPU-native trick: unpack bits to 0/1 floats,
then for bit vectors squared-L2 *is* Hamming distance
(`(a-b)^2 == |a-b|` for a,b in {0,1}), so the entire IVFFLAT machinery —
k-means coarse training, bucket scan on the MXU, deletion masking —
applies unchanged and the reported L2 score is the exact Hamming
distance. No XOR/popcount loops (VPU-serial); one matmul.

IVFRABITQ (reference: index/impl/gamma_index_ivfrabitq.cc:38 — faiss
RaBitQ 1-bit-per-dim quantization, estimator-then-rerank): served as a
progressive THREE-STAGE refinement chain. HBM holds two compressed
views of every row — packed sign-bit planes (1 bit/dim, the stage-0
tier; ops/binary_scan.py) and the int8 RaBitQ reconstruction
`centroid + scale * sign(resid)` (the stage-1 tier, shared Int8Mirror
layout) — while the raw base stays in the store (device buffer for RAM
stores, NVMe mmap for disk stores, where stage-2 gathers ride the
readahead path). A search runs binary scan -> top r0 -> int8 rescore
-> top r1 -> exact rerank -> top k; for a RAM store all three stages
fuse into ONE device program, and under a mesh the bit planes shard
row-wise in lockstep with the mirror (parallel/sharded.py
sharded_binary_refine). `r0`/`r1` are runtime-tunable (request params
or /ps/engine/config index_params) with perf-model auto-defaults
(ops/perf_model.refine_depths); `stage0: "off"` falls back to the
int8-only full-scan chain for A/B and recall-parity gating.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.int8_mirror import Int8Mirror
from vearch_tpu.index.ivf import IVFFlatIndex, IVFPQIndex
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import binary_scan as binary_ops
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops import perf_model
from vearch_tpu.ops.distance import to_device_mask


@register_index("BINARYIVF")
class BinaryIVFIndex(IVFFlatIndex):
    """Hamming-metric IVF over packed binary vectors."""

    def __init__(self, params: IndexParams, store: RawVectorStore):
        if store.dimension % 8 != 0:
            raise ValueError(
                f"BINARYIVF dimension {store.dimension} must be a multiple of 8"
            )
        super().__init__(params, store)

    @property
    def input_dim(self) -> int:
        # wire format: dimension/8 packed bytes (reference: faiss binary)
        return self.store.dimension // 8

    def decode_input(self, batch: np.ndarray) -> np.ndarray:
        """[b, d/8] uint8 -> [b, d] 0/1 float32."""
        packed = np.asarray(batch, dtype=np.uint8)
        bits = np.unpackbits(packed, axis=1, count=self.store.dimension)
        return bits.astype(np.float32)


@register_index("IVFRABITQ")
class IVFRaBitQIndex(IVFPQIndex):
    """1-bit stage-0 tier + progressive three-stage refinement.

    Overrides the PQ codebook stages: there are no codebooks — rows
    store as packed sign planes (stage 0) and as the RaBitQ first-order
    reconstruction `centroid + mean|resid| * sign(resid)` quantized
    into the int8 mirror (stage 1). `nsubvector`/`nbits` are ignored.
    """

    def __init__(self, params: IndexParams, store: RawVectorStore):
        # bypass IVFPQ's m-divides-d validation: there are no subvectors
        params = IndexParams(
            index_type=params.index_type,
            metric_type=params.metric_type,
            params={**params.params, "nsubvector": 1},
        )
        super().__init__(params, store)
        # stage-0 tier: packed sign planes of the (normalized) rows,
        # same append/flush/shard machinery as the int8 mirror
        self._bits = Int8Mirror(store.dimension, storage="bits")

    def _train_extra(self, sample: np.ndarray) -> None:
        # no codebooks to train; only the coarse quantizer (in base train)
        self.codebooks = None
        self._codes = np.zeros((0, 1), dtype=np.uint8)

    def _absorb_rows(
        self, rows: np.ndarray, assign: np.ndarray, start_docid: int
    ) -> None:
        cents = np.asarray(self.centroids)
        resid = rows - cents[assign]
        scale = np.maximum(
            np.abs(resid).mean(axis=1), 1e-12
        ).astype(np.float32)
        recon = cents[assign] + scale[:, None] * np.sign(resid)
        self._mirror.append(recon.astype(np.float32), start=start_docid)
        # stage-0 bit planes quantize the ROW itself (not the residual):
        # the binary scan is partition-global, so its estimator must not
        # depend on a per-row centroid term the kernel can't afford
        self._bits.append(rows, start=start_docid)

    def device_footprint_bytes(self) -> int:
        return super().device_footprint_bytes() + self._bits.device_bytes()

    # -- three-stage serving ---------------------------------------------------

    def _stage0_enabled(self, params: dict | None) -> bool:
        mode = str((params or {}).get(
            "stage0", self.params.get("stage0", "binary")
        )).lower()
        if mode not in ("binary", "off"):
            raise ValueError(f"stage0 must be binary|off, got {mode!r}")
        return mode == "binary"

    def _stage_depths(self, k: int, params: dict | None) -> tuple[int, int]:
        """(r0, r1) candidate depths: request params win, then index
        params (runtime-tunable via /ps/engine/config index_params),
        then the perf model's documented auto-defaults."""
        p = params or {}
        n = max(self.indexed_count, 1)
        auto_r0, auto_r1 = perf_model.refine_depths(k, n)
        r1 = int(p.get("r1", p.get(
            "rerank", self.params.get(
                "r1", self.params.get("rerank", auto_r1))
        )))
        r0 = int(p.get("r0", self.params.get("r0", auto_r0)))
        r1 = min(max(r1, k), n)
        r0 = min(max(r0, r1), n)
        return r0, r1

    def search(self, queries, k, valid_mask, params=None):
        if not self._stage0_enabled(params):
            # A/B escape hatch + recall-parity baseline: the int8-only
            # full-scan chain (scan + exact rerank) over the stage-1
            # mirror, exactly the pre-stage-0 serving path
            p = dict(params or {})
            p["scan_mode"] = "full"
            return super().search(queries, k, valid_mask, p)
        assert self.trained, "IVFRABITQ search before training"
        from vearch_tpu.index._store_paths import is_disk_store

        q = self._maybe_normalize(np.asarray(queries, np.float32))
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        r0, r1 = self._stage_depths(k, params)
        topk_mode = (params or {}).get(
            "topk_mode", self.params.get("topk_mode", "auto")
        )
        if self._mesh_enabled(params) and not is_disk_store(self.store):
            return self._search_binary_mesh(
                q, k, valid_mask, params, metric, r0, r1, topk_mode
            )
        t_flush0 = time.monotonic()
        planes, p_scale, p_vsq = self._bits.flush()
        approx8, m_scale, m_vsq = self._mirror.flush()
        n_pad = planes.shape[0]
        valid = to_device_mask(valid_mask, self.indexed_count, n_pad)
        ivf_ops.note_stage_phase("flush", t_flush0, time.monotonic())
        import jax.numpy as jnp

        qd = jnp.asarray(q)
        if is_disk_store(self.store):
            # stages 0-1 on device, stage-2 rows host-gathered through
            # the mmap + coalesced-readahead path (tiering/readahead.py
            # via store.get_rows) — the raw base never enters HBM
            t0 = time.monotonic()
            ivf_ops.note_dispatch("binary_refine_scan")
            _, cand_i = binary_ops.binary_refine_candidates(
                qd, planes, p_scale, p_vsq, approx8, m_scale, m_vsq,
                valid, r0, r1, metric, topk_mode, self.mirror_storage,
            )
            cand_i.block_until_ready()
            ivf_ops.note_stage_phase("scan", t0, time.monotonic())
            from vearch_tpu.index._store_paths import rerank_against_store

            t2 = time.monotonic()
            ivf_ops.note_dispatch("rerank")
            scores, ids = rerank_against_store(
                self.store, q, cand_i, min(k, int(cand_i.shape[1])),
                self.metric,
            )
            scores, ids = jax.device_get((scores, ids))
            ivf_ops.note_stage_phase("rerank", t2, time.monotonic())
            binary_ops.note_refine_search(
                "disk", self.indexed_count, r0, r1, k, q.shape[0])
            return self._pad_to_k(scores, ids, k)
        base, base_sqnorm, _ = self.store.device_buffer()
        t0 = time.monotonic()
        ivf_ops.note_dispatch("binary_refine_rerank")
        scores, ids = binary_ops.binary_refine_rerank(
            qd, planes, p_scale, p_vsq, approx8, m_scale, m_vsq, valid,
            base, base_sqnorm, r0, r1, k,
            scan_metric=metric, rerank_metric=self.metric,
            topk_mode=topk_mode, storage=self.mirror_storage,
        )
        scores, ids = jax.device_get((scores, ids))
        ivf_ops.note_stage_phase("refine", t0, time.monotonic())
        binary_ops.note_refine_search(
            "fused", self.indexed_count, r0, r1, k, q.shape[0])
        return self._pad_to_k(scores, ids, k)

    def _search_binary_mesh(
        self, q: np.ndarray, k: int, valid_mask, params, metric,
        r0: int, r1: int, topk_mode: str,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Mesh-spanning three-stage chain: bit planes, int8 mirror,
        and raw base row-sharded in lockstep (identical ShardedRowCache
        alignment); stages 0-1 shard-local, one all_gather merge, pmax
        exact rerank — ONE shard_map program."""
        from vearch_tpu.parallel import mesh as mesh_lib
        from vearch_tpu.parallel.sharded import sharded_binary_refine

        t_place0 = time.monotonic()
        mesh = self._serving_mesh(params)
        planes, p_scale, p_vsq = self._bits.flush_sharded(mesh)
        a8, m_scale, m_vsq = self._mirror.flush_sharded(mesh)
        n = self.indexed_count
        cap = self._bits._sh_cache.capacity(mesh, n)
        valid_sh = self._mesh_valid_sharded(mesh, valid_mask, n, cap)
        base, base_sqn, _ = self.store.device_buffer_sharded(mesh)
        qd, b = mesh_lib.shard_queries(mesh, np.asarray(q, np.float32))
        ivf_ops.note_mesh_phase("place", t_place0, time.monotonic())
        t0 = time.monotonic()
        ivf_ops.note_dispatch("sharded_binary_refine_rerank")
        scores, ids = sharded_binary_refine(
            mesh, planes, p_scale, p_vsq, a8, m_scale, m_vsq, valid_sh,
            base, base_sqn, qd, r0, r1, min(k, r1),
            scan_metric=metric, rerank_metric=self.metric,
            topk_mode=topk_mode, storage=self.mirror_storage,
        )
        scores, ids = jax.device_get((scores, ids))
        ivf_ops.note_stage_phase("refine", t0, time.monotonic())
        binary_ops.note_refine_search("mesh", n, r0, r1, k, b)
        return self._pad_to_k(scores[:b], ids[:b], k)

    def device_footprint_per_device_bytes(self) -> int:
        if not self._mesh_enabled(None):
            return self.device_footprint_bytes()
        # bit planes shard row-wise with the mirror: add their payload
        # to the sharded term of the IVFPQ per-device model
        base = super().device_footprint_per_device_bytes()
        mesh = self._serving_mesh(None)
        n_shards = int(mesh.shape["data"])
        return base + -(-self._bits.device_bytes() // max(n_shards, 1))

    def _publish(self) -> None:
        # probe mode unsupported for 1-bit codes; the stage-0/stage-1
        # mirrors (filled in _absorb_rows) are always used
        self._dirty = False

    def dump_state(self):
        state = super().dump_state()
        state.pop("codebooks", None)
        return state

    def _load_codebooks(self, state):
        self._codes = np.zeros((0, 1), dtype=np.uint8)

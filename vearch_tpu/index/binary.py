"""BINARYIVF and IVFRABITQ index types.

BINARYIVF (reference: index/impl/gamma_index_binary_ivf.cc:62 — faiss
binary IVF with Hamming distance): binary vectors arrive packed as
`dimension/8` uint8 bytes. TPU-native trick: unpack bits to 0/1 floats,
then for bit vectors squared-L2 *is* Hamming distance
(`(a-b)^2 == |a-b|` for a,b in {0,1}), so the entire IVFFLAT machinery —
k-means coarse training, bucket scan on the MXU, deletion masking —
applies unchanged and the reported L2 score is the exact Hamming
distance. No XOR/popcount loops (VPU-serial); one matmul.

IVFRABITQ (reference: index/impl/gamma_index_ivfrabitq.cc:38 — faiss
RaBitQ 1-bit-per-dim quantization of residuals): residuals quantize to
sign bits + a per-row magnitude. The device scan reconstructs
`centroid + scale * sign` as an int8 row (the shared Int8Mirror layout)
and scores by matmul; exact rerank against raw vectors restores
precision, mirroring RaBitQ's estimator-then-rerank usage.
"""

from __future__ import annotations

import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams
from vearch_tpu.index.int8_mirror import Int8Mirror
from vearch_tpu.index.ivf import IVFFlatIndex, IVFPQIndex
from vearch_tpu.index.registry import register_index


@register_index("BINARYIVF")
class BinaryIVFIndex(IVFFlatIndex):
    """Hamming-metric IVF over packed binary vectors."""

    def __init__(self, params: IndexParams, store: RawVectorStore):
        if store.dimension % 8 != 0:
            raise ValueError(
                f"BINARYIVF dimension {store.dimension} must be a multiple of 8"
            )
        super().__init__(params, store)

    @property
    def input_dim(self) -> int:
        # wire format: dimension/8 packed bytes (reference: faiss binary)
        return self.store.dimension // 8

    def decode_input(self, batch: np.ndarray) -> np.ndarray:
        """[b, d/8] uint8 -> [b, d] 0/1 float32."""
        packed = np.asarray(batch, dtype=np.uint8)
        bits = np.unpackbits(packed, axis=1, count=self.store.dimension)
        return bits.astype(np.float32)


@register_index("IVFRABITQ")
class IVFRaBitQIndex(IVFPQIndex):
    """1-bit residual quantization: IVFPQ machinery with sign-bit codes.

    Overrides the PQ codebook stages: residuals store as sign(resid) with
    per-row mean-magnitude scale (the RaBitQ estimator's first-order
    form). `nsubvector`/`nbits` are ignored — the effective code is 1 bit
    per dimension.
    """

    def __init__(self, params: IndexParams, store: RawVectorStore):
        # bypass IVFPQ's m-divides-d validation: there are no subvectors
        params = IndexParams(
            index_type=params.index_type,
            metric_type=params.metric_type,
            params={**params.params, "nsubvector": 1},
        )
        super().__init__(params, store)

    def _train_extra(self, sample: np.ndarray) -> None:
        # no codebooks to train; only the coarse quantizer (in base train)
        self.codebooks = None
        self._codes = np.zeros((0, 1), dtype=np.uint8)

    def _absorb_rows(
        self, rows: np.ndarray, assign: np.ndarray, start_docid: int
    ) -> None:
        cents = np.asarray(self.centroids)
        resid = rows - cents[assign]
        scale = np.maximum(
            np.abs(resid).mean(axis=1), 1e-12
        ).astype(np.float32)
        recon = cents[assign] + scale[:, None] * np.sign(resid)
        self._mirror.append(recon.astype(np.float32), start=start_docid)

    def _publish(self) -> None:
        # probe mode unsupported for 1-bit codes in round 1; the full-scan
        # mirror (filled in _absorb_rows) is always used
        self._dirty = False

    def search(self, queries, k, valid_mask, params=None):
        params = dict(params or {})
        params["scan_mode"] = "full"
        return super().search(queries, k, valid_mask, params)

    def dump_state(self):
        state = super().dump_state()
        state.pop("codebooks", None)
        return state

    def _load_codebooks(self, state):
        self._codes = np.zeros((0, 1), dtype=np.uint8)

"""Multi-chip FLAT index: one partition spanning a local TPU slice.

Where the reference scales only by adding partitions across machines
(SURVEY §2.3), a TPU host owns several chips over ICI — an axis the
reference never had. `FLAT` with `{"sharded": true}` row-shards the
partition's vectors over a (data x query) mesh of all local devices and
merges per-shard top-k with an `all_gather` on ICI
(parallel/sharded.py). The cluster layer still shards across hosts.

Realtime model: absorb re-places the whole host buffer on the mesh when
rows arrived (placement is one H2D per device; fine at refresh-interval
cadence — an incremental per-shard tail-append is a round-2 item). The
deletion/filter mask is sharded per search, cached per bitmap version by
the engine upstream.
"""

from __future__ import annotations

import jax
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.registry import register_index
from vearch_tpu.parallel import mesh as mesh_lib
from vearch_tpu.parallel.sharded import sharded_flat_search


@register_index("FLAT_SHARDED")
class ShardedFlatIndex(VectorIndex):
    """Exact search over all local devices (index_type FLAT_SHARDED, or
    FLAT with params {"sharded": true} via the registry alias in
    index/flat.py)."""

    needs_training = False

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        n_dev = int(params.get("n_devices", 0)) or len(jax.devices())
        query_axis = int(params.get("query_axis", 1))
        self.mesh = mesh_lib.make_mesh(n_dev, query_axis=query_axis)
        self._base = None
        self._sqnorm = None
        self._placed_rows = 0

    def _maybe_normalize(self, x: np.ndarray) -> np.ndarray:
        if self.metric is MetricType.COSINE:
            n = np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-15)
            return (x / n).astype(np.float32)
        return x

    def _place(self) -> None:
        from vearch_tpu.ops.distance import sqnorms

        host = self._maybe_normalize(
            self.store.host_view().astype(np.float32)
        ).astype(self.store.store_dtype)
        self._base, self._n = mesh_lib.shard_rows(self.mesh, host)
        self._sqnorm = sqnorms(self._base)
        self._placed_rows = self.store.count

    def absorb(self, upto: int) -> None:
        with self._absorb_lock:
            self.indexed_count = max(self.indexed_count, upto)

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        if self._base is None or self._placed_rows < self.store.count:
            self._place()
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        # sharded validity mask: alive rows up to the placed count
        n_pad = self._base.shape[0]
        v = np.zeros(n_pad, dtype=bool)
        n = min(self._placed_rows, n_pad)
        if valid_mask is not None:
            vm = np.asarray(valid_mask)[:n]
            v[: vm.shape[0]] = vm
        else:
            v[:n] = True
        valid_dev, _ = mesh_lib.shard_rows(self.mesh, v)
        qd, b = mesh_lib.shard_queries(
            self.mesh, q.astype(self.store.store_dtype)
        )
        scores, ids = sharded_flat_search(
            self.mesh, self._base, self._sqnorm, valid_dev, qd,
            min(k, max(n, 1)), metric,
        )
        scores, ids = jax.device_get((scores, ids))
        scores, ids = scores[:b], ids[:b]
        if scores.shape[1] < k:
            pad = k - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)),
                            constant_values=float("-inf"))
            ids = np.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
        return scores[:, :k], ids[:, :k]

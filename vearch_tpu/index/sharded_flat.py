"""Multi-chip FLAT index: one partition spanning a local TPU slice.

Where the reference scales only by adding partitions across machines
(SURVEY §2.3), a TPU host owns several chips over ICI — an axis the
reference never had. `FLAT` with `{"sharded": true}` row-shards the
partition's vectors over a (data x query) mesh of all local devices and
merges per-shard top-k with an `all_gather` on ICI
(parallel/sharded.py). The cluster layer still shards across hosts.

Realtime model: absorb tail-appends per shard — one H2D per touched
device of only the new rows (parallel/mesh.py ShardedRowCache); a full
re-place happens only when the sharded capacity grows. The
deletion/filter mask is sharded per mask identity, cached per bitmap
version by the engine upstream.
"""

from __future__ import annotations

import jax
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.parallel import mesh as mesh_lib
from vearch_tpu.parallel.mesh import ShardedRowCache
from vearch_tpu.parallel.sharded import sharded_flat_search


@register_index("FLAT_SHARDED")
class ShardedFlatIndex(VectorIndex):
    """Exact search over all local devices (index_type FLAT_SHARDED, or
    FLAT with params {"sharded": true} via the registry alias in
    index/flat.py)."""

    needs_training = False

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        shape = params.get("mesh_shape")
        if shape is not None:
            # unified knob shared with the IVF mesh path (engine
            # apply_config fans it into index params)
            self.mesh = mesh_lib.mesh_from_shape(shape)
        else:
            n_dev = int(params.get("n_devices", 0)) or len(jax.devices())
            query_axis = int(params.get("query_axis", 1))
            self.mesh = mesh_lib.make_mesh(n_dev, query_axis=query_axis)
        self._sh_cache = ShardedRowCache(align=128, sqnorm_of=0)
        self._placed_rows = 0
        self._valid_src = object()  # sentinel: never matches a real mask
        self._valid_dev = None
        self._valid_key = (-1, -1)

    def _maybe_normalize(self, x: np.ndarray) -> np.ndarray:
        if self.metric is MetricType.COSINE:
            n = np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-15)
            return (x / n).astype(np.float32)
        return x

    def _place(self):
        """Sharded base + derived sqnorm column, tail-appended when rows
        merely grew within capacity. Normalization is per-row, so the
        append window produces bit-identical rows to a full rebuild."""
        n = self.store.count
        d = self.store.dimension

        def build(cap):
            host = np.zeros((cap, d), dtype=np.float32)
            host[:n] = self._maybe_normalize(
                self.store.host_view()[:n].astype(np.float32)
            )
            return (host.astype(self.store.store_dtype),)

        def append(lo, hi):
            win = np.zeros((hi - lo, d), dtype=np.float32)
            m = min(hi, n) - lo
            if m > 0:
                win[:m] = self._maybe_normalize(
                    np.asarray(
                        self.store.host_view()[lo : lo + m], np.float32
                    )
                )
            return (win.astype(self.store.store_dtype),)

        (base,), _ = self._sh_cache.get(self.mesh, n, build, append)
        self._placed_rows = n
        return base, self._sh_cache.sqnorm

    def absorb(self, upto: int) -> None:
        with self._absorb_lock:
            self.indexed_count = max(self.indexed_count, upto)

    def _valid_sharded(self, valid_mask, n: int, n_pad: int):
        """Sharded alive mask, cached per mask identity (the engine
        reuses one alive-mask object per bitmap version; the strong
        source reference keeps the id() check sound)."""
        if (
            self._valid_src is valid_mask
            and valid_mask is not None
            and self._valid_key == (n, n_pad)
        ):
            return self._valid_dev
        v = np.zeros(n_pad, dtype=bool)
        if valid_mask is not None:
            vm = np.asarray(valid_mask)[:n]
            v[: vm.shape[0]] = vm
        else:
            v[:n] = True
        self._valid_dev, _ = mesh_lib.shard_rows(self.mesh, v)
        self._valid_src = valid_mask
        self._valid_key = (n, n_pad)
        return self._valid_dev

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        base, sqnorm = self._place()
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        n = min(self._placed_rows, base.shape[0])
        valid_dev = self._valid_sharded(valid_mask, n, base.shape[0])
        qd, b = mesh_lib.shard_queries(
            self.mesh, q.astype(self.store.store_dtype)
        )
        ivf_ops.note_dispatch("sharded_flat_scan")
        scores, ids = sharded_flat_search(
            self.mesh, base, sqnorm, valid_dev, qd,
            min(k, max(n, 1)), metric,
        )
        scores, ids = jax.device_get((scores, ids))
        scores, ids = scores[:b], ids[:b]
        if scores.shape[1] < k:
            pad = k - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)),
                            constant_values=float("-inf"))
            ids = np.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
        return scores[:, :k], ids[:, :k]

    def placement_stats(self) -> dict:
        """Rebuild/append/H2D counters of the sharded placement (perf
        gates assert absorb never re-places the full buffer)."""
        return dict(self._sh_cache.stats)

    def mesh_info(self) -> dict | None:
        return {
            "devices": int(self.mesh.size),
            "data_shards": int(self.mesh.shape["data"]),
            "query_shards": int(self.mesh.shape["query"]),
            "per_device_bytes": self.device_footprint_per_device_bytes(),
            "placement": self.placement_stats(),
        }

    def device_footprint_per_device_bytes(self) -> int:
        from vearch_tpu.ops import perf_model

        cap = self._sh_cache.capacity(self.mesh, self.store.count)
        sharded = perf_model.raw_store_footprint_bytes(
            cap, self.store.dimension, self.store.store_dtype.itemsize
        )
        return perf_model.per_device_bytes(
            sharded, 0, int(self.mesh.shape["data"])
        )

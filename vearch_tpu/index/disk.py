"""Disk-resident ANN index (the reference's DiskANN tier, TPU-native).

Reference: index/impl/diskann/gamma_index_diskann_static.cc:28 —
DISKANN_STATIC keeps PQ codes in RAM, full vectors + a Vamana graph on
disk, and beam-searches the graph with read-ahead. A graph walk is a
pointer chase — the worst possible shape for a TPU. The TPU-native
formulation keeps the *capability* (serve a partition far larger than
host RAM and HBM) with MXU-shaped machinery:

    disk   raw.f32       full vectors, docid-ordered mmap (rerank tier)
           approx8.i8    per-row int8 approximations (scan tier)
           meta2.f32     per-row (scale, ||approx||^2)
           assign.i32    per-row coarse assignment (bucket rebuild)
    RAM    per-bucket docid lists (~8 B/row), centroids, and a
           frequency-admitted slab tier (tiering/HostRamSlabTier) so an
           HBM miss costs a memcpy, not a page-fault walk
    HBM    coarse centroids (always resident) + a bucket slab cache
           with hot-bucket pinning (HbmBucketCache)

Search: coarse top-nprobe on device -> resolve probed buckets against
the HBM cache (misses page slabs RAM->device; RAM misses gather from
the mmap) -> int8 bucket scan (ops/ivf.py cached_bucket_scan) -> exact
rerank of the top candidates against host-gathered raw rows. The
coarse probe result also feeds a successor predictor whose predicted
next probe set prefetches asynchronously (tiering/prefetch.py), so a
steady workload's transfers overlap the previous scan and its warmed
hot path launches zero H2D bytes. Hot buckets never touch disk again;
the OS page cache backstops warm ones. See docs/TIERING.md.

Divergences from the reference, on purpose:
- per-row int8 replaces PQ for the scan tier: the scan reads decoded
  bytes either way, int8 recall is strictly better than PQ32, and the
  disk cost (d bytes/row) is paid in the mmap, not RAM;
- realtime appends work (absorb writes the tail of the mmaps and bumps
  bucket generations) — the reference's disk tier is static-only
  (space sets enable_realtime=false).
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.hbm_cache import HbmBucketCache
from vearch_tpu.index.int8_mirror import quantize_rows
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops import kmeans as km
from vearch_tpu.ops.distance import to_device_mask
from vearch_tpu.tiering import (
    HostRamSlabTier,
    PrefetchWorker,
    SequencePredictor,
    readahead,
)
from vearch_tpu.tools import lockcheck

_ABSORB_CHUNK = 262_144  # rows per device assignment batch


@register_index("DISKANN")
@register_index("DISKANN_STATIC")
class DiskANNIndex(VectorIndex):
    needs_training = True

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        self.nlist = int(params.get("ncentroids", params.get("nlist", 1024)))
        self.default_nprobe = int(params.get("nprobe", 32))
        self.train_sample = int(params.get("training_sample", 262_144))
        self.train_iters = int(params.get("train_iters", 10))
        self.cache_mb = int(params.get("cache_mb", 512))
        # tiered-storage knobs (docs/TIERING.md): host-RAM slab tier
        # budget, prefetch on/off, hot-bucket pin share of HBM slots,
        # RAM-tier admission threshold
        self.ram_mb = int(params.get("ram_mb", 256))
        self.prefetch_enabled = bool(params.get("prefetch", True))
        self._pin_slots_param = params.get("pin_slots")
        admit_after = int(params.get("admit_after", 2))
        self.centroids: jax.Array | None = None
        self._members: list[list[int]] = []
        self._gens: dict[int, int] = {}
        self._cache: HbmBucketCache | None = None
        self._ram_tier = HostRamSlabTier(
            self.ram_mb << 20, admit_after=admit_after
        )
        self._predictor = SequencePredictor()
        self._prefetcher = PrefetchWorker(self._prefetch_job)
        self._pf_lock = lockcheck.make_lock("diskann_prefetch")
        directory = params.get("index_dir") or getattr(
            store, "directory", None
        )
        if directory is None:
            # memory-backed store + disk index: keep the scan files in a
            # scratch dir (tests / ad-hoc use); durable deployments pair
            # DISKANN with a DiskRawVectorStore so both tiers co-locate
            directory = tempfile.mkdtemp(prefix="vearch_diskann_")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._a8_path = os.path.join(directory, "approx8.i8")
        self._m2_path = os.path.join(directory, "meta2.f32")
        self._as_path = os.path.join(directory, "assign.i32")
        self._a8: np.memmap | None = None
        self._m2: np.memmap | None = None
        self._assign: np.memmap | None = None

    # -- disk scan-tier files ------------------------------------------------

    def _map_files(self, capacity: int) -> None:  # lint: allow[serving-blocking] geometric-growth remap: truncate+rebind amortized over absorb batches, no data copy
        d = self.store.dimension
        for path, row_bytes in (
            (self._a8_path, d),
            (self._m2_path, 8),
            (self._as_path, 4),
        ):
            want = capacity * row_bytes
            have = os.path.getsize(path) if os.path.exists(path) else 0
            if have < want:
                with open(path, "ab") as f:
                    f.truncate(want)
        # capacity = min across the three files: a crash between the
        # truncates above must not brick reopen (rows beyond the durable
        # indexed_count are garbage either way)
        cap = min(
            os.path.getsize(self._a8_path) // d,
            os.path.getsize(self._m2_path) // 8,
            os.path.getsize(self._as_path) // 4,
        )
        self._a8 = np.memmap(
            self._a8_path, dtype=np.int8, mode="r+", shape=(cap, d)
        )
        self._m2 = np.memmap(
            self._m2_path, dtype=np.float32, mode="r+", shape=(cap, 2)
        )
        self._assign = np.memmap(
            self._as_path, dtype=np.int32, mode="r+", shape=(cap,)
        )

    def _ensure_capacity(self, n: int) -> None:
        if self._a8 is None or self._a8.shape[0] < n:
            cap = max(n, 4096, 0 if self._a8 is None else self._a8.shape[0] * 2)
            self._map_files(cap)

    # -- training ------------------------------------------------------------

    def _maybe_normalize(self, x: np.ndarray) -> np.ndarray:
        if self.metric is MetricType.COSINE:
            n = np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-15)
            return (x / n).astype(np.float32)
        return x

    def train(self, sample: np.ndarray) -> None:
        x = np.asarray(sample, np.float32)
        if x.shape[0] > self.train_sample:
            idx = np.random.default_rng(0).choice(
                x.shape[0], self.train_sample, replace=False
            )
            x = x[idx]
        x = self._maybe_normalize(x)
        self.centroids = km.train_kmeans(
            jnp.asarray(x), k=self.nlist, iters=self.train_iters
        )
        self._members = [[] for _ in range(self.nlist)]
        self._gens = {}
        self.trained = True

    # -- realtime absorb -----------------------------------------------------

    def absorb(self, upto: int) -> None:
        with self._absorb_lock:
            if not self.trained or upto <= self.indexed_count:
                self.indexed_count = max(self.indexed_count, upto)
                return
            self._ensure_capacity(upto)
            start = self.indexed_count
            host = self.store.host_view()
            for lo in range(start, upto, _ABSORB_CHUNK):
                hi = min(lo + _ABSORB_CHUNK, upto)
                rows = self._maybe_normalize(
                    np.asarray(host[lo:hi], dtype=np.float32)
                )
                assign = np.asarray(
                    km.assign_clusters(jnp.asarray(rows), self.centroids)
                ).astype(np.int32)
                q8, scale, vsq = quantize_rows(rows)
                self._a8[lo:hi] = q8
                self._m2[lo:hi, 0] = scale
                self._m2[lo:hi, 1] = vsq
                self._assign[lo:hi] = assign
                self._extend_members(assign, lo)
            self.indexed_count = upto

    def cell_populations(self) -> list[int] | None:
        with self._absorb_lock:
            if not self.trained:
                return None
            return [len(mm) for mm in self._members]

    def reconstruction_error(self, sample: int = 256,
                             seed: int = 0) -> float | None:
        """Dequantize STORED int8 scan rows (a8 * scale) against the raw
        store — reads the mmaps directly, no device work."""
        with self._absorb_lock:
            n = int(self.indexed_count)
            if not self.trained or n == 0 or self._a8 is None:
                return None
            rng = np.random.default_rng(seed)
            ids = np.sort(rng.choice(n, size=min(int(sample), n),
                                     replace=False))
            raw = self._maybe_normalize(
                np.asarray(self.store.host_view()[ids], dtype=np.float32)
            )
            approx = (
                np.asarray(self._a8[ids], dtype=np.float32)
                * np.asarray(self._m2[ids, 0], dtype=np.float32)[:, None]
            )
            num = np.linalg.norm(raw - approx, axis=1)
            den = np.maximum(np.linalg.norm(raw, axis=1), 1e-12)
            return float(np.mean(num / den))

    def _extend_members(self, assign: np.ndarray, start: int) -> None:
        order = np.argsort(assign, kind="stable")
        sorted_assign = assign[order]
        docids = order.astype(np.int64) + start
        bounds = np.searchsorted(sorted_assign, np.arange(self.nlist + 1))
        for c in np.unique(sorted_assign):
            lo, hi = bounds[c], bounds[c + 1]
            self._members[int(c)].extend(docids[lo:hi].tolist())
            self._gens[int(c)] = self._gens.get(int(c), 0) + 1

    # -- cache ---------------------------------------------------------------

    def _slab_cap(self) -> int:
        """Slab width: next power of two >= longest bucket (floor 128).
        Geometric growth keeps cache rebuilds (and the scan kernel's
        recompiles) O(log n) under steady ingest instead of one per
        128-row growth of the longest bucket."""
        longest = max((len(mm) for mm in self._members), default=0)
        cap = 128
        while cap < longest:
            cap *= 2
        return cap

    def _ensure_cache(self) -> HbmBucketCache:
        cap = self._slab_cap()
        d = self.store.dimension
        slab_bytes = cap * (d + 12)
        # cache_mb is a hard HBM budget — never exceeded; a probe set
        # that cannot fit one pass degrades to multiple fixed-shape
        # passes (plan_passes/acquire) instead of failing the search
        slots = max(1, min(self.nlist, (self.cache_mb << 20) // slab_bytes))
        if (
            self._cache is None
            or self._cache.cap < cap
            or self._cache.slots != slots
        ):
            old = self._cache
            self._cache = HbmBucketCache(
                d, slots, cap, pin_slots=self._pin_slots_param
            )
            if old is not None:
                # capacity regrow, not a reset: keep operator-facing
                # lifetime counters continuous across the rebuild
                self._cache.seed_counters(old.stats())
        return self._cache

    def _make_fetch(
        self, gens: dict[int, int], n_snap: int
    ) -> Callable[[int], tuple[np.ndarray, ...]]:
        """Slab fetch closure for a consistent (gens, indexed_count)
        snapshot. An HBM miss goes to the host-RAM slab tier first; a
        RAM miss pays the NVMe mmap gather. Safe to run outside the
        absorb lock: absorb writes mmap rows BEFORE publishing bucket
        membership, appended docids only grow past `n_snap` (filtered
        here and masked by the validity snapshot on device)."""

        def fetch(b: int):
            def loader():
                ids = np.asarray(self._members[b], dtype=np.int64)
                ids = ids[ids < n_snap]
                a8, m2 = self._a8, self._m2
                ids = ids[ids < a8.shape[0]]
                # kernel read-ahead before the strided mmap gathers: a
                # cold slab faults its rows as a few batched NVMe reads
                # instead of one synchronous fault per page
                # (tiering/readahead.py — page cache only, zero H2D)
                readahead.advise_rows(a8, ids)
                readahead.advise_rows(m2, ids)
                return (
                    np.asarray(a8[ids]),
                    np.asarray(m2[ids, 0]),
                    np.asarray(m2[ids, 1]),
                    ids.astype(np.int32),
                )

            return self._ram_tier.get(b, gens.get(b, 0), loader)

        return fetch

    def _fetch_bucket(self, b: int):
        """Single-bucket slab fetch at the live snapshot (direct cache
        pokes in tests; the search path builds fetch closures over a
        consistent snapshot via _make_fetch)."""
        return self._make_fetch(dict(self._gens), self.indexed_count)(b)

    # -- search --------------------------------------------------------------

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        assert self.trained, "DISKANN search before training"
        p = params or {}
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        nprobe = min(
            int(p.get("nprobe", self.default_nprobe)), self.nlist
        )
        r = int(p.get("rerank", self.params.get("rerank", max(10 * k, 128))))
        r = max(min(r, max(self.indexed_count, 1)), k)
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        # narrowed critical section (satellite): the absorb lock only
        # guards the snapshot — cache shape, generation map, durable row
        # count. The coarse-probe dispatch, slab resolution and scan all
        # run outside it, so realtime ingest never stalls behind a
        # disk-tier search (HbmBucketCache has its own lock; the fetch
        # closure is snapshot-consistent, see _make_fetch).
        with self._absorb_lock:
            cache = self._ensure_cache()
            gens = dict(self._gens)
            n_indexed = self.indexed_count
        qd = jnp.asarray(q)
        probes = np.asarray(
            ivf_ops._coarse_probes(qd, self.centroids, nprobe)
        )  # [B, nprobe] host
        self._schedule_prefetch(probes, gens)
        fetch = self._make_fetch(gens, n_indexed)
        n_pad = max(self.store.capacity, 1)
        valid = to_device_mask(valid_mask, n_indexed, n_pad)
        groups = cache.plan_passes(probes)
        if len(groups) == 1:
            slots, pools = cache.acquire(probes, gens, fetch)
            cand_s, cand_i = ivf_ops.cached_bucket_scan(
                qd, *pools, jnp.asarray(slots), valid, r, metric,
            )
        else:
            # graceful degradation (satellite): probe set exceeds the
            # evictable HBM slots — scan it in several fixed-shape
            # passes (deferred probes ride as slot -1, masked in the
            # kernel) and fold the per-pass top lists. Buckets are
            # disjoint across passes, so the fold never sees duplicate
            # docids.
            parts_s: list[np.ndarray] = []
            parts_i: list[np.ndarray] = []
            for group in groups:
                slots, pools = cache.acquire(
                    probes, gens, fetch, restrict=group
                )
                s_g, i_g = ivf_ops.cached_bucket_scan(
                    qd, *pools, jnp.asarray(slots), valid, r, metric,
                )
                parts_s.append(np.asarray(s_g))
                parts_i.append(np.asarray(i_g))
            cat_s = np.concatenate(parts_s, axis=1)
            cat_i = np.concatenate(parts_i, axis=1)
            order = np.argsort(-cat_s, axis=1, kind="stable")[:, :r]
            cand_s = np.take_along_axis(cat_s, order, axis=1)
            cand_i = np.take_along_axis(cat_i, order, axis=1)
        from vearch_tpu.index._store_paths import rerank_against_store

        # rerank tier: raw rows fault in from the mmap'd store (or the
        # HBM buffer when paired with a memory store)
        scores, ids = rerank_against_store(
            self.store, np.asarray(queries, np.float32), cand_i, k,
            self.metric,
        )
        scores, ids = jax.device_get((scores, ids))
        if scores.shape[1] >= k:
            return scores[:, :k], ids[:, :k]
        pad = k - scores.shape[1]
        return (
            np.pad(scores, ((0, 0), (0, pad)), constant_values=float("-inf")),
            np.pad(ids, ((0, 0), (0, pad)), constant_values=-1),
        )

    # -- tiering: prefetch + observability -----------------------------------

    def _schedule_prefetch(
        self, probes: np.ndarray, gens: dict[int, int]
    ) -> None:
        """Feed this query's probe set to the successor predictor and
        hand the predicted NEXT probe set to the async worker, which
        pages those slabs host->device while the current scan runs on
        the previous pool references."""
        if not self.prefetch_enabled:
            return
        t0 = time.monotonic()
        key = tuple(sorted({int(b) for b in np.ravel(probes)}))
        with self._pf_lock:
            predicted = self._predictor.observe(key)
        if predicted is not None:
            self._prefetcher.submit((predicted, gens))
        ivf_ops.note_tier_phase("prefetch", t0, time.monotonic())

    def _prefetch_job(self, job: tuple[tuple[int, ...], dict[int, int]]):
        buckets, gens = job
        cache = self._cache
        if cache is None:
            return
        fetch = self._make_fetch(gens, self.indexed_count)
        cache.prefetch(buckets, gens, fetch)

    def tiering_info(self) -> dict[str, Any]:
        cache = self._cache
        return {
            "kind": "diskann",
            "hbm": cache.stats() if cache is not None else None,
            "ram": self._ram_tier.stats(),
            "prefetch": {
                "enabled": self.prefetch_enabled,
                "predictor_keys": len(self._predictor),
                **self._prefetcher.stats(),
            },
        }

    def close(self) -> None:
        self._prefetcher.close()

    # -- persistence ---------------------------------------------------------

    def dump_state(self) -> dict[str, Any]:
        if not self.trained:
            return {}
        with self._absorb_lock:
            if self._a8 is not None:
                self._a8.flush()
                self._m2.flush()
                self._assign.flush()
            return {
                "centroids": np.asarray(self.centroids),
                "indexed_count": np.int64(self.indexed_count),
            }

    def load_state(self, state: dict[str, Any]) -> None:
        if "centroids" not in state:
            return
        self.centroids = jnp.asarray(state["centroids"])
        self.trained = True
        self._members = [[] for _ in range(self.nlist)]
        self._gens = {}
        n = int(state.get("indexed_count", 0))
        n = min(n, self.store.count)
        if n > 0 and os.path.exists(self._as_path):
            # the scan-tier mmaps are durable: rebuild bucket lists from
            # the persisted assignment column instead of re-encoding
            self._ensure_capacity(n)
            self._extend_members(np.asarray(self._assign[:n]), 0)
            self.indexed_count = n
        if self._cache is not None:
            self._cache.invalidate()
        self._ram_tier.clear()
        # tail rows past the durable count re-absorb from raw vectors
        self.absorb(self.store.count)

"""HNSW index type — TPU-native interpretation.

The reference vendors hnswlib (reference: index/impl/hnswlib/
gamma_index_hnswlib.cc:130) because pointer-chasing graph walks are the
right sublinear structure for CPUs. On TPU the same query budget buys a
dense MXU scan: at any N that fits a chip, one int8 matmul beats a graph
walk (hundreds of *dependent* gathers serialised through the VPU). So the
HNSW *index type* is kept for API parity — spaces declaring
`index_type: "HNSW"` work, `efSearch`/`efConstruction` are accepted — and
maps onto a two-stage device scan:

    stage 1: int8-quantized scan of all rows (the coarse pass)
    stage 2: exact rerank of the top `efSearch` candidates

This preserves HNSW's contract (approximate; efSearch = recall knob;
realtime inserts; deletes honored) with strictly better recall at the
same latency on this hardware; BASELINE.md's HNSW row ("brute-force
rerank on TPU") sanctions exactly this design. A host-side graph build
remains the escape hatch for beyond-HBM regimes (docs/ROADMAP).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.int8_mirror import Int8Mirror
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops.distance import to_device_mask


@register_index("HNSW")
class HNSWIndex(VectorIndex):
    needs_training = False

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        self.ef_search = int(params.get("efSearch", params.get("ef_search", 64)))
        self._mirror = Int8Mirror(store.dimension)

    def _maybe_normalize(self, x: np.ndarray) -> np.ndarray:
        if self.metric is MetricType.COSINE:
            n = np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-15)
            return (x / n).astype(np.float32)
        return x

    def absorb(self, upto: int) -> None:
        with self._absorb_lock:
            if upto <= self.indexed_count:
                return
            start = self.indexed_count
            rows = self._maybe_normalize(
                self.store.host_view()[start:upto].astype(np.float32)
            )
            self._mirror.append(rows, start=start)
            self.indexed_count = upto

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        self.absorb(self.store.count)
        a8, scale, vsq = self._mirror.flush()
        p = params or {}
        ef = max(int(p.get("efSearch", p.get("ef_search", self.ef_search))), k)
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        valid = to_device_mask(valid_mask, self.indexed_count, a8.shape[0])
        cand_s, cand_i = ivf_ops.int8_scan_candidates(
            jnp.asarray(q), a8, scale, vsq, valid,
            min(ef, max(self.indexed_count, 1)), metric,
        )
        from vearch_tpu.index._store_paths import rerank_against_store

        scores, ids = rerank_against_store(
            self.store, q, cand_i, k, self.metric,
        )
        scores, ids = jax.device_get((scores, ids))
        if scores.shape[1] < k:
            pad = k - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)),
                            constant_values=float("-inf"))
            ids = np.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
        return scores[:, :k], ids[:, :k]

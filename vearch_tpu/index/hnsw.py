"""HNSW index type — TPU-native interpretation with a real graph tier.

The reference vendors hnswlib (reference: index/impl/hnswlib/
gamma_index_hnswlib.cc:130). Two serving modes live behind the one
index type (param `graph`, default "auto"):

- **scan** (TPU default): pointer-chasing graph walks are wrong for the
  MXU; at any N that fits a chip, a two-stage device scan (int8 coarse
  pass + exact rerank of the top `efSearch`) beats a graph walk while
  preserving HNSW's contract (approximate, efSearch recall knob,
  realtime inserts, deletes honored).
- **graph**: an actual host-side HNSW graph (csrc/vearch_hnsw.cpp — an
  independent implementation of Malkov & Yashunin 2016, not vendored
  hnswlib), for the regimes a scan can't serve: beyond-HBM row counts
  (pairs with DiskRawVectorStore: the graph owns its own host copy) and
  single-query low-latency paths with no device round-trip.

"auto" = graph when the raw store is disk-resident and the native
toolchain is present, else scan. `graph: true` forces the graph (errors
without a toolchain); `graph: false` forces the scan.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.int8_mirror import Int8Mirror
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops.distance import to_device_mask


@register_index("HNSW")
class HNSWIndex(VectorIndex):
    needs_training = False

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        self.ef_search = int(params.get("efSearch", params.get("ef_search", 64)))
        self.m = int(params.get("nlinks", params.get("M", 16)))
        self.ef_construction = int(
            params.get("efConstruction", params.get("ef_construction", 200))
        )
        self._mirror = Int8Mirror(store.dimension)
        self._graph = None
        mode = params.get("graph", "auto")
        if mode == "auto":
            from vearch_tpu.index._store_paths import is_disk_store
            from vearch_tpu.native import hnsw_graph

            self.use_graph = is_disk_store(store) and hnsw_graph.available()
        else:
            self.use_graph = bool(mode)
        if self.use_graph:
            from vearch_tpu.native.hnsw_graph import HnswGraph

            self._graph = HnswGraph(
                store.dimension, m=self.m,
                ef_construction=self.ef_construction,
                ip=self.metric is not MetricType.L2,
            )

    def _maybe_normalize(self, x: np.ndarray) -> np.ndarray:
        if self.metric is MetricType.COSINE:
            n = np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-15)
            return (x / n).astype(np.float32)
        return x

    def absorb(self, upto: int) -> None:
        with self._absorb_lock:
            if upto <= self.indexed_count:
                return
            start = self.indexed_count
            rows = self._maybe_normalize(
                np.asarray(self.store.host_view()[start:upto],
                           dtype=np.float32)
            )
            if self._graph is not None:
                self._graph.add(rows)
            else:
                self._mirror.append(rows, start=start)
            self.indexed_count = upto

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        self.absorb(self.store.count)
        p = params or {}
        ef = max(int(p.get("efSearch", p.get("ef_search", self.ef_search))), k)
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        if self._graph is not None:
            return self._search_graph(q, k, ef, valid_mask)
        return self._search_scan(q, k, ef, valid_mask)

    def _search_graph(
        self, q: np.ndarray, k: int, ef: int, valid_mask
    ) -> tuple[np.ndarray, np.ndarray]:
        mask = None
        n = self._graph.count
        if valid_mask is not None:
            mask = np.asarray(valid_mask, dtype=np.uint8)
            if mask.shape[0] < n:
                mask = np.pad(mask, (0, n - mask.shape[0]))
        elif n > self.indexed_count:
            # a crash-rollback load can leave phantom graph nodes past
            # the durable count; mask them out rather than serving them
            mask = np.zeros(n, dtype=np.uint8)
            mask[: self.indexed_count] = 1
        scores, ids = self._graph.search(q, k, ef, mask)
        # graph distances are exact f32 (the graph owns full-precision
        # rows), so scores are final: -L2^2, or dot on normalized rows
        return scores, ids.astype(np.int64)

    def _search_scan(
        self, q: np.ndarray, k: int, ef: int, valid_mask
    ) -> tuple[np.ndarray, np.ndarray]:
        a8, scale, vsq = self._mirror.flush()
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        valid = to_device_mask(valid_mask, self.indexed_count, a8.shape[0])
        cand_s, cand_i = ivf_ops.int8_scan_candidates(
            jnp.asarray(q), a8, scale, vsq, valid,
            min(ef, max(self.indexed_count, 1)), metric,
        )
        from vearch_tpu.index._store_paths import rerank_against_store

        scores, ids = rerank_against_store(
            self.store, q, cand_i, k, self.metric,
        )
        scores, ids = jax.device_get((scores, ids))
        if scores.shape[1] < k:
            pad = k - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)),
                            constant_values=float("-inf"))
            ids = np.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
        return scores[:, :k], ids[:, :k]

    # -- persistence ---------------------------------------------------------

    def dump_state(self) -> dict[str, Any]:
        if self._graph is None or self._graph.count == 0:
            return {}
        import os
        import tempfile

        fd, tmp = tempfile.mkstemp(suffix=".hnsw")
        os.close(fd)
        try:
            self._graph.save(tmp)
            with open(tmp, "rb") as f:
                blob = np.frombuffer(f.read(), dtype=np.uint8)
        finally:
            os.unlink(tmp)
        return {
            "graph_blob": blob,
            "indexed_count": np.int64(self.indexed_count),
        }

    def load_state(self, state: dict[str, Any]) -> None:
        if "graph_blob" not in state or self._graph is None:
            # scan mode re-absorbs from raw vectors on demand
            return
        import os
        import tempfile

        from vearch_tpu.native.hnsw_graph import HnswGraph

        fd, tmp = tempfile.mkstemp(suffix=".hnsw")
        os.close(fd)
        try:
            with open(tmp, "wb") as f:
                f.write(np.asarray(state["graph_blob"]).tobytes())
            self._graph = HnswGraph.load(
                tmp, self.store.dimension, m=self.m,
                ef_construction=self.ef_construction,
                ip=self.metric is not MetricType.L2,
            )
        except ValueError:
            # corrupt blob: raw vectors are the durable source of truth
            # — fall through to the rebuild path below
            self._graph = HnswGraph(
                self.store.dimension, m=self.m,
                ef_construction=self.ef_construction,
                ip=self.metric is not MetricType.L2,
            )
        finally:
            os.unlink(tmp)
        saved = int(state.get("indexed_count", self._graph.count))
        if saved != self._graph.count or saved > self.store.count:
            # graph ids must stay == docids; any snapshot/store mismatch
            # (crash rollback) means appends would misalign — rebuild
            self._graph = HnswGraph(
                self.store.dimension, m=self.m,
                ef_construction=self.ef_construction,
                ip=self.metric is not MetricType.L2,
            )
            self.indexed_count = 0
        else:
            self.indexed_count = saved
        # tail rows past the snapshot re-absorb from the raw store
        self.absorb(self.store.count)

"""Docid-ordered int8 device mirror (shared by the scan-based indexes).

Append-only host arrays (codes, per-row scale, squared norm) with a
lazily-flushed device copy — the same tail-flush pattern as
RawVectorStore.device_buffer, for quantized payloads. Rows are int8 per-
row-scaled approximations; scoring dequantises inside the matmul kernel
(ops/ivf.py int8_scan_candidates).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.ops import perf_model
from vearch_tpu.tools import lockcheck


def quantize_rows(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-row symmetric int8 quantization; returns (q8, scale, vsq)."""
    scale = np.maximum(np.abs(rows).max(axis=1) / 127.0, 1e-12).astype(
        np.float32
    )
    q8 = np.clip(np.rint(rows / scale[:, None]), -127, 127).astype(np.int8)
    deq = q8.astype(np.float32) * scale[:, None]
    vsq = np.sum(deq * deq, axis=1).astype(np.float32)
    return q8, scale, vsq


def quantize_rows_int4(
    rows: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-row symmetric int4 quantization, nibble-packed.

    Layout contract (ops/ivf.py unpack_int4): dims [0, d/2) in the low
    nibble, dims [d/2, d) in the high nibble — concat, not interleave.
    Returns (packed [n, d/2] uint8, scale, vsq of the DEQUANTIZED rows).
    """
    d = rows.shape[1]
    assert d % 2 == 0, "int4 storage needs an even dimension"
    scale = np.maximum(np.abs(rows).max(axis=1) / 7.0, 1e-12).astype(
        np.float32
    )
    q4 = np.clip(np.rint(rows / scale[:, None]), -7, 7).astype(np.int8)
    deq = q4.astype(np.float32) * scale[:, None]
    vsq = np.sum(deq * deq, axis=1).astype(np.float32)
    lo = q4[:, : d // 2] & 0xF
    hi = q4[:, d // 2 :] & 0xF
    packed = (lo | (hi << 4)).astype(np.uint8)
    return packed, scale, vsq


class Int8Mirror:
    """Compressed device mirror; `storage` picks the tier:
    - "int8" (default): 1 byte/dim, ~0.8% row-max quantization error;
    - "int4": 0.5 byte/dim — HALF the resident HBM per row (the usual
      rows-per-chip limiter), ~7% row-max error that the exact rerank
      stage absorbs;
    - "bits": 1 BIT/dim packed sign planes (ops/binary_scan.py
      pack_sign_rows) — the stage-0 tier of the progressive refinement
      chain, 8x denser than int8's row payload; selection-grade scores
      that the int8 + exact refinement stages restore.
    """

    def __init__(self, dimension: int, storage: str = "int8"):
        self.dimension = dimension
        self.storage = str(storage).lower()
        if self.storage not in ("int8", "int4", "bits"):
            raise ValueError(f"unknown mirror storage {storage!r}")
        if self.storage == "int4" and dimension % 2 != 0:
            raise ValueError("int4 mirror storage needs an even dimension")
        if self.storage == "int8":
            width, dt = dimension, np.int8
        elif self.storage == "int4":
            width, dt = dimension // 2, np.uint8
        else:  # bits: byte-padded packed sign planes
            width, dt = -(-dimension // 8), np.uint8
        self._row_width = width
        self._row_dtype = dt
        self._h8 = np.zeros((0, width), dtype=dt)
        self._h_scale = np.zeros(0, dtype=np.float32)
        self._h_vsq = np.zeros(0, dtype=np.float32)
        self._n = 0
        self._d8: jax.Array | None = None
        self._d_scale: jax.Array | None = None
        self._d_vsq: jax.Array | None = None
        self._d_rows = 0
        # append vs flush race: a concurrent append may REPLACE the
        # host arrays (capacity growth) while flush reads them — the
        # tail-flush would mix old and new buffers. One leaf lock
        # serializes host-array mutation against device placement.
        self._flush_lock = lockcheck.make_lock("mirror_flush")

    @property
    def count(self) -> int:
        return self._n

    def device_bytes(self) -> int:
        """Modeled resident HBM bytes of the flushed mirror: compressed
        rows + per-row scale + per-row ||v||^2, at the 512-aligned
        capacity (ops/perf_model.py mirror_footprint_bytes)."""
        cap = self._h8.shape[0]
        return cap * self._row_width + 2 * cap * 4

    def append_quantized(
        self, q8: np.ndarray, scale: np.ndarray, vsq: np.ndarray,
        start: int | None = None,
    ) -> None:
        """Write rows at [start, start+b) (default: append at count)."""
        with self._flush_lock:
            self._append_locked(q8, scale, vsq, start)

    def _append_locked(
        self, q8: np.ndarray, scale: np.ndarray, vsq: np.ndarray,
        start: int | None,
    ) -> None:
        start = self._n if start is None else start
        need = start + q8.shape[0]
        if self._h8.shape[0] < need:
            # capacity stays 512-aligned: the block-max top-k reshapes
            # the score row into [n/512, 512] blocks (ops/ivf.py)
            cap = max(need, self._h8.shape[0] * 2, 1024)
            cap = -(-cap // 512) * 512
            g8 = np.zeros((cap, self._row_width), dtype=self._row_dtype)
            gs = np.zeros(cap, dtype=np.float32)
            gv = np.zeros(cap, dtype=np.float32)
            g8[: self._n] = self._h8[: self._n]
            gs[: self._n] = self._h_scale[: self._n]
            gv[: self._n] = self._h_vsq[: self._n]
            self._h8, self._h_scale, self._h_vsq = g8, gs, gv
        sl = slice(start, need)
        self._h8[sl] = q8
        self._h_scale[sl] = scale
        self._h_vsq[sl] = vsq
        self._n = max(self._n, need)
        # rows below the mirrored high-water mark were overwritten
        # (re-absorb after load_state): force re-upload from `start`
        if start < self._d_rows:
            self._d_rows = start
        if self._sh_cache is not None:
            self._sh_cache.lower_rows(start)

    def append(self, rows: np.ndarray, start: int | None = None) -> None:
        if self.storage == "bits":
            from vearch_tpu.ops.binary_scan import pack_sign_rows

            quant = pack_sign_rows
        else:
            quant = (
                quantize_rows if self.storage == "int8"
                else quantize_rows_int4
            )
        self.append_quantized(*quant(rows), start=start)

    def flush_sharded(self, mesh) -> tuple[jax.Array, jax.Array, jax.Array]:
        """Device views row-sharded over the mesh "data" axis — one
        logical partition spanning all chips (the capacity regime: rows
        beyond a single chip's HBM). Rows are padded so every shard is
        512-aligned (block-max top-k contract). Growth within the cached
        capacity tail-appends per shard (one H2D per touched device of
        only the new rows); a full re-place happens only on capacity
        change — realtime absorb on a mesh partition stays incremental.
        """
        if self._sh_cache is None:
            from vearch_tpu.parallel.mesh import ShardedRowCache

            self._sh_cache = ShardedRowCache(align=512)

        def build(cap):
            h8 = np.zeros((cap, self._row_width), dtype=self._row_dtype)
            hs = np.zeros(cap, dtype=np.float32)
            hv = np.zeros(cap, dtype=np.float32)
            n = self._n
            h8[:n] = self._h8[:n]
            hs[:n] = self._h_scale[:n]
            hv[:n] = self._h_vsq[:n]
            return h8, hs, hv

        def append(lo, hi):
            return (
                np.ascontiguousarray(self._h8[lo:hi]),
                np.ascontiguousarray(self._h_scale[lo:hi]),
                np.ascontiguousarray(self._h_vsq[lo:hi]),
            )

        with self._flush_lock:
            arrays, _ = self._sh_cache.get(mesh, self._n, build, append)
        return arrays

    _sh_cache = None

    def flush(self) -> tuple[jax.Array, jax.Array, jax.Array]:
        """Device views [cap, d] / [cap] / [cap]; rows >= count are padding."""
        with self._flush_lock:
            return self._flush_locked()

    def _flush_locked(self) -> tuple[jax.Array, jax.Array, jax.Array]:
        n = self._n
        cap = self._h8.shape[0]
        if self._d8 is None or self._d8.shape[0] != cap:
            self._d8 = jnp.asarray(self._h8)
            self._d_scale = jnp.asarray(self._h_scale)
            self._d_vsq = jnp.asarray(self._h_vsq)
            # .nbytes is metadata — no host sync
            perf_model.note_h2d_bytes(
                int(self._d8.nbytes) + int(self._d_scale.nbytes)
                + int(self._d_vsq.nbytes)
            )
            self._d_rows = n
        elif self._d_rows < n:
            sl = slice(self._d_rows, n)
            perf_model.note_h2d_bytes(
                int(self._h8[sl].nbytes) + int(self._h_scale[sl].nbytes)
                + int(self._h_vsq[sl].nbytes)
            )
            self._d8 = jax.lax.dynamic_update_slice(
                self._d8, jnp.asarray(self._h8[sl]), (self._d_rows, 0)
            )
            self._d_scale = jax.lax.dynamic_update_slice(
                self._d_scale, jnp.asarray(self._h_scale[sl]), (self._d_rows,)
            )
            self._d_vsq = jax.lax.dynamic_update_slice(
                self._d_vsq, jnp.asarray(self._h_vsq[sl]), (self._d_rows,)
            )
            self._d_rows = n
        return self._d8, self._d_scale, self._d_vsq

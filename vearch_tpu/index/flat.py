"""FLAT (exact brute-force) index.

TPU-native re-design of the reference's FLAT index (reference:
index/impl/gamma_index_flat.cc:183) — there a SIMD-dispatched scan, here
one MXU matmul over the device-resident raw-vector buffer + masked top-k.
Exact by construction; no training; results match numpy to fp32 tolerance
(the reference's exactness invariant, test/utils/vearch_utils.py:55).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops.distance import brute_force_search, to_device_mask


@register_index("FLAT")
class FlatIndex(VectorIndex):
    needs_training = False

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        from vearch_tpu.index._store_paths import disk_brute_force, is_disk_store

        if is_disk_store(self.store):
            # beyond-RAM store: stream the mmap through the device in
            # fixed-shape chunks instead of mirroring it into HBM
            return disk_brute_force(
                self.store, np.asarray(queries, np.float32), k,
                valid_mask, self.metric,
            )
        base, base_sqnorm, n = self.store.device_buffer()
        cap = base.shape[0]
        mask = to_device_mask(valid_mask, n, cap)
        ivf_ops.note_dispatch("flat_scan")
        scores, ids = brute_force_search(
            jnp.asarray(queries, dtype=base.dtype),
            base,
            mask,
            k,
            self.metric,
            base_sqnorm,
        )
        # single batched D2H fetch: device->host latency dominates small
        # results, so never fetch scores and ids separately
        return jax.device_get((scores, ids))

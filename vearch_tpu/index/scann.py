"""SCANN index type — score-aware quantization (reference `VEARCH` type).

The reference registers this as VEARCH, wrapping Google's ScaNN library
(reference: index/impl/scann/gamma_index_vearch.cc:20, scann_api.h) with
params ncentroids, nsubvector, ns_threshold (noise-shaping threshold,
default 0.2), reordering (exact rerank), metric (DotProduct default).

TPU-native re-design: same coarse k-means partitioning + realtime absorb
as IVFPQ, but the PQ codebooks are trained (and rows encoded) under the
anisotropic loss of Guo et al. 2020 via `ops/scann.py` — error parallel
to the datapoint is weighted eta = (d-1) T^2/(1-T^2) times orthogonal
error, which is what makes ScaNN win on MIPS recall at equal bitrate.
The scan path is untouched: anisotropic codes decode into the same int8
mirror scanned by one MXU matmul, then exact rerank ("reordering").
"""

from __future__ import annotations

import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams
from vearch_tpu.index.ivf import IVFPQIndex
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import scann as scann_ops


@register_index("SCANN")
@register_index("VEARCH")
class ScannIndex(IVFPQIndex):
    def __init__(self, params: IndexParams, store: RawVectorStore):
        if "nsubvector" not in params.params and "m" not in params.params:
            # reference VearchModelParams default nsubvector=64; clamp to
            # a divisor of the dimension so small-dim tables still work.
            # Copy rather than mutate the caller's schema object (same
            # pattern as BinaryIVFIndex).
            m = 64
            while store.dimension % m != 0:
                m //= 2
            params = IndexParams(
                params.index_type, params.metric_type,
                {**params.params, "nsubvector": m},
            )
        super().__init__(params, store)
        if self.opq:
            raise ValueError("SCANN does not take the opq option")
        t = float(params.get("ns_threshold", 0.2))
        self.eta = float(
            params.get("eta", scann_ops.eta_from_threshold(t, store.dimension))
        )
        # reference `reordering` toggles exact rerank; rerank is already
        # our default path, so reordering=False maps to minimal depth
        self.reordering = bool(params.get("reordering", True))

    def _unit_dirs(self, rows: np.ndarray) -> np.ndarray:
        n = np.linalg.norm(rows, axis=-1, keepdims=True)
        return (rows / np.maximum(n, 1e-15)).astype(np.float32)

    def _fit_codebooks(self, resid: np.ndarray, sample: np.ndarray):
        return scann_ops.train_anisotropic_pq(
            resid, self._unit_dirs(sample), m=self.m, ksub=self.ksub,
            eta=self.eta, iters=self.train_iters,
        )

    def _encode_rows(self, resid: np.ndarray, rows: np.ndarray) -> np.ndarray:
        return np.asarray(scann_ops.encode_anisotropic(
            resid, self._unit_dirs(rows), self.codebooks, self.eta,
        ))

    def _exact_rerank_enabled(self, params: dict | None) -> bool:
        # reference reordering=false returns pure quantized scores with
        # NO exact pass (scann_api.h reordering); an explicit rerank
        # depth — request OR index level — re-enables it
        if self.reordering:
            return True
        return bool(
            (params or {}).get("rerank") or self.params.get("rerank")
        )

    def _rerank_depth(self, k: int, params: dict | None) -> int:
        if not self._exact_rerank_enabled(params):
            return k  # candidate depth = k: no rerank pass consumes more
        return super()._rerank_depth(k, params)

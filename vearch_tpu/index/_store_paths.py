"""Store-aware search primitives shared by the index types.

Two storage regimes exist (reference: raw_vector_factory.h MemoryOnly vs
RocksDB): device-mirrored RAM stores and mmap'd disk stores
(engine/disk_vector.py). Index hot paths branch here instead of each
reimplementing the disk case:

- `rerank_against_store`: exact rerank of candidate ids — against the
  HBM-resident raw buffer for RAM stores, or via a host mmap gather +
  one [B, r, d] upload for disk stores;
- `disk_brute_force`: chunked exact scan streaming the mmap through the
  device in fixed-shape chunks (the FLAT / pre-training fallback for
  beyond-RAM stores; fixed chunk shape = one XLA compile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.types import MetricType
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops.distance import brute_force_search

_CHUNK = 262_144  # rows per device chunk for the streaming scan


def is_disk_store(store) -> bool:
    return bool(getattr(store, "durable_on_disk", False))


def rerank_against_store(
    store,
    q: np.ndarray,          # [B, d] f32 (normalized upstream if cosine)
    cand_i: jax.Array,      # [B, r] i32
    k: int,
    metric: MetricType,
) -> tuple[jax.Array, jax.Array]:
    k = min(k, int(cand_i.shape[1]))
    if is_disk_store(store):
        ci = np.asarray(cand_i)
        safe = np.maximum(ci, 0).astype(np.int64)
        vecs = np.asarray(
            store.get_rows(safe.ravel()), dtype=np.float32
        ).reshape(ci.shape[0], ci.shape[1], -1)
        return ivf_ops.exact_rerank_gathered(
            jnp.asarray(q, jnp.float32), jnp.asarray(ci),
            jnp.asarray(vecs), k, metric,
        )
    base, base_sqnorm, _ = store.device_buffer()
    return ivf_ops.exact_rerank(
        jnp.asarray(q, dtype=base.dtype), cand_i, base, base_sqnorm,
        k, metric,
    )


def disk_brute_force(
    store,
    queries: np.ndarray,    # [B, d] f32
    k: int,
    valid_mask: np.ndarray | None,
    metric: MetricType,
) -> tuple[np.ndarray, np.ndarray]:
    """Exact scan of a disk store: stream fixed-shape chunks through the
    device, fold per-chunk top-k on host. Exactness matches FLAT."""
    n = store.count
    b = queries.shape[0]
    k_eff = min(k, max(n, 1))
    host = store.host_view()
    q = jnp.asarray(queries, jnp.float32)
    # chunk = next power of two >= n, capped: small tables pay O(n), not
    # a full 262k-row pad; compile count stays logarithmic in n
    chunk = 128
    while chunk < min(n, _CHUNK):
        chunk *= 2
    rows = np.zeros((chunk, store.dimension), dtype=np.float32)
    all_s: list[np.ndarray] = []
    all_i: list[np.ndarray] = []
    for lo in range(0, max(n, 1), chunk):
        hi = min(lo + chunk, n)
        rows[:] = 0.0
        rows[: hi - lo] = host[lo:hi]
        mask = np.zeros(chunk, dtype=bool)
        if valid_mask is None:
            mask[: hi - lo] = True
        else:
            mask[: hi - lo] = np.asarray(valid_mask[lo:hi], dtype=bool)
        s, i = brute_force_search(
            q, jnp.asarray(rows), jnp.asarray(mask), k_eff, metric,
        )
        s, i = jax.device_get((s, i))
        all_s.append(s)
        all_i.append(np.where(i >= 0, i + lo, -1))
    s_cat = np.concatenate(all_s, axis=1)
    i_cat = np.concatenate(all_i, axis=1)
    order = np.argsort(-s_cat, axis=1)[:, :k]
    top_s = np.take_along_axis(s_cat, order, axis=1)
    top_i = np.take_along_axis(i_cat, order, axis=1)
    if top_s.shape[1] < k:
        pad = k - top_s.shape[1]
        top_s = np.pad(top_s, ((0, 0), (0, pad)),
                       constant_values=float("-inf"))
        top_i = np.pad(top_i, ((0, 0), (0, pad)), constant_values=-1)
    return top_s, top_i

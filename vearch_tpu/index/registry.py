"""Index type registry (reference: reflector.h:67 `REGISTER_INDEX` macro +
index_factory). Index modules self-register at import; `create_index` is
the engine's only entry point, so new index types plug in without touching
engine code — the same seam the reference uses for its GPU backends."""

from __future__ import annotations

from typing import Callable, Type

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams
from vearch_tpu.index.base import VectorIndex

_REGISTRY: dict[str, Type[VectorIndex]] = {}


def register_index(name: str) -> Callable[[Type[VectorIndex]], Type[VectorIndex]]:
    def deco(cls: Type[VectorIndex]) -> Type[VectorIndex]:
        _REGISTRY[name.upper()] = cls
        return cls

    return deco


def create_index(params: IndexParams, store: RawVectorStore) -> VectorIndex:
    name = params.index_type.upper()
    if name == "FLAT" and params.get("sharded"):
        name = "FLAT_SHARDED"  # multi-chip variant behind the same type
    if name not in _REGISTRY:
        # import built-ins lazily so registration is a side effect of use
        import vearch_tpu.index.builtin  # noqa: F401
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown index_type {params.index_type!r}; "
            f"registered: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name](params, store)


def registered_types() -> list[str]:
    import vearch_tpu.index.builtin  # noqa: F401

    return sorted(_REGISTRY)

"""IVFFLAT and IVFPQ index types.

TPU-native re-design of the reference's realtime IVF indexes (reference:
index/impl/gamma_index_ivfflat.cc:198, gamma_index_ivfpq.cc:36 + the
RTInvertIndex realtime lists, index/realtime/realtime_invert_index.h:24).

Where the reference grows per-bucket linked segments that CPU threads scan,
TPU wants static-shaped dense arrays:

- host side keeps per-cluster docid lists (cheap python/numpy appends —
  the realtime ingest structure);
- `_publish` packs them into padded [nlist, cap, ...] device arrays
  (cap = max bucket length rounded up); a publish happens lazily on the
  first search after new rows were absorbed — the generation-swap pattern
  (build arrays, then swap references atomically);
- deletes never touch the index: the engine's validity mask is applied
  in-kernel per slot.

Search: ops/ivf.py scan kernels + exact rerank against the raw device
buffer. Rerank depth `rerank` (default 4*k, min 64… capped by candidates)
is the recall knob on top of nprobe.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.engine.raw_vector import RawVectorStore
from vearch_tpu.engine.types import IndexParams, MetricType
from vearch_tpu.index.base import VectorIndex
from vearch_tpu.index.int8_mirror import Int8Mirror
from vearch_tpu.index.registry import register_index
from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops import kmeans as km
from vearch_tpu.ops import pq as pq_ops
from vearch_tpu.ops.distance import sqnorms, to_device_mask


class _IVFBase(VectorIndex):
    needs_training = True

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        self.nlist = int(params.get("ncentroids", params.get("nlist", 256)))
        self.default_nprobe = int(params.get("nprobe", 16))
        self.train_sample = int(params.get("training_sample", 262_144))
        self.train_iters = int(params.get("train_iters", 10))
        # coarse quantizer choice (reference: gamma_index_ivfpq.h:1258
        # quantizer_type_ — FLAT vs HNSW over the centroids). On TPU the
        # [B, nlist] matmul is usually the right answer; the HNSW graph
        # wins when probe selection should stay on HOST — tiny batches
        # or huge nlist, where a device dispatch per coarse step costs
        # more than an O(log nlist) graph walk.
        self.quantizer_type = str(
            params.get("quantizer_type", "flat")
        ).lower()
        self._coarse_graph = None
        self.centroids: jax.Array | None = None  # [nlist, d] f32
        self._members: list[list[int]] = []  # per-cluster docid lists (host)
        self._dirty = True
        # published device state
        self._bucket_ids: jax.Array | None = None
        self._cap = 0

    def _device_state_arrays(self) -> tuple:
        """Device tensors this index keeps resident beyond the raw store
        (footprint model input; subclasses extend)."""
        return (self.centroids, self._bucket_ids)

    def device_footprint_bytes(self) -> int:
        total = super().device_footprint_bytes()
        for a in self._device_state_arrays():
            if a is not None:
                total += int(a.size) * a.dtype.itemsize
        return total

    # -- training ------------------------------------------------------------

    def _sample(self, x: np.ndarray) -> np.ndarray:
        if x.shape[0] <= self.train_sample:
            return x
        idx = np.random.default_rng(0).choice(
            x.shape[0], self.train_sample, replace=False
        )
        return x[idx]

    def _maybe_normalize(self, x: np.ndarray) -> np.ndarray:
        """Cosine rides the IP machinery on normalized vectors."""
        if self.metric is MetricType.COSINE:
            n = np.maximum(np.linalg.norm(x, axis=-1, keepdims=True), 1e-15)
            return (x / n).astype(np.float32)
        return x

    #: "DxQ" mesh tag of the last coarse-quantizer training, None for
    #: the single-device trainer (build jobs and build.train spans
    #: record it)
    last_train_mesh: str | None = None

    def _train_mesh(self):
        """Mesh for coarse-quantizer training, or None for the single-
        device path. Opt-in (``mesh_train: true``): the sharded
        trainer's k-means++ init subsamples differently from the
        single-device trainer, so flipping it on changes the trained
        centroids — an explicit build-time decision, not an ambient one
        that would silently shift recall when the device count changes.
        """
        if not bool(self.params.get("mesh_train", False)):
            return None
        if len(jax.devices()) <= 1:
            return None
        return self._serving_mesh(None)

    def _serving_mesh(self, params: dict | None):
        """The mesh this index places/serves on: the ``mesh_shape`` knob
        (engine apply_config fans it into index params; per-request
        override wins), defaulting to the all-devices data×1 mesh."""
        from vearch_tpu.parallel import mesh as mesh_lib

        shape = (params or {}).get(
            "mesh_shape", self.params.get("mesh_shape")
        )
        return mesh_lib.mesh_from_shape(shape)

    def train(self, sample: np.ndarray) -> None:
        x = self._maybe_normalize(self._sample(np.asarray(sample, np.float32)))
        mesh = self._train_mesh()
        if mesh is not None:
            # multi-chip coarse training: per-shard partial sums, psum
            # over "data" (parallel/sharded.py train_kmeans_sharded) —
            # index builds use all chips instead of serializing Lloyd
            # rounds on one
            from vearch_tpu.parallel.sharded import train_kmeans_sharded

            self.centroids = train_kmeans_sharded(
                mesh, x, k=self.nlist, iters=self.train_iters
            )
            self.last_train_mesh = (
                f"{mesh.shape['data']}x{mesh.shape['query']}"
            )
        else:
            self.centroids = km.train_kmeans(
                jnp.asarray(x), k=self.nlist, iters=self.train_iters
            )
            self.last_train_mesh = None
        self._members = [[] for _ in range(self.nlist)]
        self._build_coarse_graph()
        self._train_extra(x)
        self.trained = True

    def _build_coarse_graph(self) -> None:
        if self.quantizer_type != "hnsw":
            return
        try:
            from vearch_tpu.native.hnsw_graph import HnswGraph

            g = HnswGraph(self.store.dimension, m=16, ef_construction=200,
                          ip=False)
            g.add(np.asarray(self.centroids, dtype=np.float32))
            self._coarse_graph = g
        except RuntimeError as e:
            from vearch_tpu.utils import log

            log.warn("hnsw coarse quantizer unavailable (%s); "
                     "falling back to flat", e)
            self.quantizer_type = "flat"
            self._coarse_graph = None

    def _assign(self, rows: np.ndarray) -> np.ndarray:
        """Cluster assignment for absorb: device matmul (exact) or the
        host HNSW graph walk (quantizer_type=hnsw — no device dispatch,
        which matters when absorb runs on the cluster's write path)."""
        if self._coarse_graph is not None:
            _s, ids = self._coarse_graph.search(rows, 1, ef=96)
            return ids[:, 0].astype(np.int64)
        return np.asarray(
            km.assign_clusters(jnp.asarray(rows), self.centroids)
        )

    def _host_probes(self, q: np.ndarray, nprobe: int) -> np.ndarray | None:
        """[B, nprobe] probe cells from the host graph, or None for the
        in-kernel matmul selection."""
        if self._coarse_graph is None:
            return None
        _s, ids = self._coarse_graph.search(
            q, min(nprobe, self.nlist), ef=max(2 * nprobe, 64)
        )
        # -1 padding (graph came up short) passes through: the scan
        # kernels mask those probe steps entirely — clamping to a real
        # cell here would scan it twice and DUPLICATE its docids
        return np.ascontiguousarray(ids, dtype=np.int32)

    def _train_extra(self, sample: np.ndarray) -> None:
        pass

    # -- realtime absorb (reference: AddRTVecsToIndex) ------------------------

    def absorb(self, upto: int) -> None:
        with self._absorb_lock:
            # recheck under the lock: a concurrent search/build thread may
            # have absorbed the same range already
            if not self.trained or upto <= self.indexed_count:
                self.indexed_count = max(self.indexed_count, upto)
                return
            start = self.indexed_count
            rows = self._maybe_normalize(
                self.store.host_view()[start:upto].astype(np.float32)
            )
            assign = self._assign(rows)
            self._absorb_rows(rows, assign, start)
            # vectorised bucket grouping: argsort by cluster + split beats a
            # python append loop ~50x at 1M rows
            order = np.argsort(assign, kind="stable")
            sorted_assign = assign[order]
            docids = order.astype(np.int64) + start
            boundaries = np.searchsorted(
                sorted_assign, np.arange(self.nlist + 1)
            )
            for c in np.unique(sorted_assign):
                lo, hi = boundaries[c], boundaries[c + 1]
                self._members[int(c)].extend(docids[lo:hi].tolist())
            self.indexed_count = upto
            self._dirty = True

    def _absorb_rows(
        self, rows: np.ndarray, assign: np.ndarray, start_docid: int
    ) -> None:
        pass

    # -- publish -------------------------------------------------------------

    def _bucket_shape(self) -> int:
        longest = max((len(mm) for mm in self._members), default=0)
        return max(128, -(-longest // 128) * 128)

    def _publish_ids(self) -> np.ndarray:
        cap = self._bucket_shape()
        ids = np.full((self.nlist, cap), -1, dtype=np.int32)
        for c, mm in enumerate(self._members):
            if mm:
                ids[c, : len(mm)] = mm
        self._cap = cap
        self._bucket_ids = jnp.asarray(ids)
        return ids

    def _valid_device(self, valid_mask, n: int) -> jax.Array:
        # pad to store capacity so the probe kernels keep a stable input
        # shape across ingest (capacity only changes on rare doublings)
        return to_device_mask(valid_mask, n, max(self.store.capacity, 1))

    def _rerank_depth(self, k: int, params: dict | None) -> int:
        """Exact-rerank candidate depth — the recall knob on top of the
        quantized scan (rerank cost is one [B, r, d] gather+matvec,
        negligible vs the scan itself, so the default is generous)."""
        p = params or {}
        r = int(p.get("rerank", self.params.get("rerank", max(10 * k, 128))))
        return max(r, k)

    def _exact_rerank_enabled(self, params: dict | None) -> bool:
        """Whether the exact raw-store rerank pass runs after the
        quantized scan. SCANN's reordering=false flips this off."""
        return True

    def _nprobe(self, params: dict | None) -> int:
        p = params or {}
        return min(int(p.get("nprobe", self.default_nprobe)), self.nlist)

    def _pad_to_k(
        self, scores: np.ndarray, ids: np.ndarray, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        if scores.shape[1] >= k:
            return scores[:, :k], ids[:, :k]
        pad = k - scores.shape[1]
        return (
            np.pad(scores, ((0, 0), (0, pad)), constant_values=float("-inf")),
            np.pad(ids, ((0, 0), (0, pad)), constant_values=-1),
        )

    def cell_populations(self) -> list[int] | None:
        """Live per-cell member counts (quality drift gauge input)."""
        with self._absorb_lock:
            if not self.trained:
                return None
            return [len(mm) for mm in self._members]

    def dump_state(self) -> dict[str, Any]:
        if not self.trained:
            return {}
        return {
            "centroids": np.asarray(self.centroids),
            "indexed_count": np.int64(self.indexed_count),
        }

    def load_state(self, state: dict[str, Any]) -> None:
        if "centroids" in state:
            self.centroids = jnp.asarray(state["centroids"])
            self._build_coarse_graph()  # rebuilt, not persisted: cheap
            self.trained = True
            self._members = [[] for _ in range(self.nlist)]
            # re-absorb everything: assignments are recomputed, codes
            # re-encoded — raw vectors are the durable source of truth
            # (reference: index is rebuildable from raw store)
            self.indexed_count = 0
            if "codebooks" in state:
                self._load_codebooks(state)
            self.absorb(self.store.count)

    def _load_codebooks(self, state: dict[str, Any]) -> None:
        pass


@register_index("IVFFLAT")
class IVFFlatIndex(_IVFBase):
    """Realtime IVF over raw vectors (reference: gamma_index_ivfflat.cc)."""

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        self._bucket_vecs: jax.Array | None = None
        self._bucket_sqnorm: jax.Array | None = None

    def _device_state_arrays(self) -> tuple:
        return super()._device_state_arrays() + (
            self._bucket_vecs, self._bucket_sqnorm,
        )

    def _publish(self) -> None:
        # under the absorb lock: a concurrent absorb would grow _members
        # between capacity sizing and the fill loop (found by the
        # concurrency stress test)
        with self._absorb_lock:
            ids = self._publish_ids()
            cap = ids.shape[1]
            d = self.store.dimension
            host = self.store.host_view()
            vecs = np.zeros((self.nlist, cap, d), dtype=np.float32)
            for c, mm in enumerate(self._members):
                if mm:
                    vecs[c, : len(mm)] = self._maybe_normalize(
                        host[np.asarray(mm, dtype=np.int64)]
                    )
            self._bucket_vecs = jnp.asarray(vecs, dtype=self.store.store_dtype)
            self._bucket_sqnorm = sqnorms(self._bucket_vecs)
            self._dirty = False

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        assert self.trained, "IVFFLAT search before training"
        if self._dirty or self._bucket_vecs is None:
            self._publish()
        nprobe = self._nprobe(params)
        r = min(self._rerank_depth(k, params), self._cap * nprobe)
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        valid = self._valid_device(valid_mask, self.store.count)
        host_probes = self._host_probes(q, nprobe)
        ivf_ops.note_dispatch("ivfflat_scan")
        scores, ids = ivf_ops.ivfflat_candidates(
            jnp.asarray(q, dtype=self.store.store_dtype),
            self.centroids,
            self._bucket_vecs,
            self._bucket_sqnorm,
            self._bucket_ids,
            valid,
            nprobe,
            min(max(r, k), 2048),
            metric,
            probes=None if host_probes is None
            else jnp.asarray(host_probes),
        )
        scores, ids = jax.device_get((scores, ids))
        # IVFFLAT scores are already exact — no rerank needed; cosine
        # similarity needs the query-norm correction only for reporting,
        # which normalization already handled.
        return self._pad_to_k(scores, ids, k)

    def reconstruction_error(self, sample: int = 256,
                             seed: int = 0) -> float | None:
        # buckets hold the raw vectors (store_dtype): scoring is exact,
        # so the quantization-drift gauge is identically zero
        return 0.0 if self.trained else None


@register_index("IVFPQ")
class IVFPQIndex(_IVFBase):
    """Realtime IVFPQ with residual encoding + exact rerank (reference:
    gamma_index_ivfpq.cc; rerank via raw vectors as in the reference's
    fine-grained reranking).

    Two device scan modes (param `scan_mode`, default "auto"):
    - "full": docid-ordered int8 compressed full scan (one MXU matmul) —
      realtime-friendly (appends, no publish rebuild) and the fastest
      path up to ~10M rows/chip;
    - "probe": bucket-grouped nprobe scan (compute scales with nprobe,
      for capacity-bound deployments);
    "auto" = full while the row count fits `full_scan_limit` (16M).
    """

    def __init__(self, params: IndexParams, store: RawVectorStore):
        super().__init__(params, store)
        self.m = int(params.get("nsubvector", params.get("m", 16)))
        if store.dimension % self.m != 0:
            # fail at create-table time, not in the background build thread
            raise ValueError(
                f"IVFPQ nsubvector={self.m} must divide dimension="
                f"{store.dimension}"
            )
        self.ksub = 1 << int(params.get("nbits_per_idx", params.get("nbits", 8)))
        # optional learned rotation before PQ (reference: OPQ option)
        self.opq = bool(params.get("opq", False))
        self.opq_iters = int(params.get("opq_iters", 5))
        self._opq_R: np.ndarray | None = None  # [d, d] orthonormal
        self.scan_mode = str(params.get("scan_mode", "auto"))
        self.full_scan_limit = int(params.get("full_scan_limit", 16_000_000))
        # one partition spanning the whole device mesh (capacity regime:
        # rows beyond a single chip's HBM — SURVEY §2.3 "intra-node
        # parallelism", the axis the reference lacks). Config
        # `mesh_serving: auto|on|off` ("data_parallel" stays as a
        # boolean back-compat alias); "auto" — the default — engages
        # whenever more than one device is visible.
        self.mesh_serving = self._norm_mesh_serving(
            params.get("mesh_serving", params.get("data_parallel", "auto"))
        )
        # row -> cluster assignment, docid-ordered (the mesh probe gate
        # reads it row-sharded in lockstep with the int8 mirror)
        self._assign_host = np.zeros(0, dtype=np.int32)
        self._assign_cache = None
        self.codebooks: jax.Array | None = None  # [m, ksub, dsub]
        self._codes: np.ndarray | None = None  # [n_indexed, m] host codes
        # probe-mode state (bucket-grouped)
        self._bucket_resid8: jax.Array | None = None
        self._bucket_scale: jax.Array | None = None
        self._bucket_vsq: jax.Array | None = None
        # full-scan-mode state (docid-ordered compressed mirror,
        # append-only). mirror_dtype "int4" halves resident HBM per row
        # (the capacity knob for the full-scan regime).
        self.mirror_storage = str(
            params.get("mirror_dtype", "int8")
        ).lower()
        self._mirror = Int8Mirror(store.dimension,
                                  storage=self.mirror_storage)

    @staticmethod
    def _norm_mesh_serving(value) -> str:
        ms = {True: "on", False: "off"}.get(value, str(value).lower())
        if ms in ("true", "1"):
            ms = "on"
        elif ms in ("false", "0", "none"):
            ms = "off"
        if ms not in ("auto", "on", "off"):
            raise ValueError(f"mesh_serving must be auto|on|off, got {value!r}")
        return ms

    def _mesh_enabled(self, params: dict | None) -> bool:
        """Whether this search serves through the device mesh. Read per
        request so apply_config({"index_params": {"mesh_serving": ...}})
        and per-request overrides both take effect without a rebuild."""
        ms = self._norm_mesh_serving(
            (params or {}).get(
                "mesh_serving",
                self.params.get(
                    "mesh_serving", self.params.get("data_parallel", "auto")
                ),
            )
        )
        if ms == "auto":
            return len(jax.devices()) > 1
        return ms == "on"

    # back-compat surface (pre-mesh_serving callers/tests)
    @property
    def data_parallel(self) -> bool:
        return self._mesh_enabled(None)

    def _device_state_arrays(self) -> tuple:
        return super()._device_state_arrays() + (
            self.codebooks, self._bucket_resid8,
            self._bucket_scale, self._bucket_vsq,
        )

    def device_footprint_bytes(self) -> int:
        # bucket/centroid state + raw rerank store (super) + the
        # docid-ordered compressed mirror the full-scan mode serves from
        return super().device_footprint_bytes() + self._mirror.device_bytes()

    def _train_extra(self, sample: np.ndarray) -> None:
        assign = np.asarray(
            km.assign_clusters(jnp.asarray(sample), self.centroids)
        )
        resid = sample - np.asarray(self.centroids)[assign]
        if self.opq:
            # OPQ (reference: gamma_index_ivfpq.h opq_ option): learn an
            # orthonormal rotation R that decorrelates subvector energy,
            # by alternating PQ training on rotated residuals with the
            # Procrustes update R = UV^T from svd(X^T D(code(XR))).
            # Downstream stays untouched: codes live in rotated space,
            # the int8 mirror stores approximations rotated BACK to the
            # original space, so scan + rerank never see R. On TPU the
            # rotation is one [d, d] matmul folded into absorb.
            d = resid.shape[1]
            R = np.eye(d, dtype=np.float32)
            for _ in range(self.opq_iters):
                z = resid @ R
                self.codebooks = pq_ops.train_pq(
                    jnp.asarray(z), m=self.m, ksub=self.ksub,
                    iters=max(self.train_iters // 2, 2),
                )
                codes = np.asarray(
                    pq_ops.encode_pq(jnp.asarray(z), self.codebooks)
                )
                decoded = pq_ops.decode_pq_np(codes, self.codebooks)
                u, _s, vt = np.linalg.svd(resid.T @ decoded)
                R = (u @ vt).astype(np.float32)
            self._opq_R = R
            resid = resid @ R
        self.codebooks = self._fit_codebooks(resid, sample)
        self._codes = np.zeros((0, self.m), dtype=np.uint8)

    def _fit_codebooks(
        self, resid: np.ndarray, sample: np.ndarray
    ) -> jax.Array:
        """Codebook trainer hook — SCANN overrides this with the
        anisotropic (score-aware) trainer; `sample` is the original
        (pre-residual) rows it needs for the parallel direction."""
        return pq_ops.train_pq(
            jnp.asarray(resid), m=self.m, ksub=self.ksub,
            iters=self.train_iters,
        )

    def _encode_rows(self, resid: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Encoder hook (same override seam as `_fit_codebooks`)."""
        return np.asarray(
            pq_ops.encode_pq(jnp.asarray(resid), self.codebooks)
        )

    def _absorb_rows(
        self, rows: np.ndarray, assign: np.ndarray, start_docid: int
    ) -> None:
        cents = np.asarray(self.centroids)
        resid = rows - cents[assign]
        if self._opq_R is not None:
            resid = resid @ self._opq_R  # encode in rotated space
        codes = self._encode_rows(resid, rows)
        if self._codes is None:
            self._codes = np.zeros((0, self.m), dtype=np.uint8)
        need = start_docid + rows.shape[0]
        if self._codes.shape[0] < need:
            grown = np.zeros((max(need, self._codes.shape[0] * 2), self.m),
                             dtype=np.uint8)
            grown[: self._codes.shape[0]] = self._codes
            self._codes = grown
        self._codes[start_docid : start_docid + rows.shape[0]] = codes
        if self._assign_host.shape[0] < need:
            ga = np.zeros(max(need, self._assign_host.shape[0] * 2),
                          dtype=np.int32)
            ga[: self._assign_host.shape[0]] = self._assign_host
            self._assign_host = ga
        self._assign_host[start_docid:need] = assign.astype(np.int32)
        if self._assign_cache is not None:
            self._assign_cache.lower_rows(start_docid)

        # docid-ordered int8 mirror for the full-scan path: decode the PQ
        # approximation, rotate back to the original space (OPQ), add the
        # centroid, quantize per-row, append
        decoded = pq_ops.decode_pq_np(codes, self.codebooks)
        if self._opq_R is not None:
            decoded = decoded @ self._opq_R.T
        approx = cents[assign] + decoded
        if self.metric is MetricType.COSINE:
            # re-normalize the approximation: rows were normalized
            # before encoding, but PQ error perturbs the norm, and the
            # IP scan would rank by (1 ± err) * cos — on norm-spread
            # data (glove-like regime) that bias alone cost r@100
            # 0.465 -> the candidate set was norm-noise, not angle
            approx = approx / np.maximum(
                np.linalg.norm(approx, axis=1, keepdims=True), 1e-12)
        self._mirror.append(approx, start=start_docid)

    def _publish(self) -> None:
        """Decode PQ codes -> residual approximations -> int8 buckets.

        The decode+quantize runs once per publish (numpy, ~1s/M rows);
        searches then scan pure int8 matmuls (see ops/ivf.py design note).
        """
        with self._absorb_lock:
            self._publish_locked()

    def _publish_locked(self) -> None:
        ids = self._publish_ids()
        cap = ids.shape[1]
        d = self.store.dimension
        cents = np.asarray(self.centroids)
        dsub = d // self.m
        resid8 = np.zeros((self.nlist, cap, d), dtype=np.int8)
        scales = np.ones(self.nlist, dtype=np.float32)
        vsq = np.zeros((self.nlist, cap), dtype=np.float32)
        for c, mm in enumerate(self._members):
            if not mm:
                continue
            rows = np.asarray(mm, dtype=np.int64)
            codes = self._codes[rows]  # [nc, m]
            decoded = pq_ops.decode_pq_np(codes, self.codebooks)
            if self._opq_R is not None:
                decoded = decoded @ self._opq_R.T  # back to original space
            if self.metric is MetricType.COSINE:
                # same re-normalization as the mirror path (review r5):
                # redefine the residual against the NORMALIZED
                # approximation so the probe scan's cent_c + s*r8
                # decomposition reconstructs a unit-norm vector — PQ
                # norm error must not rank cosine candidates
                full = cents[c][None, :] + decoded
                full /= np.maximum(
                    np.linalg.norm(full, axis=1, keepdims=True), 1e-12)
                decoded = full - cents[c][None, :]
            scale = max(float(np.abs(decoded).max()) / 127.0, 1e-12)
            q8 = np.clip(np.rint(decoded / scale), -127, 127).astype(np.int8)
            approx = cents[c][None, :] + scale * q8.astype(np.float32)
            resid8[c, : len(mm)] = q8
            scales[c] = scale
            vsq[c, : len(mm)] = np.sum(approx * approx, axis=1)
        self._bucket_resid8 = jnp.asarray(resid8)
        self._bucket_scale = jnp.asarray(scales)
        self._bucket_vsq = jnp.asarray(vsq)
        self._dirty = False

    def reconstruction_error(self, sample: int = 256,
                             seed: int = 0) -> float | None:
        """Decode the STORED codes (the serving representation) back to
        full vectors and compare against the raw store — host numpy
        only, no device dispatch. Covers SCANN too (same stored-code
        layout; the anisotropic encoder only changes which codes were
        chosen, not how they decode)."""
        with self._absorb_lock:
            n = int(self.indexed_count)
            if not self.trained or n == 0 or self._codes is None:
                return None
            rng = np.random.default_rng(seed)
            ids = np.sort(rng.choice(n, size=min(int(sample), n),
                                     replace=False))
            raw = self._maybe_normalize(
                np.asarray(self.store.host_view()[ids], dtype=np.float32)
            )
            decoded = pq_ops.decode_pq_np(self._codes[ids], self.codebooks)
            if self._opq_R is not None:
                decoded = decoded @ self._opq_R.T
            cents = np.asarray(self.centroids)
            approx = cents[self._assign_host[ids]] + decoded
            if self.metric is MetricType.COSINE:
                approx = approx / np.maximum(
                    np.linalg.norm(approx, axis=1, keepdims=True), 1e-12)
            num = np.linalg.norm(raw - approx, axis=1)
            den = np.maximum(np.linalg.norm(raw, axis=1), 1e-12)
            return float(np.mean(num / den))

    def search(
        self,
        queries: np.ndarray,
        k: int,
        valid_mask: np.ndarray | None,
        params: dict | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        assert self.trained, "IVFPQ search before training"
        q = self._maybe_normalize(np.asarray(queries, np.float32))
        metric = (
            MetricType.INNER_PRODUCT
            if self.metric is MetricType.COSINE
            else self.metric
        )
        mode = (params or {}).get("scan_mode", self.scan_mode)
        mesh_on = self._mesh_enabled(params)
        from vearch_tpu.index._store_paths import is_disk_store

        scan_kernel = (params or {}).get(
            "scan_kernel", self.params.get("scan_kernel", "xla")
        )
        # mesh mode needs the raw buffer sharded across HBM — a disk
        # store can't provide that; it falls through to the
        # single-device scan with host-gathered rerank. The pallas
        # kernel is likewise a single-device program (hardware A/B
        # flag), so it keeps the single-device path too.
        mesh_route = (
            mesh_on and scan_kernel != "pallas"
            and not is_disk_store(self.store)
        )
        if mode == "auto":
            # the full-scan budget is per chip: a mesh-spanning
            # partition scans its rows in parallel, so the cliff to
            # probe mode scales with the DATA axis of the serving mesh
            # — a query_axis>1 mesh still holds n/data_axis rows per
            # chip, so counting all devices would move the cliff to the
            # wrong row count
            limit = self.full_scan_limit
            if mesh_route:
                limit *= max(
                    int(self._serving_mesh(params).shape["data"]), 1
                )
            mode = "full" if self.indexed_count <= limit else "probe"
        if mesh_route and mode == "full":
            return self._search_mesh(q, k, valid_mask, params, metric)
        if (
            mesh_route and mode == "probe"
            and self._exact_rerank_enabled(params)
            and (params or {}).get(
                "fused_rerank", self.params.get("fused_rerank", True)
            )
        ):
            # probe regime under the mesh: keep the row-sharded layout
            # and gate the ONE fused program to the probed coarse cells
            # — past the full-scan cliff a mesh partition no longer
            # falls back to a single chip. (reordering=false and the
            # unfused A/B path keep the single-device bucket layout.)
            return self._search_mesh(
                q, k, valid_mask, params, metric,
                probe_nprobe=max(self._nprobe(params), 1),
            )
        if mode == "full":
            approx8, scale, vsq = self._mirror.flush()
            n_pad = approx8.shape[0]
            valid = to_device_mask(valid_mask, self.indexed_count, n_pad)
            r = min(self._rerank_depth(k, params), max(self.indexed_count, 1))
            topk_mode = (params or {}).get(
                "topk_mode", self.params.get("topk_mode", "auto")
            )
            fused = (params or {}).get(
                "fused_rerank", self.params.get("fused_rerank", True)
            )
            if scan_kernel == "pallas" and self.mirror_storage == "int8":
                # one-pass fused block-max kernel: scores stay in VMEM,
                # only [B, N/512] block maxima reach HBM (vs the XLA
                # path's [B, N] f32 score matrix). Behind a flag for
                # hardware A/B (r4 review next-7; microbench hook:
                # scripts/benchmarks/pallas_ab.py).
                from vearch_tpu.ops.pallas_kernels import (
                    int8_blockmax_scan_pallas,
                )

                ivf_ops.note_dispatch("pallas_blockmax_scan")
                cand_s, cand_i = int8_blockmax_scan_pallas(
                    jnp.asarray(q), approx8, scale, vsq, valid,
                    max(r, k), metric is MetricType.L2,
                )
            elif (
                fused
                and self._exact_rerank_enabled(params)
                and not is_disk_store(self.store)
            ):
                # default hot path: scan + rerank as ONE device program
                # (two dispatches paid launch/tunnel latency twice and
                # round-tripped nothing for it — r4 review next-1);
                # `fused_rerank: false` keeps the two-step path for A/B
                base, base_sqnorm, _ = self.store.device_buffer()
                ivf_ops.note_dispatch("fused_scan_rerank")
                scores, ids = ivf_ops.int8_scan_rerank(
                    jnp.asarray(q), approx8, scale, vsq, valid,
                    base, base_sqnorm, max(r, k), k,
                    scan_metric=metric, rerank_metric=self.metric,
                    topk_mode=topk_mode, storage=self.mirror_storage,
                )
                scores, ids = jax.device_get((scores, ids))
                return self._pad_to_k(scores, ids, k)
            else:
                scan = (
                    ivf_ops.int8_scan_candidates
                    if self.mirror_storage == "int8"
                    else ivf_ops.int4_scan_candidates
                )
                ivf_ops.note_dispatch("scan")
                cand_s, cand_i = scan(
                    jnp.asarray(q), approx8, scale, vsq, valid,
                    max(r, k), metric, topk_mode,
                )
        else:
            if self._dirty or self._bucket_resid8 is None:
                self._publish()
            nprobe = self._nprobe(params)
            r = min(self._rerank_depth(k, params), self._cap * nprobe, 2048)
            valid = self._valid_device(valid_mask, self.store.count)
            # pallas only pays off compiled; off-TPU the interpret-mode
            # kernel would be drastically slower than the XLA scan
            default_kernel = (
                "pallas" if jax.default_backend() == "tpu" else "xla"
            )
            kernel = (params or {}).get(
                "probe_kernel", self.params.get("probe_kernel", default_kernel)
            )
            host_probes = self._host_probes(q, nprobe)
            if host_probes is not None:
                # the pallas kernel selects probes in-kernel via scalar
                # prefetch; host-graph selection rides the XLA path
                kernel = "xla"
            ivf_ops.note_dispatch("probe_scan")
            if kernel == "pallas":
                from vearch_tpu.ops.pallas_kernels import (
                    ivfpq_probe_search_pallas,
                )

                cand_s, cand_i = ivfpq_probe_search_pallas(
                    jnp.asarray(q),
                    self.centroids,
                    self._bucket_resid8,
                    self._bucket_scale,
                    self._bucket_vsq,
                    self._bucket_ids,
                    valid,
                    nprobe,
                    max(r, k),
                    metric is MetricType.L2,
                )
            else:
                cand_s, cand_i = ivf_ops.ivfpq_candidates(
                    jnp.asarray(q),
                    self.centroids,
                    self._bucket_resid8,
                    self._bucket_scale,
                    self._bucket_vsq,
                    self._bucket_ids,
                    valid,
                    nprobe,
                    max(r, k),
                    metric,
                    probes=None if host_probes is None
                    else jnp.asarray(host_probes),
                )
        if not self._exact_rerank_enabled(params):
            # SCANN reordering=false: pure quantized scores, no raw-store
            # gather (candidates come out of the scan best-first)
            scores, ids = jax.device_get((cand_s, cand_i))
            return self._pad_to_k(scores[:, :k], ids[:, :k], k)
        from vearch_tpu.index._store_paths import rerank_against_store

        ivf_ops.note_dispatch("rerank")
        scores, ids = rerank_against_store(
            self.store, q, cand_i, min(k, int(cand_i.shape[1])), self.metric,
        )
        scores, ids = jax.device_get((scores, ids))
        return self._pad_to_k(scores, ids, k)

    def _mesh_nprobe(self, params: dict | None) -> int:
        """Coarse-probe gate depth of the mesh program (0 = ungated full
        scan). Unlike single-device "probe" mode this gates the docid-
        ordered mirror inside the one fused program instead of switching
        to the bucket-grouped layout."""
        p = params or {}
        return min(
            int(p.get("mesh_nprobe", self.params.get("mesh_nprobe", 0))),
            self.nlist,
        )

    def _mesh_valid_sharded(self, mesh, valid_mask, n: int, cap: int):
        """Sharded validity mask, cached per source-mask identity.

        The sharded mask re-uploads only when the engine handed us a
        different mask object (the engine caches its alive mask per
        bitmap version; filter masks are fresh arrays by nature). The
        strong reference to the source mask makes the identity check
        sound — a live object's id cannot be reused."""
        from vearch_tpu.parallel import mesh as mesh_lib

        fresh = not (
            getattr(self, "_mesh_valid_src", None) is valid_mask
            and valid_mask is not None
            and getattr(self, "_mesh_valid_n", -1) == n
            and getattr(self, "_mesh_valid_cap", -1) == cap
        )
        if fresh:
            host_valid = np.zeros(cap, dtype=bool)
            if valid_mask is None:
                host_valid[:n] = True
            else:
                vm = np.asarray(valid_mask)[:n]
                host_valid[: vm.shape[0]] = vm
            self._mesh_valid, _ = mesh_lib.shard_rows(mesh, host_valid)
            self._mesh_valid_src = valid_mask
            self._mesh_valid_n = n
            self._mesh_valid_cap = cap
        return self._mesh_valid

    def _assign_sharded(self, mesh, n: int):
        """Row->cluster assignment sharded in lockstep with the mirror
        (same 512 alignment, so local row offsets line up per shard)."""
        if self._assign_cache is None:
            from vearch_tpu.parallel.mesh import ShardedRowCache

            self._assign_cache = ShardedRowCache(align=512)

        def build(cap):
            host = np.zeros(cap, dtype=np.int32)
            host[:n] = self._assign_host[:n]
            return (host,)

        def append(lo, hi):
            return (np.ascontiguousarray(self._assign_host[lo:hi]),)

        (assign,), _ = self._assign_cache.get(mesh, n, build, append)
        return assign

    def _search_mesh(
        self, q: np.ndarray, k: int, valid_mask, params, metric,
        probe_nprobe: int = 0,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Mesh-spanning serving path: the int8 mirror, the raw rerank
        buffer, and the row->cluster assignment are row-sharded over the
        serving mesh's "data" axis, the query batch shards over its
        "query" axis; an optional coarse-probe gate, the compressed
        scan, the all_gather candidate merge, the exact rerank, and the
        pmax score merge all run inside ONE jitted shard_map program —
        no host round trips (reference analogue: none; this is the TPU
        capacity axis on top of the reference's partition sharding).
        Placement is incremental: absorb tail-appends only the new rows
        per shard.

        ``probe_nprobe>0`` is the probe REGIME routed here by search():
        same fused program, gated to the probed cells — distinct
        dispatch tag so the perf model tells the regimes apart."""
        import time as _time

        from vearch_tpu.parallel import mesh as mesh_lib
        from vearch_tpu.parallel.sharded import (
            sharded_exact_rerank,
            sharded_int8_search,
            sharded_ivf_search,
        )

        t_place0 = _time.monotonic()
        mesh = self._serving_mesh(params)
        a8, scale, vsq = self._mirror.flush_sharded(mesh)
        n = self.indexed_count
        cap = self._mirror._sh_cache.capacity(mesh, n)
        valid_sh = self._mesh_valid_sharded(mesh, valid_mask, n, cap)
        nprobe = probe_nprobe or self._mesh_nprobe(params)
        cents = assign_sh = None
        if nprobe > 0:
            cents = mesh_lib.replicate(mesh, np.asarray(self.centroids))
            assign_sh = self._assign_sharded(mesh, n)
        qd, b = mesh_lib.shard_queries(mesh, np.asarray(q, np.float32))
        r = min(self._rerank_depth(k, params), max(n, 1))
        topk_mode = (params or {}).get(
            "topk_mode", self.params.get("topk_mode", "auto")
        )
        fused = (params or {}).get(
            "fused_rerank", self.params.get("fused_rerank", True)
        )
        rerank = self._exact_rerank_enabled(params)
        if fused and rerank:
            base, base_sqn, _ = self.store.device_buffer_sharded(mesh)
            ivf_ops.note_mesh_phase("place", t_place0, _time.monotonic())
            ivf_ops.note_dispatch(
                "sharded_probe_scan_rerank" if probe_nprobe > 0
                else "sharded_fused_scan_rerank"
            )
            scores, ids = sharded_ivf_search(
                mesh, cents, assign_sh, a8, scale, vsq, valid_sh,
                base, base_sqn, qd, max(r, k),
                min(k, max(r, k)),
                scan_metric=metric, rerank_metric=self.metric,
                topk_mode=topk_mode, storage=self.mirror_storage,
                nprobe=nprobe,
            )
            scores, ids = jax.device_get((scores, ids))
            return self._pad_to_k(scores[:b], ids[:b], k)
        ivf_ops.note_mesh_phase("place", t_place0, _time.monotonic())
        ivf_ops.note_dispatch("sharded_scan")
        cand_s, cand_i = sharded_int8_search(
            mesh, a8, scale, vsq, valid_sh, qd, max(r, k), metric,
            topk_mode, storage=self.mirror_storage,
        )
        if not rerank:
            scores, ids = jax.device_get((cand_s, cand_i))
            return self._pad_to_k(scores[:b, :k], ids[:b, :k], k)
        base, base_sqn, _ = self.store.device_buffer_sharded(mesh)
        ivf_ops.note_dispatch("sharded_rerank")
        scores, ids = sharded_exact_rerank(
            mesh, qd.astype(base.dtype), cand_i, base, base_sqn,
            min(k, int(cand_i.shape[1])), self.metric,
        )
        scores, ids = jax.device_get((scores, ids))
        return self._pad_to_k(scores[:b], ids[:b], k)

    def mesh_info(self) -> dict[str, Any] | None:
        """Mesh data-plane placement summary (surfaced in /ps/stats and
        profile:true explains); None when mesh serving is off."""
        if not self._mesh_enabled(None):
            return None
        mesh = self._serving_mesh(None)
        sh = self._mirror._sh_cache
        info: dict[str, Any] = {
            "devices": int(mesh.size),
            "data_shards": int(mesh.shape["data"]),
            "query_shards": int(mesh.shape["query"]),
            "per_device_bytes": self.device_footprint_per_device_bytes(),
        }
        if sh is not None:
            info["mirror_placement"] = dict(sh.stats)
        rs = getattr(self.store, "_sh_cache", None)
        if rs is not None:
            info["raw_placement"] = dict(rs.stats)
        return info

    def device_footprint_per_device_bytes(self) -> int:
        """Per-device resident HBM model of mesh serving: row-sharded
        state (mirror, raw base, assignment) divides by the shard count;
        replicated state (centroids, bucket tensors when published)
        rides whole on every chip (ops/perf_model.per_device_bytes)."""
        if not self._mesh_enabled(None):
            return self.device_footprint_bytes()
        from vearch_tpu.ops import perf_model

        mesh = self._serving_mesh(None)
        n_shards = int(mesh.shape["data"])
        sharded = self._mirror.device_bytes() + \
            perf_model.raw_store_footprint_bytes(
                self.store.capacity, self.store.dimension,
                self.store.store_dtype.itemsize,
            ) + self._assign_host.shape[0] * 4
        replicated = 0
        for a in self._device_state_arrays():
            if a is not None:
                replicated += int(a.size) * a.dtype.itemsize
        return perf_model.per_device_bytes(sharded, replicated, n_shards)

    def dump_state(self) -> dict[str, Any]:
        state = super().dump_state()
        if state and self.codebooks is not None:
            state["codebooks"] = np.asarray(self.codebooks)
            if self._opq_R is not None:
                state["opq_R"] = self._opq_R
        return state

    def _load_codebooks(self, state: dict[str, Any]) -> None:
        self.codebooks = jnp.asarray(state["codebooks"])
        if "opq_R" in state:
            self._opq_R = np.asarray(state["opq_R"], dtype=np.float32)
        self._codes = np.zeros((0, self.m), dtype=np.uint8)

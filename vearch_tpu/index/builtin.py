"""Imports that register the built-in index types (side-effect imports;
reference: the static REGISTER_INDEX initialisers in index/impl/*.cc)."""

import vearch_tpu.index.flat  # noqa: F401

# IVFFLAT / IVFPQ register here as they land:
try:
    import vearch_tpu.index.ivf  # noqa: F401
except ImportError:  # pragma: no cover - during incremental build-out
    pass

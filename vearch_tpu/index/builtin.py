"""Imports that register the built-in index types (side-effect imports;
reference: the static REGISTER_INDEX initialisers in index/impl/*.cc)."""

import vearch_tpu.index.binary  # noqa: F401
import vearch_tpu.index.disk  # noqa: F401
import vearch_tpu.index.flat  # noqa: F401
import vearch_tpu.index.hnsw  # noqa: F401
import vearch_tpu.index.ivf  # noqa: F401
import vearch_tpu.index.scann  # noqa: F401
import vearch_tpu.index.sharded_flat  # noqa: F401

"""HBM bucket cache: on-demand device paging for disk-resident indexes.

The TPU-native answer to DiskANN's RAM-resident PQ + disk-resident data
(reference: index/impl/diskann/gamma_index_diskann_static.cc — beam
search pages graph nodes from disk). Here the unit of paging is an IVF
bucket slab: HBM holds a fixed-shape pool of `slots` slabs

    pool8   [slots, cap, d] int8    quantized rows
    pool_sc [slots, cap]    f32     per-row dequant scale
    pool_sq [slots, cap]    f32     ||approx||^2
    pool_id [slots, cap]    i32     docid per row (-1 padding)

and an LRU map bucket -> slot. A search resolves its probed buckets:
hits cost nothing; misses land in evicted slots via the batched slab
scatter in tiering/staging.py. Shapes never depend on the request, so
the scan kernel compiles once per (cap, slots) generation. Appends to
a bucket bump its generation, turning stale slabs into misses.

Tiered-storage extensions (see docs/TIERING.md):

- **Hot-bucket pinning** — the top `pin_slots` buckets by decayed
  access frequency are exempt from LRU eviction, so a Zipf-steady
  workload's hot path launches zero H2D bytes once warmed.
- **Prefetch** — `prefetch()` uploads predicted next-probe slabs from
  a background thread; uploads publish by reference swap (the scatter
  returns NEW pool arrays), so an in-flight scan keeps its old pools
  and nothing ever retraces. Demand hits on prefetched slabs count in
  `prefetch_hits`.
- **Multi-pass degradation** — `plan_passes()` splits a probe set that
  exceeds the evictable slots into groups; `acquire(restrict=...)`
  resolves one group per fixed-shape pass, returning slot -1 for the
  deferred probes (masked in ops/ivf.cached_bucket_scan).
- **PCIe ledger** — every upload notes its exact bytes through
  ops/perf_model.note_h2d_bytes; `stats()` exports the per-tier
  hit/miss/evict/pin counters the PS surfaces.

All public entry points are thread-safe (search threads, the realtime
absorber and the prefetch worker share one cache).

This is explicit software-managed memory — the design the pallas guide
prescribes for beyond-HBM working sets, applied at the index level.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from vearch_tpu.ops import ivf as ivf_ops
from vearch_tpu.ops import perf_model
from vearch_tpu.tiering.staging import scatter_slabs
from vearch_tpu.tools import lockcheck

FetchFn = Callable[
    [int], tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]
]

# decayed-frequency bookkeeping: every _DECAY_EVERY resolved buckets the
# effective count of every bucket halves (applied lazily), so pinning
# tracks the CURRENT hot set rather than all-time access totals
_DECAY_EVERY = 1024
_PIN_MIN_FREQ = 2.0  # a bucket must prove reuse before it can pin


class HbmBucketCache:
    _guarded_by = {
        "_lru": "_lock",
        "_slot_gen": "_lock",
        "_free": "_lock",
        "_pinned": "_lock",
        "_from_prefetch": "_lock",
        "_freq": "_lock",
        "_last_resolved": "_lock",
    }

    def __init__(
        self,
        dimension: int,
        slots: int,
        cap: int,
        pin_slots: int | None = None,
    ):
        self.dimension = dimension
        self.slots = slots
        self.cap = cap
        # at least one evictable slot must remain or demand resolves of
        # unpinned buckets could never claim space
        self.pin_slots = max(
            0,
            min(slots // 4 if pin_slots is None else int(pin_slots),
                slots - 1),
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.pin_hits = 0
        self.prefetch_hits = 0
        self.prefetched = 0
        self.h2d_bytes = 0
        self._lock = lockcheck.make_lock("hbm_cache")
        self._lru: OrderedDict[int, int] = OrderedDict()  # bucket -> slot
        self._slot_gen: dict[int, int] = {}  # bucket -> generation cached
        self._free = list(range(slots - 1, -1, -1))
        self._pinned: set[int] = set()
        self._from_prefetch: set[int] = set()
        self._freq: dict[int, tuple[float, int]] = {}
        self._epoch = 0
        self._lookups = 0
        self._last_resolved: set[int] = set()
        self._pool8 = jnp.zeros((slots, cap, dimension), dtype=jnp.int8)
        self._pool_sc = jnp.zeros((slots, cap), dtype=jnp.float32)
        self._pool_sq = jnp.zeros((slots, cap), dtype=jnp.float32)
        self._pool_id = jnp.full((slots, cap), -1, dtype=jnp.int32)

    @property
    def slab_bytes(self) -> int:
        """H2D bytes one slab upload moves (= perf_model.slab_bytes)."""
        return perf_model.slab_bytes(self.cap, self.dimension)

    @property
    def hbm_bytes(self) -> int:
        return self.slots * self.slab_bytes

    # -- demand path --------------------------------------------------

    def resolve(
        self,
        buckets: np.ndarray,
        gens: dict[int, int],
        fetch: FetchFn,
    ) -> np.ndarray:
        """Map unique bucket ids -> device slots, uploading misses.

        `gens[b]` is bucket b's current generation; `fetch(b)` returns
        host (q8 [nb, d], scale [nb], vsq [nb], docids [nb]) with
        nb <= cap. Returns slot ids aligned with `buckets`. Raises when
        the probe set cannot fit one pass — multi-pass callers use
        `plan_passes` + `acquire(restrict=...)` instead.
        """
        uniq = np.unique(buckets)
        if len(uniq) > self.slots:
            raise ValueError(
                f"probe set ({len(uniq)} buckets) exceeds cache "
                f"capacity ({self.slots} slots); raise cache_mb or "
                f"lower nprobe*batch"
            )
        with self._lock:
            return self._resolve_locked(buckets, gens, fetch, None)

    def acquire(
        self,
        buckets: np.ndarray,
        gens: dict[int, int],
        fetch: FetchFn,
        restrict: Iterable[int] | None = None,
    ) -> tuple[np.ndarray, tuple[jax.Array, ...]]:
        """Resolve + pools as one atomic step: the returned slot array
        and pool references belong to the same cache state, so a
        concurrent prefetch upload (which swaps pools by reference)
        cannot slip between them. With `restrict`, only that bucket
        subset is resolved; other probes get slot -1 (the scan kernel
        masks them) for the multi-pass degradation path."""
        with self._lock:
            slots = self._resolve_locked(
                buckets, gens, fetch,
                None if restrict is None else set(restrict),
            )
            pools = (self._pool8, self._pool_sc, self._pool_sq,
                     self._pool_id)
            return slots, pools

    def plan_passes(self, buckets: np.ndarray) -> list[list[int]]:
        """Split a probe set into groups that each fit one fixed-shape
        pass: pinned buckets keep their slots (cost 0), every other
        bucket needs one of the `slots - len(pinned)` evictable slots.
        One group for the common case; never raises."""
        uniq = [int(b) for b in np.unique(buckets)]
        with self._lock:
            limit = max(1, self.slots - len(self._pinned))
            groups: list[list[int]] = []
            cur: list[int] = []
            cost = 0
            for b in uniq:
                c = 0 if b in self._pinned else 1
                if cur and cost + c > limit:
                    groups.append(cur)
                    cur, cost = [], 0
                cur.append(b)
                cost += c
            if cur:
                groups.append(cur)
            return groups

    def _resolve_locked(self, buckets, gens, fetch, restrict):  # lint: holds[_lock]
        uniq = [int(b) for b in np.unique(buckets)]
        active = (
            uniq if restrict is None
            else [b for b in uniq if b in restrict]
        )
        missing: list[int] = []
        for b in active:
            self._touch_freq(b)
            slot = self._lru.get(b)
            if slot is not None and self._slot_gen.get(b) == gens.get(b, 0):
                self._lru.move_to_end(b)
                self.hits += 1
                if b in self._pinned:
                    self.pin_hits += 1
                elif b in self._from_prefetch:
                    self.prefetch_hits += 1
            else:
                missing.append(b)
                self.misses += 1
        if missing:
            t0 = time.monotonic()
            self._upload(missing, gens, fetch, protect=frozenset(),
                         prefetch=False)
            ivf_ops.note_tier_phase("fetch", t0, time.monotonic())
        self._last_resolved = set(active)
        self._recompute_pins()
        active_set = set(active)
        slot_of = self._lru
        return np.asarray(
            [
                slot_of[b] if b in active_set else -1
                for b in (int(x) for x in np.ravel(buckets))
            ],
            dtype=np.int32,
        ).reshape(np.shape(buckets))

    # -- prefetch path ------------------------------------------------

    def prefetch(
        self, buckets: Iterable[int], gens: dict[int, int], fetch: FetchFn
    ) -> int:
        """Upload predicted next-probe slabs ahead of demand. Already-
        resident buckets are marked prefetch-confirmed (their next
        demand hit counts in prefetch_hits); misses upload without
        evicting pinned buckets or the most recently resolved set, and
        without touching the demand hit/miss/frequency accounting.
        Returns the number of slabs uploaded."""
        with self._lock:
            missing: list[int] = []
            for b in {int(b) for b in buckets}:
                slot = self._lru.get(b)
                if slot is not None and self._slot_gen.get(b) == gens.get(b, 0):
                    self._from_prefetch.add(b)
                else:
                    missing.append(b)
            if not missing:
                return 0
            n = self._upload(
                missing, gens, fetch,
                protect=frozenset(self._last_resolved), prefetch=True,
            )
            self.prefetched += n
            return n

    # -- internals (lock held) ----------------------------------------

    def _touch_freq(self, bucket: int) -> None:  # lint: holds[_lock]
        self._lookups += 1
        if self._lookups % _DECAY_EVERY == 0:
            self._epoch += 1
            if len(self._freq) > 8 * self.slots:
                # shed fully-decayed buckets so the frequency map stays
                # O(slots), not O(nlist)
                self._freq = {
                    b: cf for b, cf in self._freq.items()
                    if cf[0] * 0.5 ** (self._epoch - cf[1]) >= 0.5
                }
        count, epoch = self._freq.get(bucket, (0.0, self._epoch))
        self._freq[bucket] = (
            count * (0.5 ** (self._epoch - epoch)) + 1.0,
            self._epoch,
        )

    def _recompute_pins(self) -> None:  # lint: holds[_lock]
        if self.pin_slots <= 0:
            return
        t0 = time.monotonic()
        scored: list[tuple[float, int]] = []
        for b in self._lru:
            cf = self._freq.get(b)
            if cf is None:
                continue
            eff = cf[0] * 0.5 ** (self._epoch - cf[1])
            if eff >= _PIN_MIN_FREQ:
                scored.append((eff, b))
        scored.sort(reverse=True)
        new = {b for _, b in scored[: self.pin_slots]}
        if new != self._pinned:
            self._pinned = new
            ivf_ops.note_tier_phase("pin", t0, time.monotonic())

    def _upload(self, missing, gens, fetch, protect, prefetch) -> int:  # lint: holds[_lock]
        staged: list[tuple[int, int]] = []  # (bucket, slot)
        for b in missing:
            slot = self._claim(b, protect, allow_pin_evict=not prefetch)
            if slot is None:  # prefetch found nothing evictable: skip
                continue
            staged.append((b, slot))
        if not staged:
            return 0
        m = len(staged)
        h8 = np.zeros((m, self.cap, self.dimension), dtype=np.int8)
        hsc = np.zeros((m, self.cap), dtype=np.float32)
        hsq = np.zeros((m, self.cap), dtype=np.float32)
        hid = np.full((m, self.cap), -1, dtype=np.int32)
        slots = np.zeros(m, dtype=np.int32)
        for j, (b, slot) in enumerate(staged):
            q8, sc, sq, ids = fetch(b)
            nb = q8.shape[0]
            assert nb <= self.cap, f"bucket {b} ({nb} rows) > cap {self.cap}"
            h8[j, :nb] = q8
            hsc[j, :nb] = sc
            hsq[j, :nb] = sq
            hid[j, :nb] = ids
            slots[j] = slot
            self._slot_gen[b] = gens.get(b, 0)
            if prefetch:
                self._from_prefetch.add(b)
            else:
                self._from_prefetch.discard(b)
        nbytes = h8.nbytes + hsc.nbytes + hsq.nbytes + hid.nbytes
        self.h2d_bytes += nbytes
        perf_model.note_h2d_bytes(nbytes)
        self._pool8, self._pool_sc, self._pool_sq, self._pool_id = (
            scatter_slabs(
                self._pool8, self._pool_sc, self._pool_sq, self._pool_id,
                jnp.asarray(h8), jnp.asarray(hsc), jnp.asarray(hsq),
                jnp.asarray(hid), jnp.asarray(slots),
            )
        )
        return m

    def _claim(self, bucket, protect, allow_pin_evict) -> int | None:  # lint: holds[_lock]
        old = self._lru.pop(bucket, None)
        if old is not None:  # stale-generation re-upload: keep the slot
            self._lru[bucket] = old
            return old
        if self._free:
            slot = self._free.pop()
            self._lru[bucket] = slot
            return slot
        victim = next(
            (b for b in self._lru
             if b not in protect and b not in self._pinned),
            None,
        )
        if victim is None and allow_pin_evict:
            # demand must succeed: fall back to evicting a pinned (then
            # any) bucket rather than failing the search
            victim = next(
                (b for b in self._lru if b not in protect), None
            )
            if victim is None:
                victim = next(iter(self._lru))
        if victim is None:
            return None
        slot = self._lru.pop(victim)
        self._slot_gen.pop(victim, None)
        self._from_prefetch.discard(victim)
        self._pinned.discard(victim)
        self.evictions += 1
        self._lru[bucket] = slot
        return slot

    # -- introspection ------------------------------------------------

    def pools(self) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        return self._pool8, self._pool_sc, self._pool_sq, self._pool_id

    def stats(self) -> dict[str, int]:
        """Tiering counters the PS metrics and /ps/stats export."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "pin_hits": self.pin_hits,
                "prefetch_hits": self.prefetch_hits,
                "prefetched": self.prefetched,
                "h2d_bytes": self.h2d_bytes,
                "pinned": len(self._pinned),
                "pin_slots": self.pin_slots,
                "resident": len(self._lru),
                "slots": self.slots,
                "cap": self.cap,
                "slab_bytes": self.slab_bytes,
                "resident_bytes": len(self._lru) * self.slab_bytes,
                "hbm_bytes": self.hbm_bytes,
            }

    def seed_counters(self, stats: dict[str, int]) -> None:
        """Carry lifetime counters across a cache rebuild (capacity
        regrow) so operator-facing hit rates don't reset mid-flight."""
        with self._lock:
            self.hits += int(stats.get("hits", 0))
            self.misses += int(stats.get("misses", 0))
            self.evictions += int(stats.get("evictions", 0))
            self.pin_hits += int(stats.get("pin_hits", 0))
            self.prefetch_hits += int(stats.get("prefetch_hits", 0))
            self.prefetched += int(stats.get("prefetched", 0))
            self.h2d_bytes += int(stats.get("h2d_bytes", 0))

    def invalidate(self) -> None:
        with self._lock:
            self._lru.clear()
            self._slot_gen.clear()
            self._free = list(range(self.slots - 1, -1, -1))
            self._pinned.clear()
            self._from_prefetch.clear()
            self._freq.clear()
            self._last_resolved = set()
            self.hits = 0
            self.misses = 0
            self.evictions = 0
            self.pin_hits = 0
            self.prefetch_hits = 0
            self.prefetched = 0

"""HBM bucket cache: on-demand device paging for disk-resident indexes.

The TPU-native answer to DiskANN's RAM-resident PQ + disk-resident data
(reference: index/impl/diskann/gamma_index_diskann_static.cc — beam
search pages graph nodes from disk). Here the unit of paging is an IVF
bucket slab: HBM holds a fixed-shape pool of `slots` slabs

    pool8   [slots, cap, d] int8    quantized rows
    pool_sc [slots, cap]    f32     per-row dequant scale
    pool_sq [slots, cap]    f32     ||approx||^2
    pool_id [slots, cap]    i32     docid per row (-1 padding)

and an LRU map bucket -> slot. A search resolves its probed buckets:
hits cost nothing; misses gather the bucket's rows from the host mmap
and land in evicted slots via one batched `dynamic_update_slice` pass.
Shapes never depend on the request, so the scan kernel compiles once
per (cap, slots) generation. Appends to a bucket bump its generation,
turning stale slabs into misses.

This is explicit software-managed memory — the design the pallas guide
prescribes for beyond-HBM working sets, applied at the index level.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


class HbmBucketCache:
    def __init__(self, dimension: int, slots: int, cap: int):
        self.dimension = dimension
        self.slots = slots
        self.cap = cap
        self.hits = 0
        self.misses = 0
        self._lru: OrderedDict[int, int] = OrderedDict()  # bucket -> slot
        self._slot_gen: dict[int, int] = {}  # bucket -> generation cached
        self._free = list(range(slots - 1, -1, -1))
        self._pool8 = jnp.zeros((slots, cap, dimension), dtype=jnp.int8)
        self._pool_sc = jnp.zeros((slots, cap), dtype=jnp.float32)
        self._pool_sq = jnp.zeros((slots, cap), dtype=jnp.float32)
        self._pool_id = jnp.full((slots, cap), -1, dtype=jnp.int32)

    @property
    def hbm_bytes(self) -> int:
        return self.slots * self.cap * (self.dimension + 12)

    def resolve(
        self,
        buckets: np.ndarray,
        gens: dict[int, int],
        fetch: Callable[[int], tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    ) -> np.ndarray:
        """Map unique bucket ids -> device slots, uploading misses.

        `gens[b]` is bucket b's current generation; `fetch(b)` returns
        host (q8 [nb, d], scale [nb], vsq [nb], docids [nb]) with
        nb <= cap. Returns slot ids aligned with `buckets`.
        """
        uniq = [int(b) for b in np.unique(buckets)]
        if len(uniq) > self.slots:
            raise ValueError(
                f"probe set ({len(uniq)} buckets) exceeds cache "
                f"capacity ({self.slots} slots); raise cache_mb or "
                f"lower nprobe*batch"
            )
        missing: list[int] = []
        for b in uniq:
            slot = self._lru.get(b)
            if slot is not None and self._slot_gen.get(b) == gens.get(b, 0):
                self._lru.move_to_end(b)
                self.hits += 1
            else:
                missing.append(b)
                self.misses += 1
        if missing:
            self._upload(missing, gens, fetch)
        slot_of = {b: s for b, s in self._lru.items()}
        return np.asarray(
            [slot_of[int(b)] for b in np.ravel(buckets)], dtype=np.int32
        ).reshape(np.shape(buckets))

    def _upload(self, missing, gens, fetch) -> None:
        m = len(missing)
        h8 = np.zeros((m, self.cap, self.dimension), dtype=np.int8)
        hsc = np.zeros((m, self.cap), dtype=np.float32)
        hsq = np.zeros((m, self.cap), dtype=np.float32)
        hid = np.full((m, self.cap), -1, dtype=np.int32)
        slots = np.zeros(m, dtype=np.int32)
        for j, b in enumerate(missing):
            q8, sc, sq, ids = fetch(b)
            nb = q8.shape[0]
            assert nb <= self.cap, f"bucket {b} ({nb} rows) > cap {self.cap}"
            h8[j, :nb] = q8
            hsc[j, :nb] = sc
            hsq[j, :nb] = sq
            hid[j, :nb] = ids
            slots[j] = self._claim(b)
            self._slot_gen[b] = gens.get(b, 0)
        self._pool8, self._pool_sc, self._pool_sq, self._pool_id = (
            _scatter_slabs(
                self._pool8, self._pool_sc, self._pool_sq, self._pool_id,
                jnp.asarray(h8), jnp.asarray(hsc), jnp.asarray(hsq),
                jnp.asarray(hid), jnp.asarray(slots),
            )
        )

    def _claim(self, bucket: int) -> int:
        old = self._lru.pop(bucket, None)
        if old is not None:
            self._lru[bucket] = old
            return old
        if self._free:
            slot = self._free.pop()
        else:
            evicted, slot = self._lru.popitem(last=False)
            self._slot_gen.pop(evicted, None)
        self._lru[bucket] = slot
        return slot

    def pools(self) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        return self._pool8, self._pool_sc, self._pool_sq, self._pool_id

    def invalidate(self) -> None:
        self._lru.clear()
        self._slot_gen.clear()
        self._free = list(range(self.slots - 1, -1, -1))
        self.hits = 0
        self.misses = 0


@jax.jit
def _scatter_slabs(p8, psc, psq, pid, h8, hsc, hsq, hid, slots):
    """Scatter m uploaded slabs into their pool slots in one dispatch."""
    p8 = p8.at[slots].set(h8)
    psc = psc.at[slots].set(hsc)
    psq = psq.at[slots].set(hsq)
    pid = pid.at[slots].set(hid)
    return p8, psc, psq, pid

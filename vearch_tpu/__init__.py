"""vearch-tpu: a TPU-native distributed vector database.

A ground-up re-design of the capabilities of vearch/vearch (reference:
master/router/partition-server cluster, raft-replicated partitions, hybrid
vector + scalar-filter search, realtime ingest, pluggable ANN indexes) where
the dense vector math — distance, IVF coarse assignment, PQ ADC, top-k —
runs as jit'd, sharded JAX/XLA programs on TPU.

Layering (mirrors reference SURVEY.md §1, re-architected TPU-first):

    cluster/   master / router / partition-server, metastore, replication
    engine/    per-partition engine: table, raw vectors, deletion bitmap
    index/     pluggable index registry (FLAT, IVFFLAT, IVFPQ, ...)
    scalar/    scalar indexes + filter planning (inverted, bitmap, composite)
    ops/       jit'd TPU kernels: distance, top-k, k-means, PQ
    parallel/  device mesh, sharded search, multi-chip top-k merge
"""

__version__ = "0.1.0"

from vearch_tpu.engine.types import (  # noqa: F401
    DataType,
    FieldSchema,
    IndexParams,
    IndexStatus,
    MetricType,
    TableSchema,
)

"""Native host-side hot loops with transparent numpy fallback.

Compiles csrc/vearch_native.cpp on first import (g++, ~2s, cached as a
.so next to this file) — the TPU-native analogue of the reference's C++
host engine pieces (SURVEY.md §2.2). Every entry point has a pure
numpy/python fallback so the framework runs even without a toolchain.

API (numpy in/out):
    murmur3_batch(keys: list[str]) -> np.uint32[n]
    merge_topk(scores f32[B, M], ids i64[B, M], k, descending=True)
        -> (f32[B, k], i64[B, k])
    read_fvecs(path, max_n=-1) -> np.float32[n, d]
    available() -> bool
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import threading

import numpy as np

_lock = threading.Lock()
_mod = None
_tried = False

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
    "vearch_native.cpp",
)
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "vearch_native.so")
_HASH = _SO + ".srchash"  # sha256 of the source the .so was built from


def _src_hash() -> str:
    import hashlib

    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build(src_hash: str) -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        with open(_HASH, "w") as f:
            f.write(src_hash)
        return True
    except Exception:
        return False


def _stale() -> tuple[bool, str]:
    """The .so is never committed (gitignored); it is rebuilt whenever the
    recorded source hash mismatches, so an unreviewable stale binary can't
    shadow reviewed csrc changes (mtimes are useless after a fresh clone —
    every file gets the checkout time)."""
    if not os.path.exists(_SRC):
        return False, ""
    h = _src_hash()
    if not os.path.exists(_SO) or not os.path.exists(_HASH):
        return True, h
    with open(_HASH) as f:
        return f.read().strip() != h, h


def _load():
    global _mod, _tried
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        stale, h = _stale()
        if not os.path.exists(_SO) or stale:
            if not os.path.exists(_SRC) or not _build(h):
                return None
        try:
            spec = importlib.util.spec_from_file_location("vearch_native", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:
            _mod = None
        return _mod


def available() -> bool:
    return _load() is not None


def murmur3_batch(keys: list) -> np.ndarray:
    mod = _load()
    if mod is not None:
        raw = mod.murmur3_batch([str(k) for k in keys], 0)
        return np.frombuffer(raw, dtype="<u4")
    from vearch_tpu.cluster.hashing import key_slot

    return np.asarray([key_slot(str(k)) for k in keys], dtype=np.uint32)


def merge_topk(
    scores: np.ndarray, ids: np.ndarray, k: int, descending: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    scores = np.ascontiguousarray(scores, dtype=np.float32)
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    b, m = scores.shape
    k = min(k, m)
    mod = _load()
    if mod is not None:
        out_s, out_i = mod.merge_topk(
            scores.tobytes(), ids.tobytes(), b, m, k, descending
        )
        return (
            np.frombuffer(out_s, dtype=np.float32).reshape(b, k).copy(),
            np.frombuffer(out_i, dtype=np.int64).reshape(b, k).copy(),
        )
    order = np.argsort(-scores if descending else scores, axis=1)[:, :k]
    return (
        np.take_along_axis(scores, order, axis=1),
        np.take_along_axis(ids, order, axis=1),
    )


def read_fvecs(path: str, max_n: int = -1) -> np.ndarray:
    mod = _load()
    if mod is not None:
        raw, n, d = mod.read_fvecs(path, max_n)
        return np.frombuffer(raw, dtype=np.float32).reshape(n, d).copy()
    data = np.fromfile(path, dtype=np.int32)
    d = int(data[0])
    rows = data.reshape(-1, d + 1)
    if max_n >= 0:
        rows = rows[:max_n]
    return rows[:, 1:].view(np.float32).copy()


def read_ivecs(path: str, max_n: int = -1) -> np.ndarray:
    """Ground-truth files (.ivecs) share the fvecs layout with i32 payload."""
    return read_fvecs(path, max_n).view(np.int32)

"""Python wrapper for the native HNSW graph (csrc/vearch_hnsw.cpp).

Same compile-on-demand + source-hash staleness discipline as the main
native module. No numpy fallback here — when the toolchain is missing,
`HnswGraph.available()` is False and index/hnsw.py stays on its device
scan path (which is also the default; the graph serves the beyond-HBM /
single-query regime).

Thread model: one writer (the engine's absorb lock), readers serialized
by the GIL at the call boundary; the C++ side releases the GIL inside
add/search, so `_rw` (a plain mutex) makes add and search mutually
exclusive — the graph's link arrays are not safe to read mid-insert.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sysconfig
import threading

import numpy as np

_lock = threading.Lock()
_mod = None
_tried = False

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
    "vearch_hnsw.cpp",
)
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "vearch_hnsw.so")
_HASH = _SO + ".srchash"


def _load():
    global _mod, _tried
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if not os.path.exists(_SRC):
            return None
        with open(_SRC, "rb") as f:
            h = hashlib.sha256(f.read()).hexdigest()
        stale = True
        if os.path.exists(_SO) and os.path.exists(_HASH):
            with open(_HASH) as f:
                stale = f.read().strip() != h
        if stale:
            include = sysconfig.get_paths()["include"]
            cmd = [
                "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                f"-I{include}", _SRC, "-o", _SO,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=180)
                with open(_HASH, "w") as f:
                    f.write(h)
            except Exception:
                return None
        try:
            spec = importlib.util.spec_from_file_location("vearch_hnsw", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:
            _mod = None
        return _mod


def available() -> bool:
    return _load() is not None


class HnswGraph:
    """Owning handle over one native HNSW graph."""

    def __init__(self, dim: int, m: int = 16, ef_construction: int = 200,
                 ip: bool = False, seed: int = 0x5EED):
        mod = _load()
        if mod is None:
            raise RuntimeError(
                "native HNSW unavailable (no toolchain); use the device "
                "scan path instead"
            )
        self._mod = mod
        self.dim = dim
        self.m = m
        self.ef_construction = ef_construction
        self.ip = ip
        self._h = mod.hnsw_new(dim, m, ef_construction, 1 if ip else 0, seed)
        self._rw = threading.Lock()

    @property
    def count(self) -> int:
        return int(self._mod.hnsw_count(self._h))

    def add(self, rows: np.ndarray) -> int:
        # ndarrays satisfy the y* buffer protocol directly — no tobytes
        # copy (the graph's target regime is beyond-HBM batches)
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        if rows.ndim != 2 or rows.shape[1] != self.dim:
            raise ValueError(
                f"rows must be [b, {self.dim}], got {rows.shape}"
            )
        with self._rw:
            return int(self._mod.hnsw_add(self._h, rows, rows.shape[0]))

    def search(
        self,
        queries: np.ndarray,
        k: int,
        ef: int,
        valid_mask: np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (scores [B, k] similarity-oriented, ids [B, k] i64;
        -inf/-1 padding). `valid_mask` is a bool array over docids."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries must be [B, {self.dim}], got {q.shape}"
            )
        b = q.shape[0]
        v = None
        if valid_mask is not None:
            v = np.ascontiguousarray(valid_mask, dtype=np.uint8)
        with self._rw:
            if v is not None and v.shape[0] < (n := self.count):
                # the graph may have grown since the caller sized the
                # mask (concurrent absorb); newer nodes are invalid for
                # this request — pad under the lock so len >= n holds
                v = np.pad(v, (0, n - v.shape[0]))
            out_s, out_i = self._mod.hnsw_search(self._h, q, b, k, ef, v)
        return (
            np.frombuffer(out_s, dtype=np.float32).reshape(b, k).copy(),
            np.frombuffer(out_i, dtype=np.int64).reshape(b, k).copy(),
        )

    def save(self, path: str) -> None:
        with self._rw:
            self._mod.hnsw_save(self._h, path)

    @classmethod
    def load(cls, path: str, dim: int, m: int = 16,
             ef_construction: int = 200, ip: bool = False) -> "HnswGraph":
        mod = _load()
        if mod is None:
            raise RuntimeError("native HNSW unavailable")
        g = cls.__new__(cls)
        g._mod = mod
        g.dim = dim
        g.m = m
        g.ef_construction = ef_construction
        g.ip = ip
        g._h = mod.hnsw_load(dim, m, ef_construction, 1 if ip else 0, path)
        g._rw = threading.Lock()
        return g

    def __del__(self):
        try:
            self._mod.hnsw_free(self._h)
        except Exception:
            pass

"""Async next-probe prefetch for the tiered storage engine.

The coarse quantizer tells us which buckets a query touches *before*
the scan dispatch runs, and successive queries in a steady workload
repeat probe sequences. `SequencePredictor` learns a successor map
over probe-set keys; `PrefetchWorker` pages the predicted next probe
set host→device on a background thread while the current scan runs on
the previous pool arrays. Because `HbmBucketCache` publishes uploads
by reference swap (tiering/staging.py), the prefetch never mutates an
array an in-flight scan holds and never changes a shape — it only
moves the H2D cost off the query's critical path.

The worker is deliberately lossy: a bounded queue that drops the
*stale* job when a new one arrives (prefetching the probe set from two
queries ago is pure waste). Prefetch failures are logged and counted,
never propagated — the demand path pays the miss instead.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Hashable

from vearch_tpu.utils import log

_log = log.get("tiering.prefetch")


class SequencePredictor:
    """First-order successor model over probe-set keys.

    `observe(key)` records that `key` followed the previously observed
    key and returns the learned successor of `key` (the predicted next
    probe set), or None when this key has never been followed yet. The
    map is LRU-capped so an adversarial key stream cannot grow it
    without bound.
    """

    def __init__(self, capacity: int = 512):
        self.capacity = max(int(capacity), 1)
        self._succ: dict[Hashable, Hashable] = {}
        self._order: list[Hashable] = []
        self._prev: Hashable | None = None

    def observe(self, key: Hashable) -> Hashable | None:
        if self._prev is not None and self._prev != key:
            if self._prev not in self._succ:
                self._order.append(self._prev)
                if len(self._order) > self.capacity:
                    evict = self._order.pop(0)
                    self._succ.pop(evict, None)
            self._succ[self._prev] = key
        self._prev = key
        return self._succ.get(key)

    def __len__(self) -> int:
        return len(self._succ)


class PrefetchWorker:
    """Single background thread running `fn(job)` for submitted jobs.

    `submit(job)` enqueues and returns immediately; when the queue is
    full the *oldest* queued job is dropped (counted) in favour of the
    fresh one. `drain()` blocks until all accepted jobs have finished —
    tests use it to make prefetch effects deterministic. The thread is
    started lazily on first submit and torn down by `close()`.
    """

    def __init__(self, fn: Callable[[Any], None], depth: int = 2):
        self._fn = fn
        self._q: queue.Queue[Any] = queue.Queue(maxsize=max(int(depth), 1))
        self._idle = threading.Condition()
        self._pending = 0
        self._thread: threading.Thread | None = None
        self._closed = False
        self.submitted = 0
        self.completed = 0
        self.dropped = 0
        self.errors = 0

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="vearch-tier-prefetch"
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                self._fn(job)
                self.completed += 1
            except Exception:
                self.errors += 1
                _log.warning("prefetch job failed", exc_info=True)
            finally:
                with self._idle:
                    self._pending -= 1
                    self._idle.notify_all()

    def submit(self, job: Any) -> None:
        """Enqueue a prefetch job, dropping the stalest queued one if
        the queue is full. No-op after close()."""
        if job is None or self._closed:
            return
        self._ensure_thread()
        with self._idle:
            self._pending += 1
        self.submitted += 1
        while True:
            try:
                self._q.put_nowait(job)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self.dropped += 1
                    with self._idle:
                        self._pending -= 1
                        self._idle.notify_all()
                except queue.Empty:
                    continue

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until every accepted job has completed (or been
        dropped). Returns False on timeout."""
        with self._idle:
            return self._idle.wait_for(
                lambda: self._pending == 0, timeout=timeout
            )

    def close(self) -> None:
        self._closed = True
        t = self._thread
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout=5.0)
        self._thread = None

    def stats(self) -> dict[str, int]:
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "dropped": self.dropped,
            "errors": self.errors,
        }

"""Host-RAM tier: frequency-admitted caches between NVMe and HBM.

Two consumers, one policy engine:

- :class:`HostRamSlabTier` — prepared bucket slabs (int8 rows + scale +
  vsq + docids) for the DISKANN scan tier. An HBM bucket-cache miss
  that hits here costs one memcpy into the staging upload instead of a
  page-fault walk over the mmap gather.
- :class:`HostRowCache` — raw f32 rows for the rerank tier
  (engine/disk_vector.py `get_rows`): hot candidate rows stop
  re-faulting mmap pages on every rerank gather.

Admission is frequency-based, not admit-on-first-touch: a one-shot
scan over a cold working set must not evict the resident hot set, so a
key is only admitted once its decayed access count reaches
``admit_after`` (default 2 — i.e. proven reuse). Decay is epoch-based:
every ``decay_every`` lookups the effective count of every key halves
lazily, so yesterday's hot bucket does not stay pinned in the
admission race forever. Eviction within the byte budget is plain LRU.

Thread-safe: the prefetch worker, search threads and rerank gathers
all go through one lock per cache (minted via tools/lockcheck.make_lock
so VEARCH_LOCKCHECK=1 runs see it in the acquisition graph).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable

import numpy as np

from vearch_tpu.tools import lockcheck


class _FreqLruBytes:
    """Byte-budgeted LRU with decayed-frequency admission.

    Values are opaque; the caller supplies each entry's byte size. A
    lookup miss records frequency; `offer` admits only keys whose
    effective frequency has reached ``admit_after``.
    """

    def __init__(
        self,
        budget_bytes: int,
        admit_after: int = 2,
        decay_every: int = 4096,
        name: str = "tier_ram",
    ):
        self.budget_bytes = int(budget_bytes)
        self.admit_after = max(int(admit_after), 1)
        self.decay_every = max(int(decay_every), 1)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.admitted = 0
        self.rejected = 0
        self.resident_bytes = 0
        self._lock = lockcheck.make_lock(name)
        self._entries: OrderedDict[Any, tuple[Any, int]] = OrderedDict()
        # key -> (raw count, epoch recorded); effective count halves
        # per elapsed epoch, applied lazily on touch
        self._freq: dict[Any, tuple[float, int]] = {}
        self._epoch = 0
        self._lookups = 0

    # internal helpers assume self._lock is held by the public entry
    # points below

    def _touch_freq(self, key: Any) -> float:  # lint: holds[_lock]
        self._lookups += 1
        if self._lookups % self.decay_every == 0:
            self._epoch += 1
            if len(self._freq) > 4 * max(len(self._entries), 64):
                # shed keys decayed below admission relevance so the
                # frequency map cannot grow with the whole keyspace
                self._freq = {
                    k: cf for k, cf in self._freq.items()
                    if cf[0] * 0.5 ** (self._epoch - cf[1]) >= 0.5
                }
        count, epoch = self._freq.get(key, (0.0, self._epoch))
        count = count * (0.5 ** (self._epoch - epoch)) + 1.0
        self._freq[key] = (count, self._epoch)
        return count

    def _evict_to(self, want_free: int) -> None:  # lint: holds[_lock]
        while (
            self._entries
            and self.resident_bytes + want_free > self.budget_bytes
        ):
            _key, (_val, nbytes) = self._entries.popitem(last=False)
            self.resident_bytes -= nbytes
            self.evictions += 1

    def get(self, key: Any) -> Any | None:
        """Cached value or None; records frequency either way."""
        with self._lock:
            self._touch_freq(key)
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit[0]
            self.misses += 1
            return None

    def offer(self, key: Any, value: Any, nbytes: int) -> bool:
        """Admit `value` if the key's decayed frequency proves reuse
        and it fits the budget. Returns whether it was admitted."""
        with self._lock:
            count, epoch = self._freq.get(key, (0.0, self._epoch))
            eff = count * (0.5 ** (self._epoch - epoch))
            if eff < self.admit_after or nbytes > self.budget_bytes:
                self.rejected += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self.resident_bytes -= old[1]
            self._evict_to(nbytes)
            self._entries[key] = (value, nbytes)
            self.resident_bytes += nbytes
            self.admitted += 1
            return True

    def invalidate(self, key: Any) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.resident_bytes -= old[1]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._freq.clear()
            self.resident_bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "entries": len(self._entries),
                "resident_bytes": self.resident_bytes,
                "budget_bytes": self.budget_bytes,
            }


class HostRamSlabTier:
    """Bucket-slab cache keyed (bucket, generation).

    `get(bucket, gen, loader)` returns the slab tuple (q8 [nb, d] int8,
    scale [nb] f32, vsq [nb] f32, docids [nb] i32), from RAM when the
    cached generation matches, else via `loader()` (the NVMe mmap
    gather) with frequency-based admission. A generation bump (realtime
    absorb appended rows to the bucket) turns the stale copy into a
    miss — same invalidation discipline as the HBM pool.
    """

    def __init__(self, budget_bytes: int, admit_after: int = 2):
        self._cache = _FreqLruBytes(
            budget_bytes, admit_after=admit_after, name="tier_ram_slab"
        )

    def get(  # lint: allow[serving-blocking] slab-tier miss path is the design point: RAM hit is free, a miss pays the NVMe gather once behind WILLNEED readahead and is then admission-cached
        self,
        bucket: int,
        gen: int,
        loader: Callable[[], tuple[np.ndarray, ...]],
    ) -> tuple[np.ndarray, ...]:
        hit = self._cache.get(bucket)
        if hit is not None and hit[0] == gen:
            return hit[1]
        if hit is not None:  # stale generation: a miss, not a hit
            self._cache.invalidate(bucket)
            with self._cache._lock:
                self._cache.hits -= 1
                self._cache.misses += 1
        slab = loader()
        nbytes = int(sum(a.nbytes for a in slab))
        self._cache.offer(bucket, (gen, slab), nbytes)
        return slab

    def clear(self) -> None:
        self._cache.clear()

    def stats(self) -> dict[str, int]:
        return self._cache.stats()


class HostRowCache:
    """Raw-row cache for disk-store rerank gathers.

    `get_rows(docids, loader)` returns [len(docids), d] float32; hot
    rows come from RAM, the rest from `loader(missing_ids)` (the mmap
    gather) and are admitted per decayed frequency. Rows are immutable
    once written (append-only stores, docid == row id), so entries
    never go stale; `clear()` exists for store rollback paths.
    """

    def __init__(self, dimension: int, budget_bytes: int,
                 admit_after: int = 2):
        self.dimension = int(dimension)
        self._row_bytes = self.dimension * 4
        self._cache = _FreqLruBytes(
            budget_bytes, admit_after=admit_after, name="tier_ram_row"
        )

    def get_rows(  # lint: allow[serving-blocking] miss-path faults are the design point: bounded by the RAM slab cache + WILLNEED readahead
        self,
        docids: np.ndarray,
        loader: Callable[[np.ndarray], np.ndarray],
    ) -> np.ndarray:
        ids = np.asarray(docids, dtype=np.int64)
        out = np.empty((ids.shape[0], self.dimension), dtype=np.float32)
        missing_pos: list[int] = []
        for j, docid in enumerate(ids.tolist()):
            row = self._cache.get(docid)
            if row is not None:
                out[j] = row
            else:
                missing_pos.append(j)
        if missing_pos:
            miss_ids = ids[missing_pos]
            rows = np.asarray(loader(miss_ids), dtype=np.float32)
            for j, docid, row in zip(
                missing_pos, miss_ids.tolist(), rows
            ):
                out[j] = row
                self._cache.offer(docid, np.array(row), self._row_bytes)
        return out

    def clear(self) -> None:
        self._cache.clear()

    def stats(self) -> dict[str, int]:
        return self._cache.stats()

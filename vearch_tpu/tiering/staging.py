"""Device staging for the tiered storage engine.

One jitted program: scatter a batch of uploaded bucket slabs into their
HBM pool slots. `Array.at[slots].set(...)` returns a NEW pool array, so
every upload builds a *staging* pool that the cache swaps in by
reference assignment — an in-flight scan holds the previous arrays and
finishes against them unchanged. That reference swap IS the double
buffer: shapes are fixed at (slots, cap, d), so neither the scatter nor
the downstream scan ever retraces, and the H2D cost of an upload is
exactly ops/perf_model.slab_bytes(cap, d) per slab (gated in
tests/test_perf_gates.py via note_h2d_bytes).
"""

from __future__ import annotations

import jax

from vearch_tpu.ops.perf_model import register_jit


@jax.jit
def _scatter_slabs(p8, psc, psq, pid, h8, hsc, hsq, hid, slots):
    """Scatter m uploaded slabs into their pool slots in one dispatch.

    Inputs: pools [slots, cap, ...], host slabs [m, cap, ...], slot ids
    [m] i32. Returns the four staged pools (new arrays — the caller
    publishes them by reference assignment).
    """
    p8 = p8.at[slots].set(h8)
    psc = psc.at[slots].set(hsc)
    psq = psq.at[slots].set(hsq)
    pid = pid.at[slots].set(hid)
    return p8, psc, psq, pid


scatter_slabs = register_jit("tiering.scatter_slabs", _scatter_slabs)

"""Tiered storage engine: HBM <-> host RAM <-> NVMe.

The reference serves beyond-RAM partitions through gamma's disk tiers
(RocksDB-backed RawVector; the DISKANN_STATIC tier keeps compressed
codes in RAM and full vectors on disk). The TPU-native analogue pages
IVF bucket *slabs* instead of graph nodes, and this package is the
machinery between the NVMe mmaps and the HBM bucket cache:

    NVMe   approx8.i8 / meta2.f32 / raw.f32 mmaps (index/disk.py,
           engine/disk_vector.py) — durable, page-cache backed
    RAM    HostRamSlabTier / HostRowCache (ram_tier.py) — frequency-
           admitted slab and row copies, so an HBM miss costs a memcpy,
           not a page fault storm
    HBM    HbmBucketCache (index/hbm_cache.py) — fixed-shape slab
           pools, hot-bucket pinning, LRU for the rest

`staging.py` owns the one jitted program of the subsystem: the batched
slab scatter that lands uploaded buckets in their pool slots. Because
`pool.at[slots].set(...)` returns a NEW pool, every upload is a staging
pool swapped in by reference — an in-flight scan keeps the old arrays,
so the async prefetch worker (prefetch.py) can page next-probe slabs
while the current scan runs without ever changing a shape.

The perf contract lives in ops/perf_model.py (`slab_bytes`,
`tier_h2d_bytes`, `note_h2d_bytes`) and is gated in
tests/test_perf_gates.py: a warmed hot-working-set search launches
ZERO H2D bytes; a cold miss pays exactly the modeled slab bytes.
See docs/TIERING.md for the tier map, knobs and runbook.
"""

from vearch_tpu.tiering.prefetch import PrefetchWorker, SequencePredictor
from vearch_tpu.tiering.ram_tier import HostRamSlabTier, HostRowCache
from vearch_tpu.tiering.readahead import advise_rows
from vearch_tpu.tiering.staging import scatter_slabs

__all__ = [
    "HostRamSlabTier",
    "HostRowCache",
    "PrefetchWorker",
    "SequencePredictor",
    "advise_rows",
    "scatter_slabs",
]

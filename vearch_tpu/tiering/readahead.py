"""madvise(MADV_WILLNEED) read-ahead for the NVMe mmap gather paths.

A cold slab fetch or rerank gather is a strided walk over an mmap: each
touched row faults its page synchronously, so a 256-row gather spread
over 256 distinct pages pays 256 serialized NVMe round-trips. Advising
the kernel about the row runs FIRST lets it batch those faults into a
few large asynchronous reads before the copy loop touches anything —
the classic `madvise` read-ahead the ROADMAP carried for the tiering
gather path.

Host-side only: this changes page-cache behaviour, never bytes moved to
the device — the warm-path H2D ledger stays exactly zero (asserted in
tests/test_quality.py alongside the tiering perf gates). Purely
advisory and best-effort: any platform that lacks `mmap.madvise`
(py<3.8, non-Linux) or rejects the advice silently degrades to the
plain faulting gather.
"""

from __future__ import annotations

import mmap as _mmap_mod

import numpy as np

#: rows whose gaps are below this many rows are coalesced into one
#: advised run — one big readahead beats many tiny ones, and NVMe
#: sequential bandwidth makes over-reading small gaps free
_GAP_ROWS = 32

#: cap on advised runs per gather: a pathological id spread should cost
#: a bounded number of madvise syscalls, not one per row
_MAX_RUNS = 64


def _coalesce(ids: np.ndarray, gap: int = _GAP_ROWS) -> list[tuple[int, int]]:
    """Sorted docids -> [(start_row, n_rows)] contiguous-ish runs."""
    if ids.size == 0:
        return []
    s = np.sort(np.asarray(ids, dtype=np.int64))
    # run boundaries where the gap to the previous id exceeds the merge
    # threshold; everything between boundaries is advised as one run
    breaks = np.nonzero(np.diff(s) > gap)[0]
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks, [s.size - 1]))
    return [(int(s[a]), int(s[b] - s[a] + 1)) for a, b in zip(starts, ends)]


def advise_rows(arr: np.ndarray, ids: np.ndarray) -> int:  # lint: allow[serving-blocking] madvise(WILLNEED) IS the fault-cost mitigation: coalesced runs, bounded syscalls, never raises
    """Advise WILLNEED for the pages holding `arr[ids]` when `arr` is an
    np.memmap. Returns the number of advised runs (0 = no-op: in-memory
    array, unsupported platform, or empty id set). Never raises."""
    mm = getattr(arr, "_mmap", None)
    if mm is None or not hasattr(mm, "madvise"):
        return 0
    try:
        row_bytes = int(arr.strides[0]) if arr.ndim > 1 else int(arr.itemsize)
        if row_bytes <= 0:
            return 0
        base = int(getattr(arr, "offset", 0))
        page = _mmap_mod.ALLOCATIONGRANULARITY
        runs = _coalesce(np.asarray(ids))
        if len(runs) > _MAX_RUNS:
            # one spanning advisement: bounded syscalls, and WILLNEED
            # over-reading is cheap relative to per-row faults
            lo = runs[0][0]
            hi = runs[-1][0] + runs[-1][1]
            runs = [(lo, hi - lo)]
        advised = 0
        for start_row, n_rows in runs:
            off = base + start_row * row_bytes
            length = n_rows * row_bytes
            # madvise must be page-aligned: round the start down and
            # extend the length to cover the tail row's page
            aligned = (off // page) * page
            length += off - aligned
            end = min(aligned + length, len(mm))
            if end <= aligned:
                continue
            mm.madvise(_mmap_mod.MADV_WILLNEED, aligned, end - aligned)
            advised += 1
        return advised
    except (OSError, ValueError, AttributeError):
        return 0

"""Prometheus-format metrics (dependency-free).

TPU-native stand-in for the reference's monitor package (reference:
internal/monitor/monitor_service.go:77 Register — request duration/count
histograms labelled by op/code, cluster gauges, /metrics on every role).
Counter/Gauge/Histogram with label support, rendered in the Prometheus
text exposition format; every JsonRpcServer mounts a /metrics route and
auto-instruments request count + latency per (method, path, code).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterable

_log = logging.getLogger("vearch.internal")

_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)

# power-of-two buckets for count/size-shaped histograms (WAL batch
# entries, docs per write) where the latency-shaped defaults would put
# every sample in +Inf
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)  # lint: allow[bucket-drift] histogram boundaries, not device batch shapes


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{v}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = ()):
        self.name, self.help, self.labels = name, help_, labels
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        lv = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[lv] = self._values.get(lv, 0.0) + by

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        for lv, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_fmt_labels(self.labels, lv)} {v}")
        return "\n".join(lines)


class Gauge(Counter):
    def set(self, value: float, *label_values: str) -> None:
        lv = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[lv] = value

    def render(self) -> str:
        return super().render().replace(" counter", " gauge", 1)


class CallbackGauge:
    """Gauge whose samples are computed at scrape time (reference:
    monitor_service.go:51-73 cluster gauges are refreshed from master +
    etcd state on collection — pull-time evaluation gives the same
    freshness without a scrape loop). `fn` returns
    {label_values_tuple: value}; unlabelled gauges return {(): value}."""

    def __init__(self, name: str, help_: str, labels: tuple[str, ...], fn):
        self.name, self.help, self.labels, self.fn = name, help_, labels, fn

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        try:
            values = self.fn() or {}
        except Exception:  # a scrape must never 500 the /metrics page
            values = {}
        for lv, v in sorted(values.items()):
            lv = tuple(str(x) for x in lv)
            lines.append(f"{self.name}{_fmt_labels(self.labels, lv)} {v}")
        return "\n".join(lines)


class CallbackCounter(CallbackGauge):
    """Counter sampled at scrape time from an existing monotonic source
    (e.g. raft election totals, the OTLP exporter's dropped-span count)
    — avoids double-bookkeeping a value the owner already maintains.
    `fn` has the CallbackGauge contract: {label_values_tuple: value}."""

    def render(self) -> str:
        return super().render().replace(" gauge", " counter", 1)


class Histogram:
    def __init__(
        self,
        name: str,
        help_: str,
        labels: tuple[str, ...] = (),
        buckets: Iterable[float] = _DEFAULT_BUCKETS,
    ):
        self.name, self.help, self.labels = name, help_, labels
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, *label_values: str) -> None:
        lv = tuple(str(v) for v in label_values)
        with self._lock:
            counts = self._counts.setdefault(lv, [0] * (len(self.buckets) + 1))
            self._sums[lv] = self._sums.get(lv, 0.0) + value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        for lv, counts in sorted(self._counts.items()):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(self.labels + ('le',), lv + (str(b),))} {cum}"
                )
            lines.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(self.labels + ('le',), lv + ('+Inf',))} "
                f"{counts[-1]}"
            )
            lines.append(
                f"{self.name}_sum{_fmt_labels(self.labels, lv)} "
                f"{self._sums[lv]}"
            )
            lines.append(
                f"{self.name}_count{_fmt_labels(self.labels, lv)} {counts[-1]}"
            )
        return "\n".join(lines)


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._lock = threading.Lock()

    def counter(self, name, help_, labels=()) -> Counter:
        m = Counter(name, help_, labels)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name, help_, labels=()) -> Gauge:
        m = Gauge(name, help_, labels)
        with self._lock:
            self._metrics.append(m)
        return m

    def callback_gauge(self, name, help_, labels, fn) -> CallbackGauge:
        m = CallbackGauge(name, help_, labels, fn)
        with self._lock:
            self._metrics.append(m)
        return m

    def callback_counter(self, name, help_, labels, fn) -> CallbackCounter:
        m = CallbackCounter(name, help_, labels, fn)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, name, help_, labels=(), buckets=_DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help_, labels, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def attach(self, metric) -> None:
        """Expose an externally-owned metric (e.g. the process-wide
        internal-error counter) on this registry's /metrics page."""
        with self._lock:
            if metric not in self._metrics:
                self._metrics.append(metric)

    def render(self) -> str:
        with self._lock:
            return "\n".join(m.render() for m in self._metrics) + "\n"


# process-wide swallowed-exception counter (lint rule VL302: a broad
# except in a replication-critical path must raise, log, or count).
# Lives outside any server's registry — raft nodes and WALs are not
# servers — and is attach()ed to every JsonRpcServer registry so each
# role's /metrics page exposes it.
_internal_registry = Registry()
INTERNAL_ERRORS = _internal_registry.counter(
    "vearch_internal_errors_total",
    "exceptions deliberately swallowed at non-fatal sites, by site",
    ("site",))


def internal_error(site: str, exc: BaseException | None = None) -> None:
    """Count + log an exception a caller chose not to propagate.

    The contract for 'this failure must not break the caller' paths
    (observer hooks, best-effort notifications): swallowing is allowed
    only if the event is counted per site and logged — a replica that
    diverges silently is the incident the obs stack exists to catch.
    """
    INTERNAL_ERRORS.inc(site)
    if exc is not None:
        _log.warning("internal error at %s: %s: %s",
                     site, type(exc).__name__, exc)


def register_tracer_metrics(registry: "Registry", tracer) -> None:
    """OTLP exporter health counters on every traced role: a dead or
    slow collector costs dropped batches, never request latency — these
    make that loss visible instead of silent. Zero when no collector is
    configured (the exporter is absent)."""

    def _read(attr: str):
        def read() -> dict[tuple, float]:
            exp = getattr(tracer, "exporter", None)
            return {(): float(getattr(exp, attr, 0) or 0) if exp else 0.0}
        return read

    registry.callback_counter(
        "tracing_dropped_spans_total",
        "spans lost to queue overflow or a dead collector",
        (), _read("dropped"))
    registry.callback_counter(
        "tracing_exported_spans_total",
        "spans successfully shipped to the collector",
        (), _read("exported"))


def register_process_gauges(registry: "Registry") -> None:
    """Node/process system gauges on every role (reference:
    pkg/metrics/mserver system stats feeding the monitor registry):
    RSS, virtual size, CPU seconds, open fds, threads, uptime — read
    from /proc (zero-dep; silently absent off Linux)."""
    import os
    import time as _time

    start = _time.monotonic()  # clock steps must not bend uptime
    tick = float(os.sysconf("SC_CLK_TCK")) if hasattr(os, "sysconf") else 100.0
    page = float(os.sysconf("SC_PAGE_SIZE")) if hasattr(os, "sysconf") else 4096.0

    def read() -> dict[tuple, float]:
        out: dict[tuple, float] = {}
        try:
            with open("/proc/self/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            # fields after comm: utime=11 stime=12 num_threads=17
            # vsize=20 rss=21 (0-based in this post-comm slice)
            out[("cpu_seconds",)] = (float(parts[11]) + float(parts[12])) / tick
            out[("threads",)] = float(parts[17])
            out[("vsize_bytes",)] = float(parts[20])
            out[("rss_bytes",)] = float(parts[21]) * page
        except (OSError, IndexError, ValueError):
            pass
        try:
            out[("open_fds",)] = float(len(os.listdir("/proc/self/fd")))
        except OSError:
            pass
        out[("uptime_seconds",)] = _time.monotonic() - start
        return out

    registry.callback_gauge(
        "vearch_process", "process/system stats", ("stat",), read,
    )

"""Pluggable object store for backup/restore.

The reference backs up shards to S3/MinIO (reference:
ps/backup/ps_backup_service.go:14,67 minio client; versioned layout with
ref-counted files). The interface here is S3-shaped (put/get/list by key);
`LocalObjectStore` is the in-tree backend (shared filesystem / NFS), and
an S3 backend can implement the same three methods against any client
without touching the backup service (this image is zero-egress, so no S3
SDK is vendored — see docs/PARITY.md).
"""

from __future__ import annotations

import os
import shutil


def is_within(root: str, path: str) -> bool:
    """True when `path` resolves inside `root` (commonpath, not string
    prefix: '<root>-evil/x' shares the prefix but not the directory)."""
    root = os.path.abspath(root)
    path = os.path.abspath(path)
    return os.path.commonpath([root, path]) == root


class ObjectStore:
    def put_file(self, key: str, local_path: str) -> None:
        raise NotImplementedError

    def get_file(self, key: str, local_path: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError


class LocalObjectStore(ObjectStore):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.abspath(
            os.path.join(os.path.abspath(self.root), key.lstrip("/"))
        )
        if not is_within(self.root, path):
            raise ValueError(f"key escapes store root: {key}")
        return path

    def put_file(self, key: str, local_path: str) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copyfile(local_path, dst)

    def get_file(self, key: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        shutil.copyfile(self._path(key), local_path)

    def list(self, prefix: str) -> list[str]:
        base = self._path(prefix)
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for f in files:
                full = os.path.join(dirpath, f)
                out.append(os.path.relpath(full, self.root))
        return sorted(out)

    def put_tree(self, key_prefix: str, local_dir: str) -> int:
        n = 0
        for dirpath, _dirs, files in os.walk(local_dir):
            for f in files:
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, local_dir)
                self.put_file(f"{key_prefix}/{rel}", full)
                n += 1
        return n

    def get_tree(self, key_prefix: str, local_dir: str) -> int:
        n = 0
        for key in self.list(key_prefix):
            rel = os.path.relpath(key, key_prefix)
            self.get_file(key, os.path.join(local_dir, rel))
            n += 1
        return n

"""Pluggable object store for backup/restore.

The reference backs up shards to S3/MinIO (reference:
ps/backup/ps_backup_service.go:14,67 minio client; versioned layout).
Two backends behind one interface:

- `LocalObjectStore` — shared filesystem / NFS;
- `S3ObjectStore` — stdlib-only S3 client (AWS Signature V4 over
  http.client; works against AWS S3 and MinIO). No SDK: the image is
  zero-egress, and the wire protocol is small enough that the four
  operations the backup service needs (PUT/GET object, ListObjectsV2)
  fit in ~100 lines.

Integrity: `put_tree` writes a MANIFEST with per-file CRC32s;
`get_tree` verifies every file against it and fails loudly on mismatch
(reference: ps/backup CRC32 checks).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import zlib

MANIFEST = "MANIFEST.json"
DEDUP_MANIFEST = "MANIFEST.dedup.json"
REFS = "refs.json"


class S3HttpError(IOError):
    """Deliberate S3 error raised AFTER the response body was drained —
    the keep-alive connection is still reusable (unlike transport-level
    OSErrors mid-body, which must drop the connection)."""


def s3_endpoint_host(endpoint: str) -> str:
    """Normalize an endpoint to its host:port — shared by the client and
    the PS allowlist check so both accept/deny identically."""
    return endpoint.split("://", 1)[-1].rstrip("/")


def is_within(root: str, path: str) -> bool:
    """True when `path` resolves inside `root` (commonpath, not string
    prefix: '<root>-evil/x' shares the prefix but not the directory)."""
    root = os.path.abspath(root)
    path = os.path.abspath(path)
    return os.path.commonpath([root, path]) == root


class ObjectStore:
    def put_bytes(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def put_file(self, key: str, local_path: str) -> None:
        with open(local_path, "rb") as f:
            self.put_bytes(key, f.read())

    def get_file(self, key: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(self.get_bytes(key))

    def exists(self, key: str) -> bool:
        # abstract on purpose: a get_bytes-based fallback would download
        # whole blobs per probe and read transient store errors as
        # "absent", silently re-uploading (or worse, GC'ing) under
        # faults — every backend must answer existence natively
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    # -- content-addressed dedup tier (reference: ps/backup/
    #    ref_count_manager.go — ref-counted shard files shared across
    #    backup versions) ---------------------------------------------------

    def put_tree_dedup(self, version_prefix: str, local_dir: str,
                       pool_prefix: str, progress=None) -> dict:
        """Upload a tree content-addressed: file payloads land in
        `{pool_prefix}/blobs/{sha256}` (skipped when already present —
        unchanged segments cost nothing across versions), the version
        keeps only a manifest mapping paths to hashes. Ref counts in
        `{pool_prefix}/refs.json` record which versions hold each blob.

        Single-writer discipline: the pool is per-partition and the
        master serialises backup commands per space, so refs read-
        modify-write needs no CAS (matches the reference's per-shard
        manager ownership).
        """
        manifest: dict[str, dict] = {}
        uploads: list[tuple[str, str]] = []
        for dirpath, _dirs, files in os.walk(local_dir):
            for fname in files:
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, local_dir).replace(os.sep, "/")
                h = _sha_file(full)
                manifest[rel] = {"sha256": h,
                                 "size": os.path.getsize(full)}
                uploads.append((h, full))
        # ordering (the ref_count_manager pattern): incref FIRST, then
        # manifest, then blobs. A crash mid-sequence leaves at worst a
        # harmless leaked ref; incref-last would leave a window where a
        # restorable-looking version's shared blobs are unprotected
        # from a concurrent delete's GC.
        seen: set[str] = set()
        for h, _full in uploads:
            seen.add(h)
        refs = self._read_refs(pool_prefix)
        for h in seen:
            holders = refs.setdefault(h, [])
            if version_prefix not in holders:
                holders.append(version_prefix)
        self.put_bytes(f"{pool_prefix}/{REFS}", json.dumps(refs).encode())
        # manifest before blobs: an interrupted backup fails restore
        # loudly (missing blobs), never poses as a complete smaller one
        self.put_bytes(f"{version_prefix}/{DEDUP_MANIFEST}",
                       json.dumps(manifest).encode())
        new = 0
        done: set[str] = set()
        for pos, (h, full) in enumerate(uploads):
            if h not in done:
                done.add(h)
                blob_key = f"{pool_prefix}/blobs/{h}"
                if not self.exists(blob_key):
                    self.put_file(blob_key, full)
                    new += 1
            if progress is not None:
                # progress(files_done, files_total) after each file —
                # the async backup job's per-partition counter
                progress(pos + 1, len(uploads))
        return {"files": len(manifest), "blobs_uploaded": new,
                "blobs_shared": len(seen) - new}

    def get_tree_dedup(self, version_prefix: str, local_dir: str,
                       pool_prefix: str) -> int:
        """Restore a dedup tree, verifying sha256 + size per file."""
        try:
            manifest = json.loads(
                self.get_bytes(f"{version_prefix}/{DEDUP_MANIFEST}")
            )
        except (KeyError, FileNotFoundError) as e:
            raise IOError(
                f"backup at {version_prefix!r} has no dedup manifest "
                f"(incomplete or interrupted backup)"
            ) from e
        os.makedirs(local_dir, exist_ok=True)
        for rel, meta in manifest.items():
            dst = os.path.join(local_dir, rel)
            if os.path.isabs(rel) or not is_within(local_dir, dst):
                raise IOError(f"backup key escapes restore dir: {rel!r}")
            self.get_file(f"{pool_prefix}/blobs/{meta['sha256']}", dst)
            if (
                _sha_file(dst) != meta["sha256"]
                or os.path.getsize(dst) != meta["size"]
            ):
                raise IOError(
                    f"backup integrity check failed for {rel!r}: "
                    f"sha/size mismatch"
                )
        return len(manifest)

    def delete_tree_dedup(self, version_prefix: str,
                          pool_prefix: str) -> dict:
        """Drop a version: decref every pool ref naming it,
        garbage-collect blobs no other version holds (reference:
        ref_count_manager.go decref + cleanup)."""
        # scrub this version from EVERY refs entry, not just the hashes
        # its manifest names: incref runs before the manifest write, so
        # a backup that crashed in that window has refs but no manifest —
        # keying decref on the manifest would pin its blobs (and any it
        # shares with healthy versions) behind a phantom holder forever
        refs = self._read_refs(pool_prefix)
        deleted = 0
        changed = False
        for h in list(refs):
            holders = refs[h]
            if version_prefix in holders:
                holders.remove(version_prefix)
                changed = True
            if not holders:
                # drop the refs entry only once the blob is actually
                # gone: a transient store error must leave the empty
                # entry behind so the NEXT delete call retries the GC
                # instead of orphaning the blob forever
                try:
                    self.delete(f"{pool_prefix}/blobs/{h}")
                    deleted += 1
                except (FileNotFoundError, KeyError):
                    pass  # already gone
                except IOError:
                    continue
                refs.pop(h, None)
                changed = True
        if changed or deleted:
            self.put_bytes(f"{pool_prefix}/{REFS}",
                           json.dumps(refs).encode())
        for key in self.list(version_prefix.rstrip("/") + "/"):
            try:
                self.delete(key)
            except (FileNotFoundError, KeyError, IOError):
                pass
        return {"blobs_deleted": deleted, "blobs_kept": len(refs)}

    def _read_refs(self, pool_prefix: str) -> dict:
        try:
            return json.loads(self.get_bytes(f"{pool_prefix}/{REFS}"))
        except (KeyError, FileNotFoundError, ValueError):
            return {}

    # -- tree transfer with CRC32 manifest (reference: ps/backup crc
    #    integrity + ref-counted shard files) ------------------------------

    def put_tree(self, key_prefix: str, local_dir: str,
                 progress=None) -> int:
        """Upload a directory tree. The manifest (per-file CRC32 + size,
        streamed, never whole-file in memory) is written FIRST: a backup
        interrupted mid-upload then fails restore loudly as incomplete,
        instead of masquerading as a smaller complete one."""
        manifest: dict[str, dict] = {}
        paths: list[tuple[str, str]] = []
        for dirpath, _dirs, files in os.walk(local_dir):
            for fname in files:
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, local_dir).replace(os.sep, "/")
                manifest[rel] = {"crc32": _crc_file(full),
                                 "size": os.path.getsize(full)}
                paths.append((rel, full))
        self.put_bytes(f"{key_prefix}/{MANIFEST}",
                       json.dumps(manifest).encode())
        for pos, (rel, full) in enumerate(paths):
            self.put_file(f"{key_prefix}/{rel}", full)
            if progress is not None:
                progress(pos + 1, len(paths))
        return len(paths)

    def get_tree(self, key_prefix: str, local_dir: str) -> int:
        """Restore a tree, verifying every file's CRC32 against the
        manifest (required); corrupt, missing, or path-escaping entries
        abort the restore rather than quietly loading damaged state."""
        try:
            manifest = json.loads(
                self.get_bytes(f"{key_prefix}/{MANIFEST}")
            )
        except (KeyError, FileNotFoundError) as e:
            raise IOError(
                f"backup at {key_prefix!r} has no manifest (incomplete "
                f"or interrupted backup)"
            ) from e
        pfx = key_prefix.rstrip("/") + "/"  # exact dir, not shard_1 ~ shard_10
        os.makedirs(local_dir, exist_ok=True)
        n = 0
        restored = set()
        for key in self.list(pfx):
            rel = key[len(pfx):] if key.startswith(pfx) else key
            if rel == MANIFEST:
                continue
            dst = os.path.join(local_dir, rel)
            # a hostile/corrupt store must not write outside local_dir
            if os.path.isabs(rel) or not is_within(local_dir, dst):
                raise IOError(f"backup key escapes restore dir: {rel!r}")
            meta = manifest.get(rel)
            if meta is None:
                raise IOError(f"backup file {rel!r} not in manifest")
            self.get_file(key, dst)
            if _crc_file(dst) != meta["crc32"] or \
                    os.path.getsize(dst) != meta["size"]:
                raise IOError(
                    f"backup integrity check failed for {rel!r}: "
                    f"crc/size mismatch"
                )
            restored.add(rel)
            n += 1
        missing = set(manifest) - restored
        if missing:
            raise IOError(f"backup incomplete: missing {sorted(missing)}")
        return n


def _sha_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return h.hexdigest()
            h.update(buf)


def _crc_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def make_object_store(spec: dict | str) -> "ObjectStore":
    """Factory from a backup request's store spec: a plain string is a
    local root; {"type": "s3", ...} builds the S3 backend."""
    if isinstance(spec, str):
        return LocalObjectStore(spec)
    t = spec.get("type", "local")
    if t == "local":
        return LocalObjectStore(spec["root"])
    if t == "s3":
        return S3ObjectStore(
            endpoint=spec["endpoint"], bucket=spec["bucket"],
            access_key=spec.get("access_key", ""),
            secret_key=spec.get("secret_key", ""),
            region=spec.get("region", "us-east-1"),
            prefix=spec.get("prefix", ""),
        )
    raise ValueError(f"unknown object store type {t!r}")


class LocalObjectStore(ObjectStore):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.abspath(
            os.path.join(os.path.abspath(self.root), key.lstrip("/"))
        )
        if not is_within(self.root, path):
            raise ValueError(f"key escapes store root: {key}")
        return path

    def put_bytes(self, key: str, data: bytes) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, dst)

    def get_bytes(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def put_file(self, key: str, local_path: str) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copyfile(local_path, dst)

    def get_file(self, key: str, local_path: str) -> None:
        # streamed copy: multi-GB shard files never sit in memory
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        shutil.copyfile(self._path(key), local_path)

    def list(self, prefix: str) -> list[str]:
        base = self._path(prefix)
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for f in files:
                full = os.path.join(dirpath, f)
                out.append(
                    os.path.relpath(full, self.root).replace(os.sep, "/")
                )
        return sorted(out)

    def exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class S3ObjectStore(ObjectStore):
    """Minimal S3 client: PUT/GET object + ListObjectsV2 with AWS
    Signature V4 (reference: ps/backup uses the minio client for the
    same three calls). Stdlib only; path-style addressing so MinIO
    works out of the box."""

    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 prefix: str = ""):
        import threading

        # endpoint: "host:port" or "http(s)://host:port"
        self.secure = endpoint.startswith("https://")
        self.host = s3_endpoint_host(endpoint)
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.prefix = prefix.strip("/")
        # one kept-alive connection per store (a tree transfer would
        # otherwise pay a TCP/TLS handshake per file)
        self._conn = None
        self._conn_lock = threading.Lock()

    def _key(self, key: str) -> str:
        key = key.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    # -- SigV4 (AWS Signature Version 4, the public spec) ----------------

    def _sign(self, method: str, path: str, query: str, payload_hash: str
              ) -> dict:
        import datetime
        import hashlib
        import hmac
        from urllib.parse import quote

        t = datetime.datetime.now(datetime.timezone.utc)
        amz_date = t.strftime("%Y%m%dT%H%M%SZ")
        datestamp = t.strftime("%Y%m%d")
        headers = {
            "host": self.host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        signed = ";".join(sorted(headers))
        # SigV4 canonicalises query params SORTED by name — real S3
        # rejects construction order (SignatureDoesNotMatch)
        canonical_query = "&".join(sorted(query.split("&"))) if query else ""
        canonical = "\n".join([
            method, quote(path), canonical_query,
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed, payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ])

        def hm(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hm(("AWS4" + self.secret_key).encode(), datestamp)
        k = hm(hm(hm(k, self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        return headers

    def _request(self, method: str, key: str = "", query: str = "",
                 payload: bytes = b"", body_path: str | None = None,
                 stream_to: str | None = None) -> bytes:
        """One signed S3 call. body_path streams the request body from
        disk (two-pass: sha256 then send); stream_to writes the response
        to disk in chunks — multi-GB shard files never sit in memory."""
        import hashlib
        import http.client
        from urllib.parse import quote

        path = f"/{self.bucket}"
        if key:
            path += f"/{key}"
        if body_path is not None:
            h = hashlib.sha256()
            size = 0
            with open(body_path, "rb") as f:
                while True:
                    buf = f.read(1 << 20)
                    if not buf:
                        break
                    h.update(buf)
                    size += len(buf)
            payload_hash = h.hexdigest()
        else:
            payload_hash = hashlib.sha256(payload).hexdigest()
        headers = self._sign(method, path, query, payload_hash)
        url = quote(path) + (f"?{query}" if query else "")

        def send(conn):
            if body_path is not None:
                headers["Content-Length"] = str(size)
                with open(body_path, "rb") as f:
                    conn.request(method, url, body=f, headers=headers)
            else:
                conn.request(method, url, body=payload or None,
                             headers=headers)
            return conn.getresponse()

        with self._conn_lock:
            cls = http.client.HTTPSConnection if self.secure \
                else http.client.HTTPConnection
            try:
                if self._conn is None:
                    self._conn = cls(self.host, timeout=60)
                resp = send(self._conn)
            except (http.client.HTTPException, OSError):
                # stale keep-alive connection: one fresh retry
                if self._conn is not None:
                    self._conn.close()
                self._conn = cls(self.host, timeout=60)
                resp = send(self._conn)
            try:
                if resp.status == 404:
                    resp.read()  # drained: connection stays reusable
                    raise FileNotFoundError(f"s3://{self.bucket}/{key}")
                if resp.status >= 300:
                    body = resp.read()
                    raise S3HttpError(
                        f"S3 {method} {path}: {resp.status} {body[:200]!r}"
                    )
                if stream_to is not None:
                    os.makedirs(os.path.dirname(stream_to) or ".",
                                exist_ok=True)
                    with open(stream_to, "wb") as out:
                        while True:
                            buf = resp.read(1 << 20)
                            if not buf:
                                break
                            out.write(buf)
                    return b""
                return resp.read()
            except (FileNotFoundError, S3HttpError):
                raise  # drained above: keep-alive intact
            except Exception:
                # anything else (reset mid-body, disk full during the
                # streamed write, ...) leaves an undrained response
                # that would poison keep-alive: drop the connection
                self._conn.close()
                self._conn = None
                raise

    # -- ObjectStore interface -------------------------------------------

    def put_bytes(self, key: str, data: bytes) -> None:
        self._request("PUT", self._key(key), payload=data)

    def get_bytes(self, key: str) -> bytes:
        return self._request("GET", self._key(key))

    def put_file(self, key: str, local_path: str) -> None:
        self._request("PUT", self._key(key), body_path=local_path)

    def get_file(self, key: str, local_path: str) -> None:
        self._request("GET", self._key(key), stream_to=local_path)

    def exists(self, key: str) -> bool:
        try:
            self._request("HEAD", self._key(key))
            return True
        except FileNotFoundError:
            return False

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", self._key(key))
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> list[str]:
        import html
        import re
        from urllib.parse import quote

        full_prefix = self._key(prefix)
        out: list[str] = []
        token = ""
        while True:
            query = f"list-type=2&prefix={quote(full_prefix, safe='')}"
            if token:
                query += f"&continuation-token={quote(token, safe='')}"
            body = self._request("GET", "", query=query).decode()
            # keys ride XML-escaped (&amp; etc.); unescape or keys with
            # '&'/'<' silently mismatch the manifest on restore
            out.extend(
                html.unescape(k)
                for k in re.findall(r"<Key>([^<]+)</Key>", body)
            )
            m = re.search(
                r"<NextContinuationToken>([^<]+)</NextContinuationToken>",
                body,
            )
            if not m:
                break
            token = html.unescape(m.group(1))
        strip = (self.prefix + "/") if self.prefix else ""
        return sorted(
            k[len(strip):] if strip and k.startswith(strip) else k
            for k in out
        )

"""Per-partition write-ahead log.

TPU-native analogue of the reference's raft WAL (reference:
internal/ps/storage/raftstore/store.go:124 wal storage under the
partition path; tiglabs raft log semantics). The log is the durability
and replication substrate: every write is fsync'd here before it is
acked, replayed on recovery, shipped to followers, and truncated behind
the periodic flush (store_raft_job.go:40).

On-disk format, one file per partition (`wal.log`):
    [u32 len][u32 crc32(payload)][payload json]
Recovery stops at the first short/corrupt record (torn tail from a
crash) and truncates the file there. A sidecar `wal.meta.json`
(tmp+rename atomic) records first_index / term / commit_index.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any

from vearch_tpu.cluster.metrics import internal_error
from vearch_tpu.tools import lockcheck

_HDR = struct.Struct("<II")


@lockcheck.guarded
class Wal:
    # lock discipline (lint VL201 + runtime lockcheck): the in-memory
    # log mirror and its window bounds only mutate under _lock. term/
    # commit_index/voted_for are deliberately absent — they are owner-
    # serialized (RaftNode mutates them under ITS _lock; the WAL only
    # reads them back under its own when persisting meta).
    _guarded_by = {
        "_entries": "_lock",
        "first_index": "_lock",
        "horizon_term": "_lock",
    }

    def __init__(self, dirpath: str):
        os.makedirs(dirpath, exist_ok=True)
        self.path = os.path.join(dirpath, "wal.log")
        self.meta_path = os.path.join(dirpath, "wal.meta.json")
        self._lock = lockcheck.make_lock("wal._lock", reentrant=True)
        # in-memory mirror: entry dicts {"index", "term", "op"} — the log
        # tail is bounded by flush-truncation, so this stays modest
        self._entries: list[dict] = []
        self.first_index = 1  # index of the first entry retained in log
        # term of the entry at first_index - 1 (the compaction/snapshot
        # horizon). Persisted so a leader can always send a REAL
        # prev_term for appends starting exactly at its horizon — the
        # alternative (matching by index alone) lets a follower keep a
        # divergent uncommitted entry at that index, a Log Matching
        # violation. None = unknown (legacy meta): callers must fall
        # back to snapshot install rather than trust the index.
        self.horizon_term: int | None = 0
        self.term = 0
        self.commit_index = 0
        self.voted_for: int | None = None  # election mode only
        # optional (event, info) sink set by the owner (the PS wires it
        # to /metrics histograms). Same contract as the raft observer:
        # cheap, non-blocking, exceptions swallowed — it fires under the
        # WAL lock on the write path.
        self.observer = None
        self._load_meta()
        self._recover()
        self._fd = open(self.path, "ab")

    # -- meta ----------------------------------------------------------------

    def _load_meta(self) -> None:  # lint: allow[guarded] construction-time, runs before the instance is published
        if os.path.exists(self.meta_path):
            with open(self.meta_path) as f:
                m = json.load(f)
            self.first_index = int(m.get("first_index", 1))
            self.term = int(m.get("term", 0))
            self.commit_index = int(m.get("commit_index", 0))
            self.voted_for = m.get("voted_for")
            if "horizon_term" in m:
                ht = m["horizon_term"]
                self.horizon_term = None if ht is None else int(ht)
            else:
                # legacy meta: the horizon term is only knowable when
                # the log was never compacted (horizon = index 0)
                self.horizon_term = 0 if self.first_index == 1 else None

    def save_meta(self, fsync: bool = False) -> None:
        with self._lock:
            tmp = self.meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({
                    "first_index": self.first_index,
                    "term": self.term,
                    "commit_index": self.commit_index,
                    "voted_for": self.voted_for,
                    "horizon_term": self.horizon_term,
                }, f)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self.meta_path)

    # -- recovery ------------------------------------------------------------

    def _recover(self) -> None:  # lint: allow[guarded] construction-time, runs before the instance is published
        if not os.path.exists(self.path):
            return
        good = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    break
                ln, crc = _HDR.unpack(hdr)
                payload = f.read(ln)
                if len(payload) < ln or zlib.crc32(payload) != crc:
                    break  # torn tail
                self._entries.append(json.loads(payload))
                good = f.tell()
        actual = os.path.getsize(self.path)
        if good < actual:
            with open(self.path, "r+b") as f:
                f.truncate(good)
        # drop entries the meta says were already pruned (crash between
        # file rewrite and meta update cannot happen — rewrite updates
        # meta first; but be defensive)
        while self._entries and self._entries[0]["index"] < self.first_index:
            self._entries.pop(0)
        if self._entries:
            self.first_index = self._entries[0]["index"]

    # -- reads ---------------------------------------------------------------

    @property
    def last_index(self) -> int:
        with self._lock:
            if self._entries:
                return self._entries[-1]["index"]
            return self.first_index - 1

    @property
    def last_term(self) -> int:
        with self._lock:
            return self._entries[-1]["term"] if self._entries else 0

    def get(self, index: int) -> dict | None:
        with self._lock:
            i = index - self.first_index
            if 0 <= i < len(self._entries):
                return self._entries[i]
            return None

    def term_at(self, index: int) -> int | None:
        """Term of the entry at `index`; the persisted horizon term at
        first_index - 1 (which is the 0-sentinel, term 0, for a
        never-compacted log); None when the entry has been truncated
        away (and the horizon term is unknown) or is beyond the end.

        NOTE: index 0 deliberately has NO special case. On a compacted
        log (first_index > 1) an unconditional `term_at(0) == 0` let a
        leader believe it could serve an append anchored at prev=0 —
        but entries 1..first_index-1 are GONE, so the 'entries from 1'
        it would attach actually start at first_index and the follower
        hits an append gap. Returning None forces the snapshot path for
        followers behind the horizon (found by the empty-log master
        joiner)."""
        e = self.get(index)
        if e is not None:
            return int(e["term"])
        with self._lock:
            if index == self.first_index - 1:
                return self.horizon_term
        return None

    def entries_from(self, index: int, max_n: int = 512) -> list[dict]:
        with self._lock:
            i = max(0, index - self.first_index)
            return list(self._entries[i : i + max_n])

    # -- writes --------------------------------------------------------------

    def append(self, entries: list[dict], fsync: bool = True) -> None:
        if not entries:
            return
        with self._lock:
            expect = self.last_index + 1
            assert entries[0]["index"] == expect, (
                f"append gap: {entries[0]['index']} != {expect}"
            )
            buf = bytearray()
            for e in entries:
                payload = json.dumps(e).encode()
                buf += _HDR.pack(len(payload), zlib.crc32(payload))
                buf += payload
            t0 = time.monotonic()
            self._fd.write(buf)
            self._fd.flush()
            t_fsync = time.monotonic()
            if fsync:
                os.fsync(self._fd.fileno())
            t1 = time.monotonic()
            self._entries.extend(entries)
            obs = self.observer
            if obs is not None:
                try:
                    obs("append", {
                        "entries": len(entries),
                        "bytes": len(buf),
                        "seconds": t1 - t0,
                        "fsync_seconds": t1 - t_fsync if fsync else 0.0,
                    })
                except Exception as e:
                    # the observer is best-effort by contract, but its
                    # failures are counted, never silent
                    internal_error("wal.observer", e)

    def truncate_suffix(self, from_index: int) -> None:
        """Drop entries >= from_index (conflict resolution on a follower
        that diverged from the leader)."""
        with self._lock:
            if from_index > self.last_index:
                return
            keep = max(0, from_index - self.first_index)
            self._entries = self._entries[:keep]
            self._rewrite()

    def truncate_prefix(self, new_first: int) -> None:
        """Drop entries < new_first (log compaction behind a flush —
        reference: store_raft_job.go:40 truncate job)."""
        with self._lock:
            if new_first <= self.first_index:
                return
            # record the term at the NEW horizon before the entry holding
            # it is dropped (None only if new_first - 1 is itself already
            # behind an unknown horizon)
            self.horizon_term = self.term_at(new_first - 1)
            drop = min(new_first - self.first_index, len(self._entries))
            self._entries = self._entries[drop:]
            self.first_index = new_first
            self._rewrite()

    def reset(self, first_index: int,
              horizon_term: int | None = None) -> None:
        """Clear the log entirely (after installing a snapshot at
        first_index - 1). `horizon_term` is the term of the snapshot's
        last included entry; None when the installer doesn't know it
        (subsequent appends at the horizon then require a fresh
        snapshot rather than index-matching)."""
        with self._lock:
            self._entries = []
            self.first_index = first_index
            self.horizon_term = horizon_term
            self._rewrite()

    def _rewrite(self) -> None:
        self._fd.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for e in self._entries:
                payload = json.dumps(e).encode()
                f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
                f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self.save_meta(fsync=True)
        self._fd = open(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            self.save_meta()
            self._fd.close()

"""User/role auth: BasicAuth + privilege checks.

Mirrors the reference's auth model (reference: entity/user.go User/Role/
Privilege; root bootstrap master/server.go:160-181; BasicAuth middleware
cluster_api.go:252 and router doc_http.go:179). Users carry a role; roles
grant privileges per resource: "ResourceAll", "ResourceDocument",
"ResourceSpace", ... with operations Read/Write/All.
"""

from __future__ import annotations

import base64
import hashlib
import secrets

from vearch_tpu.cluster.rpc import RpcError

ROOT_NAME = "root"

PRIVI_ALL = "All"
PRIVI_READ = "Read"
PRIVI_WRITE = "WriteOnly"

RESOURCE_ALL = "ResourceAll"
RESOURCE_DOCUMENT = "ResourceDocument"

BUILTIN_ROLES = {
    "root": {RESOURCE_ALL: PRIVI_ALL},
    "read": {RESOURCE_ALL: PRIVI_READ},
    "write": {RESOURCE_ALL: PRIVI_ALL},
    "document": {RESOURCE_DOCUMENT: PRIVI_ALL},
}


def hash_password(password: str, salt: str | None = None) -> str:
    salt = salt or secrets.token_hex(8)
    digest = hashlib.sha256((salt + password).encode()).hexdigest()
    return f"{salt}${digest}"


def verify_password(password: str, stored: str) -> bool:
    salt, _digest = stored.split("$", 1)
    return secrets.compare_digest(hash_password(password, salt), stored)


def parse_basic_auth(headers) -> tuple[str, str]:
    """Extract (user, password) from an Authorization: Basic header."""
    header = headers.get("Authorization", "")
    if not header.startswith("Basic "):
        raise RpcError(401, "missing Basic auth")
    try:
        raw = base64.b64decode(header[6:]).decode()
        user, _, password = raw.partition(":")
    except Exception as e:
        raise RpcError(401, "malformed Basic auth") from e
    return user, password


class AuthService:
    """Master-side user/role registry over the metastore."""

    def __init__(self, store, root_password: str = "secret"):
        self.store = store
        if self.store.get(f"/user/{ROOT_NAME}") is None:
            self.store.put(f"/user/{ROOT_NAME}", {
                "name": ROOT_NAME,
                "password": hash_password(root_password),
                "role": "root",
            })
        for name, privileges in BUILTIN_ROLES.items():
            if self.store.get(f"/role/{name}") is None:
                self.store.put(f"/role/{name}",
                               {"name": name, "privileges": privileges})

    def create_user(self, name: str, password: str, role: str) -> dict:
        if self.store.get(f"/user/{name}") is not None:
            raise RpcError(409, f"user {name} exists")
        if self.store.get(f"/role/{role}") is None:
            raise RpcError(404, f"role {role} not found")
        user = {"name": name, "password": hash_password(password),
                "role": role}
        self.store.put(f"/user/{name}", user)
        return {"name": name, "role": role}

    def delete_user(self, name: str) -> None:
        if name == ROOT_NAME:
            raise RpcError(400, "cannot delete root")
        if not self.store.delete(f"/user/{name}"):
            raise RpcError(404, f"user {name} not found")

    def create_role(self, name: str, privileges: dict[str, str]) -> dict:
        if self.store.get(f"/role/{name}") is not None:
            raise RpcError(409, f"role {name} exists")
        role = {"name": name, "privileges": privileges}
        self.store.put(f"/role/{name}", role)
        return role

    def check(self, user: str, password: str) -> dict:
        """Validate credentials; returns the user's role record."""
        u = self.store.get(f"/user/{user}")
        if u is None or not verify_password(password, u["password"]):
            raise RpcError(401, "bad credentials")
        role = self.store.get(f"/role/{u['role']}") or {"privileges": {}}
        return {"name": user, "role": u["role"],
                "privileges": role["privileges"]}

    def authorize(self, privileges: dict[str, str], resource: str,
                  write: bool) -> None:
        grant = privileges.get(resource) or privileges.get(RESOURCE_ALL)
        if grant is None:
            raise RpcError(403, f"no privilege on {resource}")
        if write and grant == PRIVI_READ:
            raise RpcError(403, f"read-only privilege on {resource}")

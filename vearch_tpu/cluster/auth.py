"""User/role auth: BasicAuth + per-endpoint privilege checks.

Mirrors the reference's auth model (reference: entity/user.go — Privilege
None/WriteOnly/ReadOnly/WriteRead, Resource map, ParseResources
entity/user.go:194-260, Role.HasPermissionForResources entity/user.go:300;
root bootstrap master/server.go:160-181; BasicAuth middleware
cluster_api.go:153 and router doc_http.go:122). Users carry a role; roles
grant a privilege per resource; every authenticated request is checked
against the (resource, privilege) derived from its endpoint + method.
"""

from __future__ import annotations

import base64
import hashlib
import secrets

from vearch_tpu.cluster.rpc import RpcError

ROOT_NAME = "root"

# privilege lattice (reference: entity/user.go:29-34)
PRIVI_NONE = "None"
PRIVI_WRITE = "WriteOnly"
PRIVI_READ = "ReadOnly"
PRIVI_ALL = "WriteRead"

RESOURCE_ALL = "ResourceAll"
RESOURCE_CLUSTER = "ResourceCluster"
RESOURCE_SERVER = "ResourceServer"
RESOURCE_PARTITION = "ResourcePartition"
RESOURCE_DB = "ResourceDB"
RESOURCE_SPACE = "ResourceSpace"
RESOURCE_DOCUMENT = "ResourceDocument"
RESOURCE_INDEX = "ResourceIndex"
RESOURCE_ALIAS = "ResourceAlias"
RESOURCE_USER = "ResourceUser"
RESOURCE_ROLE = "ResourceRole"
RESOURCE_CONFIG = "ResourceConfig"

# builtin roles (reference: entity/user.go RoleMap — root/ClusterAdmin/
# SpaceAdmin/DocumentAdmin...; the short "read"/"write"/"document" names
# are kept for the SDK surface, with reference-faithful grants: "write"
# carries WriteOnly, not admin)
BUILTIN_ROLES = {
    "root": {RESOURCE_ALL: PRIVI_ALL},
    "read": {RESOURCE_ALL: PRIVI_READ},
    "write": {RESOURCE_ALL: PRIVI_WRITE},
    "document": {RESOURCE_DOCUMENT: PRIVI_ALL, RESOURCE_INDEX: PRIVI_ALL},
    "defaultClusterAdmin": {
        RESOURCE_CLUSTER: PRIVI_ALL, RESOURCE_SERVER: PRIVI_ALL,
        RESOURCE_PARTITION: PRIVI_ALL, RESOURCE_DB: PRIVI_ALL,
        RESOURCE_SPACE: PRIVI_ALL, RESOURCE_DOCUMENT: PRIVI_ALL,
        RESOURCE_INDEX: PRIVI_ALL, RESOURCE_ALIAS: PRIVI_ALL,
        RESOURCE_CONFIG: PRIVI_ALL, RESOURCE_USER: PRIVI_ALL,
        RESOURCE_ROLE: PRIVI_ALL,
    },
    "defaultSpaceAdmin": {
        RESOURCE_SPACE: PRIVI_ALL, RESOURCE_DOCUMENT: PRIVI_ALL,
        RESOURCE_INDEX: PRIVI_ALL, RESOURCE_ALIAS: PRIVI_READ,
    },
    "defaultDocumentAdmin": {
        RESOURCE_DOCUMENT: PRIVI_ALL, RESOURCE_INDEX: PRIVI_ALL,
    },
}


def parse_resources(endpoint: str, method: str) -> tuple[str, str]:
    """Map (endpoint, method) -> (resource, required privilege)
    (reference: entity/user.go:194 ParseResources). GET needs ReadOnly,
    everything else WriteOnly — except /document/{search,query} which are
    reads that ride POST."""
    privilege = PRIVI_READ if method == "GET" else PRIVI_WRITE
    e = endpoint
    if e.startswith("/clean_lock"):
        # rides GET but MUTATES state (clears expired space-mutation
        # locks) — classify as a cluster write so a blanket ReadOnly
        # grant cannot reach the ops escape hatch
        return RESOURCE_CLUSTER, PRIVI_WRITE
    if e.startswith("/cluster") or e == "/" or e.startswith("/members"):
        return RESOURCE_CLUSTER, privilege
    if (e.startswith("/servers") or e.startswith("/register")
            or e.startswith("/routers") or e.startswith("/schedule")):
        return RESOURCE_SERVER, privilege
    if e.startswith("/partitions"):
        return RESOURCE_PARTITION, privilege
    if e.startswith("/dbs"):
        return (RESOURCE_SPACE if "/spaces" in e else RESOURCE_DB), privilege
    if e.startswith("/backup"):
        return RESOURCE_SPACE, privilege
    if e.startswith("/document"):
        if "query" in e or "search" in e:
            return RESOURCE_DOCUMENT, PRIVI_READ
        return RESOURCE_DOCUMENT, PRIVI_WRITE
    if e.startswith("/index"):
        return RESOURCE_INDEX, privilege
    if e.startswith("/alias"):
        return RESOURCE_ALIAS, privilege
    if e.startswith("/config"):
        return RESOURCE_CONFIG, privilege
    if e.startswith("/users") or e.startswith("/user"):
        return RESOURCE_USER, privilege
    if e.startswith("/roles") or e.startswith("/role"):
        return RESOURCE_ROLE, privilege
    return RESOURCE_ALL, privilege


def has_permission(role_name: str, privileges: dict[str, str],
                   endpoint: str, method: str) -> None:
    """Raise 403 unless the role's grants cover the endpoint (reference:
    entity/user.go:300 HasPermissionForResources — root bypasses; a grant
    matches when equal to the need or WriteRead)."""
    if role_name == ROOT_NAME:
        return
    resource, needed = parse_resources(endpoint, method)
    grant = privileges.get(resource)
    if grant is None:
        grant = privileges.get(RESOURCE_ALL)
        if grant is None:
            raise RpcError(
                403, f"role {role_name!r} has no privilege on {resource}"
            )
        # user/role management is admin surface: a blanket ResourceAll
        # grant below WriteRead must not cover it, or a WriteOnly data
        # user could POST /users a root-role account and escalate
        # (reference: user management is ClusterAdmin/root-only)
        if resource in (RESOURCE_USER, RESOURCE_ROLE) and grant != PRIVI_ALL:
            raise RpcError(
                403,
                f"role {role_name!r} ResourceAll grant {grant} does not "
                f"extend to {resource} (admin surface)",
            )
        # cluster-topology mutations (recover/fail-server/member ops) are
        # likewise admin surface: a blanket WriteOnly data grant must not
        # let a data writer force replica re-placement or erase failure
        # records (reference: ops routes are ClusterAdmin-gated)
        if needed != PRIVI_READ and resource in (
            RESOURCE_SERVER, RESOURCE_CLUSTER, RESOURCE_PARTITION
        ) and grant != PRIVI_ALL:
            raise RpcError(
                403,
                f"role {role_name!r} ResourceAll grant {grant} does not "
                f"extend to {resource} mutations (admin surface)",
            )
    if grant == needed or grant == PRIVI_ALL:
        return
    raise RpcError(
        403,
        f"role {role_name!r} privilege {grant} on {resource} does not "
        f"cover {needed} for {method} {endpoint}",
    )


def hash_password(password: str, salt: str | None = None) -> str:
    salt = salt or secrets.token_hex(8)
    digest = hashlib.sha256((salt + password).encode()).hexdigest()
    return f"{salt}${digest}"


def verify_password(password: str, stored: str) -> bool:
    salt, _digest = stored.split("$", 1)
    return secrets.compare_digest(hash_password(password, salt), stored)


def parse_basic_auth(headers) -> tuple[str, str]:
    """Extract (user, password) from an Authorization: Basic header."""
    header = headers.get("Authorization", "")
    if not header.startswith("Basic "):
        raise RpcError(401, "missing Basic auth")
    try:
        raw = base64.b64decode(header[6:]).decode()
        user, _, password = raw.partition(":")
    except Exception as e:
        raise RpcError(401, "malformed Basic auth") from e
    return user, password


class AuthService:
    """Master-side user/role registry over the metastore."""

    def __init__(self, store, root_password: str = "secret",
                 bootstrap: bool = True):
        self.store = store
        self._root_password = root_password
        if bootstrap:
            self.ensure_bootstrap()

    def ensure_bootstrap(self) -> None:
        """Write root user + builtin roles if missing. In multi-master
        mode this runs on the metadata leader only (mutations replicate
        through the log; a follower couldn't propose them)."""
        if self.store.get(f"/user/{ROOT_NAME}") is None:
            self.store.put(f"/user/{ROOT_NAME}", {
                "name": ROOT_NAME,
                "password": hash_password(self._root_password),
                "role": "root",
            })
        for name, privileges in BUILTIN_ROLES.items():
            if self.store.get(f"/role/{name}") is None:
                self.store.put(f"/role/{name}",
                               {"name": name, "privileges": privileges})

    def create_user(self, name: str, password: str, role: str) -> dict:
        if self.store.get(f"/user/{name}") is not None:
            raise RpcError(409, f"user {name} exists")
        if self.store.get(f"/role/{role}") is None:
            raise RpcError(404, f"role {role} not found")
        user = {"name": name, "password": hash_password(password),
                "role": role}
        self.store.put(f"/user/{name}", user)
        return {"name": name, "role": role}

    def update_user(self, name: str, password: str | None = None,
                    role: str | None = None) -> dict:
        """Change a user's password and/or role (reference: updateUser).
        Root's role is fixed; its password may rotate."""
        u = self.store.get(f"/user/{name}")
        if u is None:
            raise RpcError(404, f"user {name} not found")
        if role is not None:
            if name == ROOT_NAME:
                raise RpcError(400, "cannot change root's role")
            if self.store.get(f"/role/{role}") is None:
                raise RpcError(404, f"role {role} not found")
            u["role"] = role
        if password is not None:
            u["password"] = hash_password(password)
        self.store.put(f"/user/{name}", u)
        return {"name": name, "role": u["role"]}

    def update_role(self, name: str, privileges: dict[str, str]) -> dict:
        """Replace a role's privilege map (reference:
        changeRolePrivilege). Built-in roles are immutable."""
        if name in BUILTIN_ROLES:
            raise RpcError(400, f"built-in role {name!r} is immutable")
        if self.store.get(f"/role/{name}") is None:
            raise RpcError(404, f"role {name} not found")
        role = {"name": name, "privileges": privileges}
        self.store.put(f"/role/{name}", role)
        return role

    def delete_user(self, name: str) -> None:
        if name == ROOT_NAME:
            raise RpcError(400, "cannot delete root")
        if not self.store.delete(f"/user/{name}"):
            raise RpcError(404, f"user {name} not found")

    def create_role(self, name: str, privileges: dict[str, str]) -> dict:
        if self.store.get(f"/role/{name}") is not None:
            raise RpcError(409, f"role {name} exists")
        role = {"name": name, "privileges": privileges}
        self.store.put(f"/role/{name}", role)
        return role

    def check(self, user: str, password: str) -> dict:
        """Validate credentials; returns the user's role record."""
        u = self.store.get(f"/user/{user}")
        if u is None or not verify_password(password, u["password"]):
            raise RpcError(401, "bad credentials")
        role = self.store.get(f"/role/{u['role']}") or {"privileges": {}}
        return {"name": user, "role": u["role"],
                "privileges": role["privileges"]}

    def authorize(self, record: dict, endpoint: str, method: str) -> None:
        """Per-request privilege check on a record returned by check()."""
        has_permission(record.get("role", ""),
                       record.get("privileges") or {}, endpoint, method)

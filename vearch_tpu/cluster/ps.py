"""Partition server (data plane): hosts one Engine per partition.

TPU-native re-design of the reference's PS role (reference:
internal/ps/server.go:76 lifecycle + partition registry sync.Map;
handler_document.go:64 data RPC; handler_admin.go:90 admin RPC;
partition_service.go:154 create/recover). Raft replication slots in at
this layer in a later round (replica_num=1 paths are complete); the
handler surface already mirrors the reference's admin/data split.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import TableSchema
from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.entities import Partition
from vearch_tpu.cluster.rpc import JsonRpcServer, RpcError


class PSServer:
    def __init__(
        self,
        data_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        master_addr: str | None = None,
        heartbeat_interval: float = 2.0,
        max_concurrent_searches: int = 256,
        memory_limit_mb: int = 0,
        master_auth: tuple[str, str] | None = None,
        backup_roots: list[str] | None = None,
    ):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.engines: dict[int, Engine] = {}
        self.partitions: dict[int, Partition] = {}
        self._lock = threading.Lock()
        self.master_addr = master_addr
        # service credentials for master calls when the cluster runs with
        # auth (replication metadata reads would otherwise 401 silently)
        self.master_auth = master_auth
        self.node_id: int | None = None
        self.heartbeat_interval = heartbeat_interval
        self._stop = threading.Event()
        # concurrency gate (reference: RequestConcurrentController,
        # search/engine.h:197; rpcx request concurrency, ps/server.go:89)
        self._search_gate = threading.BoundedSemaphore(max_concurrent_searches)
        # 0 = unlimited (reference: resource-limit write guard,
        # store_writer.go:82-95 -> partition flips read-only)
        self.memory_limit_mb = memory_limit_mb
        # operator allowlist for backup/restore store roots: when set,
        # /ps/backup and /ps/restore refuse store_root paths outside it
        # (anyone reaching the PS port could otherwise read/write
        # arbitrary filesystem paths through the object store)
        self.backup_roots = (
            [os.path.abspath(r) for r in backup_roots] if backup_roots
            else None
        )
        self.replication_errors = 0  # surfaced in /ps/stats

        self.server = JsonRpcServer(host, port)
        s = self.server
        s.route("POST", "/ps/partition/create", self._h_create_partition)
        s.route("POST", "/ps/partition/delete", self._h_delete_partition)
        s.route("POST", "/ps/doc/upsert", self._h_upsert)
        s.route("POST", "/ps/doc/delete", self._h_delete)
        s.route("POST", "/ps/doc/get", self._h_get)
        s.route("POST", "/ps/doc/search", self._h_search)
        s.route("POST", "/ps/doc/query", self._h_query)
        s.route("POST", "/ps/index/build", self._h_build)
        s.route("POST", "/ps/index/rebuild", self._h_rebuild)
        s.route("POST", "/ps/flush", self._h_flush)
        s.route("POST", "/ps/engine/config", self._h_engine_config)
        s.route("POST", "/ps/backup", self._h_backup)
        s.route("POST", "/ps/restore", self._h_restore)
        s.route("GET", "/ps/stats", self._h_stats)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        self._recover_partitions()
        if self.master_addr:
            self._register()
            t = threading.Thread(target=self._heartbeat_loop, daemon=True)
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for eng in self.engines.values():
            eng.close()
        self.server.stop()

    @property
    def addr(self) -> str:
        return self.server.addr

    def _register(self) -> None:
        """Register with the master, retrying forever (reference:
        ps/server.go:228 lease-backed registration)."""
        while not self._stop.is_set():
            try:
                data = rpc.call(
                    self.master_addr, "POST", "/register",
                    {"rpc_addr": self.addr, "node_id": self.node_id},
                    auth=self.master_auth,
                )
                self.node_id = data["node_id"]
                return
            except RpcError:
                time.sleep(0.5)

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.heartbeat_interval)
            try:
                rpc.call(
                    self.master_addr, "POST", "/register",
                    {"rpc_addr": self.addr, "node_id": self.node_id},
                    auth=self.master_auth,
                )
            except RpcError:
                pass

    def _recover_partitions(self) -> None:
        """Reload engines dumped under data_dir (reference:
        partition_service.go:275 recoverPartitions)."""
        for name in os.listdir(self.data_dir):
            p = os.path.join(self.data_dir, name)
            if name.startswith("partition_") and os.path.isdir(p):
                pid = int(name.split("_")[1])
                try:
                    eng = Engine.open(p)
                    eng.start_refresh_loop()
                    self.engines[pid] = eng
                except Exception:
                    continue

    # -- handlers ------------------------------------------------------------

    def _engine(self, pid: int) -> Engine:
        eng = self.engines.get(int(pid))
        if eng is None:
            raise RpcError(404, f"partition {pid} not on this node")
        return eng

    def _h_create_partition(self, body: dict, _parts) -> dict:
        pid = int(body["partition"]["id"])
        with self._lock:
            if pid in self.engines:
                raise RpcError(409, f"partition {pid} already exists")
            schema = TableSchema.from_dict(body["schema"])
            data_dir = os.path.join(self.data_dir, f"partition_{pid}")
            eng = Engine(schema, data_dir=data_dir)
            eng.start_refresh_loop()
            self.engines[pid] = eng
            self.partitions[pid] = Partition.from_dict(body["partition"])
        return {"partition_id": pid}

    def _h_delete_partition(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        with self._lock:
            self.engines.pop(pid, None)
            self.partitions.pop(pid, None)
        import shutil

        shutil.rmtree(
            os.path.join(self.data_dir, f"partition_{pid}"), ignore_errors=True
        )
        return {"partition_id": pid}

    # -- replication v0 (primary-backup) -------------------------------------
    # The leader applies a write locally, then forwards it synchronously to
    # every follower replica before acking (the reference replicates through
    # a raft log, raftstore/store_writer.go:77; a log-structured raft sits
    # here in a later round — the fan-out seam is identical).

    def _peer_addrs(self, pid: int) -> list[str]:
        part = self.partitions.get(pid)
        if part is None or self.master_addr is None:
            return []
        if part.leader != self.node_id:
            return []
        peers = [r for r in part.replicas if r != self.node_id]
        if not peers:
            return []
        try:
            servers = rpc.call(self.master_addr, "GET", "/servers",
                               auth=self.master_auth)["servers"]
        except RpcError:
            return []
        by_id = {s["node_id"]: s["rpc_addr"] for s in servers}
        return [by_id[p] for p in peers if p in by_id]

    def _replicate(self, pid: int, path: str, body: dict) -> None:
        import sys

        peers = self._peer_addrs(pid)
        part = self.partitions.get(pid)
        if not peers and part is not None and part.leader == self.node_id \
                and len(part.replicas) > 1:
            # replicas exist but none reachable/resolvable: never silent —
            # this exact silence hid an auth misconfiguration once
            self.replication_errors += 1
            if self.replication_errors == 1:
                print(f"[ps {self.node_id}] WARNING: partition {pid} has "
                      f"replicas {part.replicas} but peer resolution "
                      f"returned none; followers are going stale",
                      file=sys.stderr, flush=True)
        for addr in peers:
            try:
                rpc.call(addr, "POST", path, {**body, "replicated": True})
            except RpcError as e:
                self.replication_errors += 1
                print(f"[ps {self.node_id}] replication to {addr} failed: "
                      f"{e.msg[:80]}", file=sys.stderr, flush=True)

    def _h_upsert(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        eng = self._engine(pid)
        if self.memory_limit_mb:
            used = sum(
                e.memory_usage_bytes() for e in self.engines.values()
            ) >> 20
            if used >= self.memory_limit_mb:
                raise RpcError(
                    403,
                    f"resource_exhausted: {used}MB >= "
                    f"limit {self.memory_limit_mb}MB (writes rejected, "
                    f"reads still served)",
                )
        keys = eng.upsert(body["documents"])
        if not body.get("replicated"):
            self._replicate(pid, "/ps/doc/upsert",
                            {"partition_id": pid,
                             "documents": body["documents"]})
        return {"keys": keys, "count": len(keys)}

    def _h_delete(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        eng = self._engine(pid)
        if body.get("keys"):
            deleted = eng.delete(body["keys"])
            if not body.get("replicated"):
                self._replicate(pid, "/ps/doc/delete",
                                {"partition_id": pid, "keys": body["keys"]})
            return {"deleted": deleted}
        # delete-by-filter (reference: /document/delete with filters).
        # Drain in batches until no matches remain — a single capped
        # query would silently delete only the first 10k of a larger
        # match set (r1 VERDICT weak-8). An explicit client `limit`
        # still bounds the total.
        limit = int(body["limit"]) if body.get("limit") is not None else None
        batch = 10_000
        deleted = 0
        while True:
            want = batch if limit is None else min(batch, limit - deleted)
            if want <= 0:
                break
            docs = eng.query(body.get("filters"), limit=want,
                             include_fields=[], order_by_key=False)
            if not docs:
                break
            keys = [d["_id"] for d in docs]
            deleted += eng.delete(keys)
            if not body.get("replicated"):
                self._replicate(pid, "/ps/doc/delete",
                                {"partition_id": pid, "keys": keys})
            if len(docs) < want:
                break
        return {"deleted": deleted}

    def _h_get(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        return {"documents": eng.get(body["keys"], body.get("fields"),
                                      bool(body.get("vector_value", False)))}

    def _h_search(self, body: dict, _parts) -> dict:
        import numpy as np

        eng = self._engine(body["partition_id"])
        vectors = {
            name: np.asarray(v, dtype=np.float32)
            for name, v in body["vectors"].items()
        }
        if not self._search_gate.acquire(timeout=30.0):
            raise RpcError(429, "partition server search queue full")
        try:
            return self._do_search(eng, body, vectors)
        finally:
            self._search_gate.release()

    def _do_search(self, eng, body, vectors) -> dict:
        trace = {} if body.get("trace") else None
        req = SearchRequest(
            vectors=vectors,
            k=int(body.get("k", 10)),
            filters=body.get("filters"),
            include_fields=body.get("include_fields"),
            brute_force=bool(body.get("brute_force", False)),
            field_weights=body.get("field_weights") or {},
            index_params=body.get("index_params") or {},
            trace=trace,
        )
        results = eng.search(req)
        metric = eng.indexes[next(iter(vectors))].metric.value
        out = {
            "metric": metric,
            "results": [
                [
                    {"_id": it.key, "_score": it.score, **it.fields}
                    for it in r.items
                ]
                for r in results
            ],
        }
        if trace is not None:
            out["timing"] = trace
        return out

    def _h_query(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        vv = bool(body.get("vector_value", False))
        if body.get("document_ids"):
            docs = eng.get(body["document_ids"], body.get("fields"), vv)
        else:
            docs = eng.query(
                body.get("filters"),
                limit=int(body.get("limit", 50)),
                offset=int(body.get("offset", 0)),
                include_fields=body.get("fields"),
                vector_value=vv,
            )
        return {"documents": docs}

    def _h_build(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        eng.build_index()
        return {"status": int(eng.status)}

    def _h_rebuild(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        eng.rebuild_index()
        return {"status": int(eng.status)}

    def _h_flush(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        eng.dump()
        return {"doc_count": eng.doc_count}

    def _h_engine_config(self, body: dict, _parts) -> dict:
        cfg = body.get("config") or {}
        if "memory_limit_mb" in cfg:
            self.memory_limit_mb = int(cfg["memory_limit_mb"])
        eng = self._engine(body["partition_id"])
        return eng.apply_config(cfg)

    # -- backup/restore (reference: ps/backup/ps_backup_service.go:77
    #    PSShardManager — shard dump streamed to object storage) -------------

    def _check_backup_root(self, store_root: str) -> None:
        from vearch_tpu.cluster.objectstore import is_within

        if self.backup_roots is None:
            return
        if any(is_within(allowed, store_root)
               for allowed in self.backup_roots):
            return
        raise RpcError(403, f"store_root {store_root!r} not in the "
                            f"operator backup_roots allowlist")

    def _h_backup(self, body: dict, _parts) -> dict:
        import tempfile

        from vearch_tpu.cluster.objectstore import LocalObjectStore

        pid = int(body["partition_id"])
        eng = self._engine(pid)
        self._check_backup_root(body["store_root"])
        store = LocalObjectStore(body["store_root"])
        with tempfile.TemporaryDirectory() as tmp:
            eng.dump(tmp)
            n = store.put_tree(body["key_prefix"], tmp)
        return {"partition_id": pid, "files": n}

    def _h_restore(self, body: dict, _parts) -> dict:
        import shutil

        from vearch_tpu.cluster.objectstore import LocalObjectStore

        pid = int(body["partition_id"])
        eng = self._engine(pid)  # partition must exist (space created first)
        self._check_backup_root(body["store_root"])
        store = LocalObjectStore(body["store_root"])
        data_dir = os.path.join(self.data_dir, f"partition_{pid}")
        shutil.rmtree(data_dir, ignore_errors=True)
        n = store.get_tree(body["key_prefix"], data_dir)
        eng.close()
        restored = Engine.open(data_dir)
        restored.start_refresh_loop()
        with self._lock:
            self.engines[pid] = restored
        return {"partition_id": pid, "files": n,
                "doc_count": restored.doc_count}

    def _h_stats(self, _body, _parts) -> dict:
        return {
            "node_id": self.node_id,
            "replication_errors": self.replication_errors,
            "partitions": {
                str(pid): {
                    "doc_count": eng.doc_count,
                    "status": int(eng.status),
                    "memory_bytes": eng.memory_usage_bytes(),
                }
                for pid, eng in self.engines.items()
            },
        }

"""Partition server (data plane): hosts one Engine + RaftNode per partition.

TPU-native re-design of the reference's PS role (reference:
internal/ps/server.go:76 lifecycle + partition registry;
handler_document.go:64 data RPC; handler_admin.go:90 admin RPC;
partition_service.go:154 create/recover). Every write flows through a
per-partition replicated log (cluster/raft.py — the analogue of
raftstore/store_writer.go:77): WAL fsync + quorum ack before the client
ack, follower apply from the log, snapshot catch-up for laggards. A
periodic flush job checkpoints the engine with its applied index and
truncates the log behind it (reference: store_raft_job.go:97,40).
"""

from __future__ import annotations

import io
import json
import os
import shutil
import tarfile
import threading
import time

import numpy as np
from collections import deque
from typing import Any

from vearch_tpu.engine.engine import Engine, SearchRequest
from vearch_tpu.engine.types import DataType, TableSchema
from vearch_tpu.cluster import rpc
from vearch_tpu.cluster.entities import Partition
from vearch_tpu.cluster.metrics import (
    SIZE_BUCKETS,
    internal_error,
    register_tracer_metrics,
)
from vearch_tpu.cluster.raft import RaftNode
from vearch_tpu.obs import accounting
from vearch_tpu.ops import perf_model
from vearch_tpu.cluster.rpc import (
    ERR_REQUEST_KILLED,
    JsonRpcServer,
    RpcError,
)
from vearch_tpu.tools import lockcheck
from vearch_tpu.utils import log

_log = log.get("ps")

# log entries retained behind the flushed/applied horizon so a briefly
# lagging follower catches up by replay instead of full snapshot
# (reference: raft_truncate_count)
WAL_KEEP_ENTRIES = 10_000

# split copy batch size: bounds both the per-forward RPC payload and
# how long the mirror queue waits between drain opportunities
SPLIT_COPY_BATCH = 256


class _SplitAborted(Exception):
    """Internal control flow for the split worker: the job must end in
    status=error (master garbage-collects the children and may retry)."""


def _profile_from_timing(timing: dict) -> dict:
    """Shape the engine's flat trace dict into the structured
    profile=true breakdown one partition contributes (the
    Elasticsearch-profile / EXPLAIN analogue; schema documented in
    docs/OBSERVABILITY.md). Phase keys lose their `_ms` suffix; per-
    dispatch timings and the perf-model prediction are grouped under
    `dispatches` so measured-vs-documented drift reads off directly."""
    phases = {
        k[: -len("_ms")]: v for k, v in timing.items()
        if k.endswith("_ms") and not k.startswith("dispatch_")
    }
    per_dispatch = {
        k[len("dispatch_"): -len("_ms")]: v for k, v in timing.items()
        if k.startswith("dispatch_") and k.endswith("_ms")
    }
    out: dict = {
        "phases": phases,
        "dispatches": {
            "tags": timing.get("dispatches", []),
            "count": timing.get("dispatch_count", 0),
            "path": timing.get("perf_path"),
            "predicted": timing.get("predicted_dispatches"),
            "predicted_scan_bytes": timing.get("predicted_scan_bytes"),
            "per_dispatch_ms": per_dispatch,
        },
    }
    if "doc_count" in timing:
        out["doc_count"] = timing["doc_count"]
    if "micro_batch_rows" in timing:
        out["micro_batch_rows"] = timing["micro_batch_rows"]
    if "mesh" in timing:
        out["mesh"] = timing["mesh"]
    return out


def _write_profile_from_timing(timing: dict) -> dict:
    """Write-side profile=true breakdown: the raft proposal's phase
    windows (propose-wait / wal append+fsync / commit-wait / apply),
    shaped like the search profile so the router merges both the same
    way (schema in docs/OBSERVABILITY.md)."""
    out: dict = {
        "phases": {
            k[: -len("_ms")]: v for k, v in timing.items()
            if k.endswith("_ms")
        },
    }
    for k in ("doc_count", "entries"):
        if k in timing:
            out[k] = timing[k]
    return out


@lockcheck.guarded
class PSServer:
    # lock discipline (lint VL201 + runtime lockcheck): the partition
    # registries mutate under _lock; the in-flight request registry and
    # its kill counter under _inflight_lock; async backup jobs under
    # _backup_jobs_lock; the small hot-path caches/counters under a
    # dedicated _stats_lock so stats updates never contend with
    # partition registry operations.
    _guarded_by = {
        "engines": "_lock",
        "partitions": "_lock",
        "raft_nodes": "_lock",
        "_flushed": "_lock",
        "_flush_locks": "_lock",
        "_inflight": "_inflight_lock",
        "killed_requests": "_inflight_lock",
        "_backup_jobs": "_backup_jobs_lock",
        "_peer_cache": "_stats_lock",
        "_mem_cache": "_stats_lock",
        "_mem_dirty": "_stats_lock",
        "replication_errors": "_stats_lock",
        "slow_routed": "_stats_lock",
        "_search_ewma": "_stats_lock",
        "_op_counts": "_stats_lock",
        "_op_inflight": "_stats_lock",
        "_op_waiting": "_stats_lock",
        "_split_jobs": "_split_lock",
    }

    def __init__(
        self,
        data_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        master_addr: str | None = None,
        heartbeat_interval: float = 2.0,
        max_concurrent_searches: int = 256,
        memory_limit_mb: int = 0,
        master_auth: tuple[str, str] | None = None,
        backup_roots: list[str] | None = None,
        backup_endpoints: list[str] | None = None,
        flush_interval: float = 5.0,
        raft_tick: float = 0.4,
        labels: dict[str, str] | None = None,
        trace_collector: str | None = None,
        search_cache_entries: int = 256,
        device_sample_interval: float = 5.0,
        hbm_drift_tolerance: float = 0.5,
        hbm_drift_slack_mb: int = 64,
        admission_queue_limit: int = 0,
    ):
        from vearch_tpu.utils import apply_jax_platform_env

        apply_jax_platform_env()  # before any engine touches jax
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.engines: dict[int, Engine] = {}
        self.partitions: dict[int, Partition] = {}
        self.raft_nodes: dict[int, RaftNode] = {}
        self._flushed: dict[int, int] = {}  # pid -> applied idx at last flush
        # one checkpoint at a time per partition: concurrent flushes
        # (flush loop + /ps/flush + snapshot sends) would interleave
        # writes to the same snapshot files
        self._flush_locks: dict[int, Any] = {}
        self._lock = lockcheck.make_lock("ps._lock")
        self.master_addr = master_addr
        # service credentials for master calls when the cluster runs with
        # auth (replication metadata reads would otherwise 401 silently)
        self.master_auth = master_auth
        self.node_id: int | None = None
        self.heartbeat_interval = heartbeat_interval
        self.flush_interval = flush_interval
        self.raft_tick = raft_tick
        self._stop = threading.Event()
        # concurrency gate (reference: RequestConcurrentController,
        # search/engine.h:197; rpcx request concurrency, ps/server.go:89)
        self._search_gate = threading.BoundedSemaphore(max_concurrent_searches)
        self.max_concurrent_searches = max_concurrent_searches
        # 0 = unlimited (reference: resource-limit write guard,
        # store_writer.go:82-95 -> partition flips read-only)
        self.memory_limit_mb = memory_limit_mb
        # operator allowlist for backup/restore store roots: when set,
        # /ps/backup and /ps/restore refuse store_root paths outside it
        # (anyone reaching the PS port could otherwise read/write
        # arbitrary filesystem paths through the object store)
        self.backup_roots = (
            [os.path.abspath(r) for r in backup_roots] if backup_roots
            else None
        )
        # s3 counterpart of backup_roots: allowed endpoint hosts. When
        # EITHER allowlist is configured, the other destination type is
        # default-denied — a confined operator setup must not be
        # escapable by just switching store types (exfiltration/SSRF)
        self.backup_endpoints = backup_endpoints
        # small hot-path counters/caches (guard map above) — their own
        # lock so stats writes never queue behind registry operations
        self._stats_lock = lockcheck.make_lock("ps._stats_lock")
        self.replication_errors = 0  # surfaced in /ps/stats
        # topology labels (host/rack/zone) for placement anti-affinity
        self.labels = dict(labels or {})
        self._peer_cache: tuple[float, dict[int, str]] = (0.0, {})
        # in-flight request registry (reference: handler_document.go:96
        # Rqueue registration for kill + ps/schedule_job.go:252 slow-
        # request killer). 0 disables the automatic killer.
        self._inflight: dict[str, dict] = {}
        self._inflight_lock = lockcheck.make_lock("ps._inflight_lock")
        # async shard-backup jobs (reference: PSShardManager state)
        self._backup_jobs: dict[str, dict] = {}
        self._backup_jobs_lock = lockcheck.make_lock(
            "ps._backup_jobs_lock")
        # online partition-split jobs (elastic data plane): pid -> job
        # dict owned by one named worker thread; write handlers enqueue
        # mirror entries under the same lock so the lock never nests
        # with the partition registry's
        self._split_jobs: dict[int, dict] = {}
        self._split_lock = lockcheck.make_lock("ps._split_lock")
        self._split_cv = threading.Condition(self._split_lock)
        # per-partition cumulative search/write counters riding the
        # heartbeat — the master's rebalance planner scores hotness
        # from the deltas
        self._op_counts: dict[int, dict[str, int]] = {}
        # admission observability for ROADMAP item 5: requests waiting
        # on a gate vs executing, per op. Both render as gauges from
        # the first scrape (fixed op label set) — cardinality-soak safe.
        self._op_waiting: dict[str, int] = {"search": 0, "write": 0}
        self._op_inflight: dict[str, int] = {"search": 0, "write": 0}
        self.slow_request_ms = 0
        self.killed_requests = 0
        # per-request deadline default (ms); a search may override via
        # its own deadline_ms option. 0 disables. Arms RequestContext so
        # expiry aborts between dispatches (reference: the timeout the
        # reference's rpcx layer enforces per handler).
        self.request_deadline_ms = 0
        # cached cross-engine memory accounting: _h_upsert used to
        # re-sum memory_usage_bytes() over every engine per request —
        # O(partitions) host walks on the hot write path. Applies mark
        # the cache dirty; a dirty read refreshes at most every
        # _mem_min_interval seconds, a clean one every _mem_max_age.
        self._mem_cache: tuple[float, int] = (0.0, 0)
        self._mem_dirty = True
        self._mem_min_interval = 0.02
        self._mem_max_age = 5.0
        # slow-query isolation (reference: dedicated slow-search channel
        # pool, ps/server.go:95 + engine slow_search_time marking): each
        # partition keeps an EWMA of its search latency; partitions
        # whose history exceeds slow_route_ms are routed through a
        # small separate semaphore so a hot/expensive space cannot
        # occupy every fast-path slot. 0 disables routing.
        self.slow_route_ms = 0
        self._slow_gate = threading.BoundedSemaphore(
            max(1, max_concurrent_searches // 4)
        )
        self._search_ewma: dict[int, float] = {}  # pid -> ms
        self.slow_routed = 0
        # admission control (tail-latency tentpole): bounded wait queue
        # in front of the search gates — when more than
        # admission_queue_limit requests are already waiting, new
        # arrivals shed with 429 + Retry-After instead of queueing past
        # the point anyone will wait. 0 disables (default). Runtime-
        # tunable via /ps/engine/config {"admission_queue_limit": n}.
        from vearch_tpu.cluster.admission import AdmissionController

        self._admission = AdmissionController(admission_queue_limit)
        # fault injection for tail-latency tests/bench: every search
        # sleeps this long (killable, in deadline-check chunks) before
        # touching the engine. Set via /ps/engine/config.
        self.debug_search_delay_ms = 0
        # PS-tier result cache + coalescing (perf tentpole: the
        # cheapest dispatch is the one never issued). Keys embed
        # (partition, canonical query, raft apply index, engine data
        # version), so any applied write makes every prior entry for
        # that partition unreachable — exact invalidation without a
        # flush pass; superseded keys simply age out of the LRU.
        # SingleFlight collapses N concurrent identical searches into
        # one engine dispatch set. Runtime-tunable via /ps/engine/
        # config {"search_cache_entries": n}; 0 disables.
        from vearch_tpu.cluster.querycache import (
            SingleFlight, VersionedLRUCache,
        )

        self.search_cache = VersionedLRUCache(
            max_entries=search_cache_entries)
        self._search_flight = SingleFlight()

        from vearch_tpu.cluster.tracing import NULL_SPAN, SlowLog, Tracer

        # spans join the router's trace via the _trace_ctx envelope
        # (reference: PS extracts span context from rpcx metadata,
        # ps/handler_document.go:123-126)
        self.tracer = Tracer("ps", collector_endpoint=trace_collector)
        # slow/killed request ring at GET /debug/slowlog; threshold via
        # /ps/engine/config {"slow_log_ms": ...}
        self.slowlog = SlowLog()

        # runtime truth layer (obs tentpole): compile-audit flight
        # recorder (process-global, like the jit cache it watches),
        # per-(partition, op) latency quantile sketches, and the
        # device-runtime sampler measuring live HBM against the
        # footprint model
        from vearch_tpu.obs import flight_recorder as _flightrec
        from vearch_tpu.obs.quantiles import QuantileRegistry, _qlabel
        from vearch_tpu.obs.sampler import DeviceSampler

        self.flight_recorder = _flightrec.install()
        self.latency_quantiles = QuantileRegistry(name="ps.quantiles")
        self.device_sampler = DeviceSampler(
            self._model_device_bytes,
            interval_s=device_sample_interval,
            drift_tolerance=hbm_drift_tolerance,
            drift_slack_bytes=int(hbm_drift_slack_mb) << 20,
        )
        # search-quality truth layer (docs/QUALITY.md): shadow exact-
        # rerank recall sampling + index-health drift gauges. Per-node,
        # not process-global — in-process multi-node tests host the
        # same partition id on several PSServers.
        from vearch_tpu.obs.quality import QualityMonitor

        self._quality = QualityMonitor(
            get_engines=lambda: self.engines,
            pid_space=self._space_key,
            admission=self._admission,
        )

        self.server = JsonRpcServer(host, port)
        self.server.tracer = self.tracer
        s = self.server
        s.route("POST", "/ps/partition/create", self._h_create_partition)
        s.route("POST", "/ps/partition/delete", self._h_delete_partition)
        s.route("POST", "/ps/doc/upsert", self._h_upsert)
        s.route("POST", "/ps/doc/delete", self._h_delete)
        s.route("POST", "/ps/doc/get", self._h_get)
        s.route("POST", "/ps/doc/search", self._h_search)
        s.route("POST", "/ps/doc/query", self._h_query)
        s.route("POST", "/ps/index/build", self._h_build)
        s.route("POST", "/ps/field_index", self._h_field_index)
        s.route("POST", "/ps/schema/field", self._h_schema_field)
        s.route("POST", "/ps/index/rebuild", self._h_rebuild)
        s.route("POST", "/ps/flush", self._h_flush)
        s.route("POST", "/ps/engine/config", self._h_engine_config)
        s.route("POST", "/ps/backup", self._h_backup)
        s.route("GET", "/ps/backup/progress", self._h_backup_progress)
        s.route("POST", "/ps/restore", self._h_restore)
        s.route("GET", "/ps/stats", self._h_stats)
        s.route("POST", "/ps/kill", self._h_kill)
        s.route("GET", "/ps/requests", self._h_requests)
        s.route("GET", "/ps/jobs", self._h_jobs)
        s.route("GET", "/debug/slowlog", self._h_slowlog)
        # compile-audit flight recorder: post-warmup serving compiles
        s.route("GET", "/debug/compiles", self._h_compiles)
        s.route("POST", "/debug/compiles/reset", self._h_compiles_reset)
        # online partition split (elastic data plane): the master drives
        # start -> poll progress -> finish(commit|abort) on the parent's
        # leader; the double-write mirror lives here
        s.route("POST", "/ps/partition/split/start", self._h_split_start)
        s.route("GET", "/ps/partition/split/progress",
                self._h_split_progress)
        s.route("POST", "/ps/partition/split/finish", self._h_split_finish)
        # raft transport (reference: raftstore/server.go heartbeat +
        # replicate ports; here routes on the one RPC server)
        s.route("POST", "/ps/raft/append", self._h_raft_append)
        s.route("POST", "/ps/raft/fence", self._h_raft_fence)
        s.route("POST", "/ps/raft/lead", self._h_raft_lead)
        s.route("POST", "/ps/raft/members", self._h_raft_members)
        s.route("POST", "/ps/raft/snapshot", self._h_raft_snapshot)
        s.route("GET", "/ps/raft/state", self._h_raft_state)

        # per-partition gauges on this node's /metrics (reference:
        # monitor_service.go partition gauges; VERDICT r2 missing #2)
        def _gauges(field: str):
            def fn():
                return {
                    (str(pid),): float(st[field])
                    for pid, st in self._partition_stats().items()
                }
            return fn

        m = s.metrics
        m.callback_gauge("vearch_ps_partition_docs",
                         "docs per partition on this node",
                         ("partition",), _gauges("doc_count"))
        m.callback_gauge("vearch_ps_partition_size_bytes",
                         "engine memory per partition on this node",
                         ("partition",), _gauges("size_bytes"))
        m.callback_gauge("vearch_ps_partition_status",
                         "engine index status per partition",
                         ("partition",), _gauges("status"))
        m.callback_gauge("vearch_ps_partition_leader",
                         "1 when this node leads the partition",
                         ("partition",), _gauges("leader"))
        m.callback_gauge("vearch_ps_partitions",
                         "partitions hosted on this node", (),
                         lambda: {(): float(len(self.engines))})
        m.callback_gauge("vearch_ps_memory_used_bytes",
                         "engine memory across all partitions "
                         "(cached accounting, feeds the write limit)",
                         (),
                         lambda: {(): float(self.memory_used_bytes())})

        def _mesh_devices():
            # devices the mesh data plane spans, per partition; 0 when
            # the partition serves single-device (mesh_serving off, one
            # visible device, or a disk-store field)
            out = {}
            for pid, eng in list(self.engines.items()):
                try:
                    info = eng.mesh_info()
                except Exception:
                    info = None
                out[(str(pid),)] = float(
                    (info or {}).get("devices", 0)
                )
            return out

        m.callback_gauge("vearch_engine_mesh_devices",
                         "devices the mesh serving data plane spans "
                         "per partition (0 = single-device path)",
                         ("partition",), _mesh_devices)

        # write path (tentpole: ingest observability symmetric with the
        # read path) — throughput counters per partition, kill counters
        # by reason, WAL durability histograms fed by the Wal observer
        self._write_docs_total = m.counter(
            "vearch_ps_write_docs_total",
            "documents written per partition (op: upsert/delete)",
            ("partition", "op"))
        self._killed_total = m.counter(
            "vearch_requests_killed_total",
            "in-flight requests aborted, by reason "
            "(deadline/slow/operator) and tenant space",
            ("reason", "space"))
        self._shed_total = m.counter(
            "vearch_ps_admission_shed_total",
            "requests shed (429) by admission control before any "
            "device work, per op and tenant space",
            ("op", "space"))
        # render from 1st scrape; no tenant has been admitted yet
        self._shed_total.inc(  # lint: allow[space-attr] zero-fill render
            "search", accounting.OTHER_LABEL, by=0.0)

        # -- per-tenant cost accounting (docs/ACCOUNTING.md) -----------
        # The process-global accountant hooks the dispatch + H2D
        # ledgers; these callback metrics render its meters under the
        # fixed top-K + "other" label policy, so series stay bounded no
        # matter how many spaces this node hosts. Exact per-space
        # numbers ride /ps/stats and the heartbeat usage block.
        self._accountant = accounting.install()

        def _usage(meter: str, scale: float = 1.0):
            return lambda: self._accountant.labelled(meter, scale)

        m.callback_counter("vearch_space_requests_total",
                           "search RPCs billed per space (won hedges "
                           "bill once)", ("space",), _usage("requests"))
        m.callback_counter("vearch_space_dispatches_total",
                           "device dispatches attributed per space "
                           "(reconciles with the dispatch ledger)",
                           ("space",), _usage("dispatches"))
        m.callback_counter("vearch_space_h2d_bytes_total",
                           "host->device bytes attributed per space "
                           "(reconciles with vearch_ps_h2d_bytes_total)",
                           ("space",), _usage("h2d_bytes"))
        m.callback_counter("vearch_space_device_ms_total",
                           "engine device wall-time per space, ms "
                           "(co-batched buckets split by row share)",
                           ("space",), _usage("device_us", 1e-3))
        m.callback_counter("vearch_space_queue_wait_ms_total",
                           "admission-gate + scheduler queue wait per "
                           "space, ms", ("space",),
                           _usage("queue_wait_us", 1e-3))
        m.callback_counter("vearch_space_cache_hits_total",
                           "result-cache hits per space (zero device "
                           "cost)", ("space",), _usage("cache_hits"))
        m.callback_gauge("vearch_space_hbm_bytes",
                         "modelled device-memory residency per space "
                         "on this node", ("space",),
                         self._space_hbm_labelled)
        self._wal_fsync_hist = m.histogram(
            "vearch_wal_fsync_latency_seconds",
            "WAL fsync wall time per append batch",
            ("partition",))
        self._wal_batch_hist = m.histogram(
            "vearch_wal_append_batch_entries",
            "log entries per WAL append batch",
            ("partition",), buckets=SIZE_BUCKETS)

        # index-build jobs (tentpole: background-job telemetry)
        self._build_hist = m.histogram(
            "vearch_index_build_duration_seconds",
            "index build wall time (op: build/rebuild)",
            ("partition", "op"),
            buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))

        def _build_progress():
            # one series per hosted partition regardless of job state:
            # 0.0 before any build, fraction while running, 1.0 done —
            # a mid-soak build must not mint a new series
            out = {}
            for pid, eng in list(self.engines.items()):
                job = getattr(eng, "build_job", None)
                if job is None:
                    out[(str(pid),)] = 0.0
                else:
                    total = max(int(job.get("docs_total") or 0), 1)
                    frac = float(job.get("docs_done", 0)) / total
                    if job.get("status") in ("done", "error"):
                        frac = 1.0
                    out[(str(pid),)] = min(frac, 1.0)
            return out

        m.callback_gauge("vearch_index_build_progress",
                         "docs processed / total for the current or "
                         "last index build",
                         ("partition",), _build_progress)

        # split-job progress gauges: one series per hosted partition
        # with 0.0 when idle (same cardinality discipline as the build
        # gauge — a split starting mid-soak must not mint a new series)
        def _split_progress():
            with self._split_lock:
                jobs = {pid: (job.get("docs_done", 0),
                              job.get("docs_total", 0),
                              job.get("status"))
                        for pid, job in self._split_jobs.items()}
            out = {}
            for pid in list(self.engines):
                done, total, status = jobs.get(pid, (0, 0, None))
                if status in ("done", "error"):
                    out[(str(pid),)] = 1.0
                else:
                    out[(str(pid),)] = min(
                        float(done) / max(int(total or 0), 1), 1.0)
            return out

        def _split_queue():
            with self._split_lock:
                depth = {pid: len(job["_queue"])
                         for pid, job in self._split_jobs.items()
                         if job.get("status") == "running"}
            return {(str(pid),): float(depth.get(pid, 0))
                    for pid in list(self.engines)}

        m.callback_gauge("vearch_ps_split_progress",
                         "copied docs / total for the current or last "
                         "partition split on this node",
                         ("partition",), _split_progress)
        m.callback_gauge("vearch_ps_split_mirror_queue",
                         "pending double-write mirror entries for the "
                         "active partition split",
                         ("partition",), _split_queue)

        # -- search-quality truth layer (docs/QUALITY.md) --------------
        # Recall/RBO render under the accountant's top-K + "other" space
        # label policy and the fixed RECALL_K_TIERS depth grid; health
        # gauges are one series per hosted partition with 0.0 until the
        # first health pass — the cardinality soak must see no series
        # growth as sampling warms up mid-soak. Exact per-space numbers
        # ride /ps/stats; these series exist for alerting.
        from vearch_tpu.ops.perf_model import RECALL_K_TIERS

        def _quality_space_labels() -> set[str]:
            labels = {
                self._accountant.label(self._space_key(pid))
                for pid in list(self.engines)
            }
            labels.add(accounting.OTHER_LABEL)
            return labels

        def _recall_gauge():
            snap = self._quality.recall_snapshot()["spaces"]
            out = {(str(kt), lbl): 0.0
                   for kt in RECALL_K_TIERS
                   for lbl in _quality_space_labels()}
            for space, sp in snap.items():
                lbl = self._accountant.label(space)
                for kt, rec in (sp.get("recall") or {}).items():
                    if rec.get("estimate") is not None:
                        out[(str(kt), lbl)] = float(rec["estimate"])
            return out

        def _rbo_gauge():
            snap = self._quality.recall_snapshot()["spaces"]
            out = {(lbl,): 0.0 for lbl in _quality_space_labels()}
            for space, sp in snap.items():
                if sp.get("rbo") is not None:
                    out[(self._accountant.label(space),)] = float(sp["rbo"])
            return out

        def _breach_gauge():
            hit = {self._accountant.label(s)
                   for s in self._quality.breach_spaces()}
            return {(lbl,): (1.0 if lbl in hit else 0.0)
                    for lbl in _quality_space_labels()}

        m.callback_gauge("vearch_ps_search_recall",
                         "shadow-sampled recall@k vs the exact FLAT "
                         "path, decayed estimate (0 until sampled)",
                         ("k", "space"), _recall_gauge)
        m.callback_gauge("vearch_ps_search_rbo",
                         "rank-biased overlap of served vs exact "
                         "ordering, decayed (0 until sampled)",
                         ("space",), _rbo_gauge)
        m.callback_gauge("vearch_ps_search_recall_floor_breach",
                         "1 while the Wilson-upper recall bound sits "
                         "under the space's recall floor",
                         ("space",), _breach_gauge)
        m.callback_counter("vearch_ps_quality_shadow_total",
                           "shadow recall-sampling pipeline events "
                           "(sampled/executed/shed/stale/dropped/error)",
                           ("event",),
                           lambda: {(e,): float(n) for e, n in
                                    self._quality.counters().items()})

        # progressive-refinement serving: fixed label topology straight
        # from the ops-layer counters (ops/binary_scan.py), zero-filled
        # from first scrape — path/stage sets are module constants, so
        # the series count is flat regardless of traffic
        from vearch_tpu.ops import binary_scan as _binary_scan

        m.callback_counter("vearch_ps_refine_searches_total",
                           "three-stage (binary->int8->exact) searches "
                           "served, by serving path",
                           ("path",),
                           lambda: {(p,): float(n) for p, n in
                                    _binary_scan.refine_search_counts()
                                    .items()})
        m.callback_counter("vearch_ps_refine_stage_rows_total",
                           "candidate rows scored per refinement stage "
                           "(binary=full scan, int8=r0, exact=r1)",
                           ("stage",),
                           lambda: {(s,): float(n) for s, n in
                                    _binary_scan.refine_stage_rows()
                                    .items()})

        def _health_gauge(metric: str, field_level: bool):
            def read():
                h = self._quality.health_snapshot()
                out = {}
                for pid in list(self.engines):
                    info = h.get(pid) or {}
                    if not field_level:
                        out[(str(pid),)] = float(info.get(metric) or 0.0)
                        continue
                    vals = [f[metric]
                            for f in (info.get("fields") or {}).values()
                            if f.get(metric) is not None]
                    # worst field per partition: the gauge answers "does
                    # this partition need attention", not "which field"
                    out[(str(pid),)] = float(max(vals)) if vals else 0.0
                return out
            return read

        m.callback_gauge("vearch_ps_index_health_recon_error",
                         "quantization reconstruction error, worst "
                         "vector field (relative L2)", ("partition",),
                         _health_gauge("recon_error", True))
        m.callback_gauge("vearch_ps_index_health_cell_imbalance",
                         "IVF cell-population coefficient of variation, "
                         "worst vector field", ("partition",),
                         _health_gauge("cell_imbalance_cv", True))
        m.callback_gauge("vearch_ps_index_health_deleted_frac",
                         "deleted-doc fraction of the partition",
                         ("partition",),
                         _health_gauge("deleted_frac", False))
        m.callback_gauge("vearch_ps_index_health_unindexed_frac",
                         "tail appends not yet absorbed into the ANN "
                         "index, worst vector field", ("partition",),
                         _health_gauge("unindexed_frac", True))

        def _retrain_gauge():
            h = self._quality.health_snapshot()
            return {
                (str(pid),): (
                    1.0 if (h.get(pid) or {}).get("needs_retrain")
                    else 0.0)
                for pid in list(self.engines)
            }

        m.callback_gauge("vearch_ps_index_health_needs_retrain",
                         "1 when drift gauges say the partition should "
                         "retrain (reasons in /ps/stats quality block)",
                         ("partition",), _retrain_gauge)

        # raft replication observability (tentpole: VERDICT weak #2 was
        # undiagnosable because raft exposed no lag/latency/election
        # series). Histograms are fed by the per-node observer hook
        # (_raft_observer); everything else is sampled from node state
        # at scrape time, so idle partitions cost nothing.
        self._raft_commit_hist = m.histogram(
            "vearch_raft_commit_latency_seconds",
            "append -> quorum-commit wall time per proposal",
            ("partition",))
        self._raft_apply_hist = m.histogram(
            "vearch_raft_apply_latency_seconds",
            "state-machine apply wall time per log entry",
            ("partition",))

        def _per_node(fn):
            def read():
                return {
                    (str(pid),): float(fn(node))
                    for pid, node in list(self.raft_nodes.items())
                }
            return read

        def _per_peer(field: str):
            def read():
                out = {}
                for pid, node in list(self.raft_nodes.items()):
                    for peer, info in node.state()["peers"].items():
                        out[(str(pid), peer)] = float(info[field])
                return out
            return read

        m.callback_gauge("vearch_raft_peer_lag",
                         "entries this peer trails the leader log end",
                         ("partition", "peer"), _per_peer("lag"))
        m.callback_gauge("vearch_raft_peer_next_index",
                         "leader next_index per peer",
                         ("partition", "peer"), _per_peer("next"))
        m.callback_gauge("vearch_raft_peer_ack_age_seconds",
                         "seconds since this peer acked an append",
                         ("partition", "peer"), _per_peer("ack_age"))
        m.callback_gauge("vearch_raft_commit_index",
                         "raft commit index", ("partition",),
                         _per_node(lambda n: n.commit))
        m.callback_gauge("vearch_raft_applied_index",
                         "raft applied index", ("partition",),
                         _per_node(lambda n: n.applied))
        m.callback_gauge("vearch_raft_apply_lag",
                         "committed-but-unapplied entries "
                         "(commit - applied)", ("partition",),
                         _per_node(
                             lambda n: max(n.commit - n.applied, 0)))
        m.callback_gauge("vearch_raft_term",
                         "raft term", ("partition",),
                         _per_node(lambda n: n.term))
        m.callback_gauge("vearch_raft_is_leader",
                         "1 when this node leads the raft group",
                         ("partition",),
                         _per_node(lambda n: 1.0 if n.is_leader else 0.0))
        m.callback_gauge("vearch_raft_heartbeat_age_seconds",
                         "seconds since replication liveness was proven "
                         "(leader: oldest peer ack; follower: leader "
                         "contact)", ("partition",),
                         _per_node(lambda n: n.heartbeat_age()))

        def _elections():
            out = {}
            for pid, node in list(self.raft_nodes.items()):
                out[(str(pid), "started")] = float(node.elections_started)
                out[(str(pid), "won")] = float(node.elections_won)
            return out

        def _snapshots():
            out = {}
            for pid, node in list(self.raft_nodes.items()):
                out[(str(pid), "sent")] = float(node.snapshots_sent)
                out[(str(pid), "installed")] = float(
                    node.snapshots_installed)
            return out

        m.callback_counter("vearch_raft_elections_total",
                           "raft elections by outcome",
                           ("partition", "event"), _elections)
        m.callback_counter("vearch_raft_snapshots_total",
                           "raft snapshots by direction",
                           ("partition", "direction"), _snapshots)

        # serving-cache observability (caching tentpole). Callback
        # metrics read the cache's pre-initialized stats dict, so the
        # full event label set exists from the first scrape — a cache
        # warming up mid-soak must not mint new series.
        def _search_cache_events():
            return {(e,): float(v)
                    for e, v in self.search_cache.stats.items()}

        m.callback_counter("vearch_ps_search_cache_events_total",
                           "partition result-cache events "
                           "(hit/miss/coalesced/bypass/eviction/"
                           "invalidated)",
                           ("event",), _search_cache_events)
        m.callback_gauge("vearch_ps_search_cache_entries",
                         "live entries in the partition result cache",
                         (),
                         lambda: {(): float(len(self.search_cache))})

        def _filter_cache_events():
            hits = misses = 0
            for eng in list(self.engines.values()):
                hits += getattr(eng, "filter_cache_hits", 0)
                misses += getattr(eng, "filter_cache_misses", 0)
            return {("hit",): float(hits), ("miss",): float(misses)}

        m.callback_counter("vearch_ps_filter_cache_events_total",
                           "scalar-filter bitmap cache events summed "
                           "across hosted engines",
                           ("event",), _filter_cache_events)

        # tiered storage observability (tiering tentpole): both
        # callbacks render the FULL fixed (tier, event) label set from
        # the first scrape, zero-filled — an engine whose disk tier
        # warms up mid-soak must not mint new series.
        m.callback_counter("vearch_ps_tier_events_total",
                           "tiered-storage events summed across hosted "
                           "engines: HBM slab cache "
                           "(hit/miss/eviction/pin_hit/prefetch_hit/"
                           "prefetched), host-RAM slab tier and rerank "
                           "row cache (hit/miss/eviction/admitted/"
                           "rejected), prefetch worker "
                           "(submitted/completed/dropped/error)",
                           ("tier", "event"),
                           lambda: self._tier_snapshot()[0])
        m.callback_gauge("vearch_ps_tier_resident_bytes",
                         "resident bytes per storage tier summed "
                         "across hosted engines",
                         ("tier",),
                         lambda: self._tier_snapshot()[1])

        # runtime truth layer (obs tentpole). Device labels are bounded
        # by the local device count, op/q labels by fixed tuples — all
        # rendered from the first scrape, so the cardinality soak sees
        # zero growth. The compile counter only mints a series when a
        # post-warmup compile actually happens, which is precisely the
        # regression it exists to expose.
        def _device_bytes():
            snap = self.device_sampler.snapshot()
            return {(lbl,): float(b)
                    for lbl, b in snap["devices"].items()}

        m.callback_gauge("vearch_ps_device_hbm_live_bytes",
                         "live device buffer bytes per local device, "
                         "as sampled from the JAX runtime",
                         ("device",), _device_bytes)
        m.callback_counter("vearch_ps_h2d_bytes_total",
                           "host->device transfer bytes accumulated by "
                           "the absorb/upload paths (process-wide)",
                           (),
                           lambda: {(): float(perf_model.h2d_bytes_total())})
        m.callback_gauge("vearch_ps_compiled_programs",
                         "live jit-cache entries across registered "
                         "serving programs",
                         (),
                         lambda: {(): float(
                             perf_model.total_compiled_programs())})
        m.callback_gauge("vearch_ps_hbm_model_drift_bytes",
                         "measured live device bytes in excess of the "
                         "footprint model + start baseline (worst "
                         "device)",
                         (),
                         lambda: {(): float(
                             self.device_sampler.snapshot()["drift_bytes"])})
        m.callback_gauge("vearch_ps_hbm_model_drift",
                         "1 when measured HBM exceeds the footprint "
                         "model beyond tolerance (degrades "
                         "/cluster/health)",
                         (),
                         lambda: {(): float(
                             1.0 if self.device_sampler.snapshot()["drift"]
                             else 0.0)})
        m.callback_counter("vearch_serving_compiles_total",
                           "post-warmup XLA compilations on serving "
                           "paths, by registered program",
                           ("path",),
                           lambda: {(p,): float(n) for p, n in
                                    self.flight_recorder.counts().items()})

        def _latency_quantiles():
            snap = self.latency_quantiles.snapshot()
            out = {}
            for op in ("search", "write"):
                node_q = (snap.get(("_node", op)) or {}).get("q", {})
                for q in self.latency_quantiles.quantiles:
                    lbl = _qlabel(q)
                    out[(op, lbl)] = float(node_q.get(lbl, 0.0))
            return out

        m.callback_gauge("vearch_ps_latency_quantile",
                         "streaming latency quantiles (ms) per op, "
                         "node-level P2 sketch",
                         ("op", "q"), _latency_quantiles)

        def _queue_depth():
            with self._stats_lock:
                return {(op,): float(n)
                        for op, n in self._op_waiting.items()}

        def _inflight_ops():
            with self._stats_lock:
                return {(op,): float(n)
                        for op, n in self._op_inflight.items()}

        m.callback_gauge("vearch_ps_queue_depth",
                         "requests waiting on the admission gate, "
                         "per op",
                         ("op",), _queue_depth)
        m.callback_gauge("vearch_ps_inflight",
                         "requests currently executing, per op",
                         ("op",), _inflight_ops)

        # continuous-batching scheduler: fixed event universe, node-
        # level sums across hosted engines — zero-filled every scrape so
        # the cardinality soak sees no series growth as traffic mixes
        def _sched_events():
            out = {(e,): 0.0 for e in
                   ("batch", "batched_request", "full_dispatch",
                    "age_timeout")}
            for eng in list(self.engines.values()):
                mb = eng._microbatcher
                if mb is None:
                    continue
                out[("batch",)] += float(mb.batches)
                out[("batched_request",)] += float(mb.batched_requests)
                out[("full_dispatch",)] += float(mb.full_dispatches)
                out[("age_timeout",)] += float(mb.age_timeout_fires)
            return out

        def _pad_waste_bytes():
            total = 0
            for eng in list(self.engines.values()):
                total += int(getattr(eng, "pad_waste_bytes", 0))
            return {(): float(total)}

        def _bucket_occupancy():
            rows = cap = 0
            for eng in list(self.engines.values()):
                mb = eng._microbatcher
                if mb is None:
                    continue
                rows += mb.dispatch_rows
                cap += mb.dispatch_capacity
            return {(): round(100.0 * rows / max(cap, 1), 2)}

        m.callback_counter("vearch_ps_batch_sched_events_total",
                           "continuous-batching scheduler events: "
                           "multi-request dispatches (batch), requests "
                           "that shared one (batched_request), buckets "
                           "dispatched full vs on age-bound expiry",
                           ("event",), _sched_events)
        m.callback_counter("vearch_ps_batch_padding_waste_bytes",
                           "bytes of padding rows added to reach the "
                           "declared shape buckets, summed across "
                           "hosted engines",
                           (), _pad_waste_bytes)
        m.callback_gauge("vearch_ps_batch_occupancy_pct",
                         "real rows as a share of padded bucket "
                         "capacity across all scheduler dispatches "
                         "(100 = perfectly packed)",
                         (), _bucket_occupancy)
        register_tracer_metrics(m, self.tracer)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        if self.master_addr:
            self._register()
        # engine open/recovery compiles are expected — keep them out of
        # the serving-compile audit
        with self.flight_recorder.warmup():
            self._recover_partitions()
        self.device_sampler.start()
        self._quality.start()
        if self.master_addr:
            threading.Thread(target=self._heartbeat_loop, daemon=True,
                             name="ps-heartbeat").start()
        threading.Thread(target=self._flush_loop, daemon=True,
                         name="ps-flush").start()
        threading.Thread(target=self._raft_tick_loop, daemon=True,
                         name="ps-raft-tick").start()
        threading.Thread(target=self._slow_killer_loop, daemon=True,
                         name="ps-slow-killer").start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        self.device_sampler.stop()
        self._quality.stop()
        for pid in list(self.raft_nodes):
            if flush:
                try:
                    self.flush_partition(pid)
                except Exception:
                    pass
            self.raft_nodes[pid].close()
        for eng in self.engines.values():
            eng.close()
        self.server.stop()
        if self.tracer.exporter is not None:
            self.tracer.exporter.close()  # ship the last buffered spans

    @property
    def addr(self) -> str:
        return self.server.addr

    def _register(self) -> None:
        """Register with the master, retrying forever (reference:
        ps/server.go:228 lease-backed registration). Node identity is
        persisted locally so a restarted PS keeps its node_id — the
        partitions on disk are addressed by it (reference:
        ps/psutil/meta.go:40 InitMeta local meta file)."""
        meta_path = os.path.join(self.data_dir, "node_meta.json")
        if self.node_id is None and os.path.exists(meta_path):
            with open(meta_path) as f:
                self.node_id = int(json.load(f)["node_id"])
        while not self._stop.is_set():
            try:
                data = rpc.call(
                    self.master_addr, "POST", "/register",
                    {"rpc_addr": self.addr, "node_id": self.node_id,
                     "labels": self.labels},
                    auth=self.master_auth,
                )
                self.node_id = data["node_id"]
                tmp = meta_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"node_id": self.node_id}, f)
                os.replace(tmp, meta_path)
                return
            except RpcError:
                time.sleep(0.5)

    def _partition_stats(self) -> dict[str, dict]:
        """Per-partition stats riding the heartbeat so the master can
        export cluster-level doc/size gauges (reference: master scrapes
        partition stats into monitor_service.go:51-73 gauges)."""
        with self._split_lock:
            split_status = {pid: job.get("status")
                            for pid, job in self._split_jobs.items()}
        with self._stats_lock:
            ops = {pid: dict(c) for pid, c in self._op_counts.items()}
        out = {}
        for pid, eng in list(self.engines.items()):
            try:
                job = eng.build_job
                part = self.partitions.get(pid)
                out[str(pid)] = {
                    "doc_count": eng.doc_count,
                    "size_bytes": eng.memory_usage_bytes(),
                    "status": int(eng.status),
                    "leader": (
                        bool(self.raft_nodes[pid].state().get("is_leader"))
                        if pid in self.raft_nodes else True
                    ),
                    # cumulative op counters: the master's rebalance
                    # planner derives hotness from scrape-to-scrape
                    # deltas of these
                    "searches_total": ops.get(pid, {}).get("searches", 0),
                    "writes_total": ops.get(pid, {}).get("writes", 0),
                    # elastic-job state rides the heartbeat so
                    # /cluster/health rolls up splits and learner
                    # catch-ups without polling every PS
                    "split_status": split_status.get(pid),
                    "learner": bool(
                        part is not None
                        and self.node_id in getattr(part, "learners", [])
                    ),
                    # index-build job state rides the heartbeat so the
                    # master's /cluster/health can roll up in-flight and
                    # failed builds cluster-wide
                    "build_status": job.get("status") if job else None,
                    # data-version signal for the router result cache:
                    # the raft apply index (or the engine's own version
                    # counter off-raft) piggybacks on heartbeats so
                    # cache entries can be revalidated out-of-band of
                    # the search path
                    "apply_version": (
                        int(self.raft_nodes[pid].applied)
                        if pid in self.raft_nodes
                        else int(eng.data_version)
                    ),
                    # index-health drift block (recon error, cell
                    # imbalance, deleted/unindexed fractions,
                    # needs_retrain + reasons) — elastic.compute_plan
                    # reads it out of the master's node stats
                    "quality": self._quality.partition_stats(pid),
                }
            except Exception:
                continue
        return out

    def _space_key(self, pid: int) -> str:
        """The billing key ("db/space") for a hosted partition; the
        `_system` bucket when the partition record is unknown (e.g.
        a dev-mode engine opened outside the metastore)."""
        part = self.partitions.get(pid)
        if part is None or not getattr(part, "space_name", None):
            return accounting.SYSTEM_SPACE
        return f"{part.db_name}/{part.space_name}"

    def _usage_summary(self) -> dict:
        """Per-tenant meter snapshot riding the heartbeat: the process
        accountant's exact per-space dict (never label-collapsed) plus
        this node's per-space HBM residency split. The master rolls
        these up into GET /cluster/usage, deduplicating accountant
        scopes shared by co-located nodes."""
        snap = self._accountant.snapshot()
        return {
            "scope_id": snap["scope_id"],
            "spaces": snap["spaces"],
            "totals": snap["totals"],
            "hbm_bytes": {
                sp: int(n) for sp, n in self._space_device_bytes().items()
            },
        }

    def _obs_summary(self) -> dict:
        """Drift + compile + search-quality digest riding the
        heartbeat (master: _node_obs -> /cluster/health)."""
        samp = self.device_sampler.snapshot()
        return {
            "hbm_drift": bool(samp.get("drift")),
            "drift_bytes": int(samp.get("drift_bytes") or 0),
            "compiles_post_warmup": self.flight_recorder.total(),
            # spaces whose shadow-sampled recall sits statistically
            # under their floor, and partitions whose drift gauges say
            # retrain — the master degrades /cluster/health on these
            **self._quality.obs_summary(),
        }

    def _load_summary(self) -> dict:
        """Search-path load digest riding the heartbeat: queue depth,
        inflight, and node latency quantiles. The master merges it into
        /servers (in-memory only) so routers can score replicas for
        least-loaded read routing without polling each PS."""
        with self._stats_lock:
            waiting = int(self._op_waiting.get("search", 0))
            inflight = int(self._op_inflight.get("search", 0))
        q = (self.latency_quantiles.snapshot()
             .get(("_node", "search")) or {}).get("q", {})
        return {
            "waiting": waiting,
            "inflight": inflight,
            "q50_ms": float(q.get("0.5", 0.0)),
            "q95_ms": float(q.get("0.95", 0.0)),
        }

    def _retry_after_s(self) -> float:
        """Backpressure hint for 429 sheds: a rough time-to-drain —
        median search latency times queue depth over service capacity,
        clamped so clients neither hammer (floor) nor give up (cap)."""
        q = (self.latency_quantiles.snapshot()
             .get(("_node", "search")) or {}).get("q", {})
        q50_s = float(q.get("0.5", 0.0)) / 1e3 or 0.05
        with self._stats_lock:
            waiting = int(self._op_waiting.get("search", 0))
        est = q50_s * (waiting + 1) / max(1, self.max_concurrent_searches)
        return round(min(5.0, max(0.05, est)), 3)

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.heartbeat_interval)
            try:
                resp = rpc.call(
                    self.master_addr, "POST", "/register",
                    {"rpc_addr": self.addr, "node_id": self.node_id,
                     "labels": self.labels,
                     "partitions": self._partition_stats(),
                     # runtime-truth digest: the master's health
                     # rollup degrades on drift without polling us
                     "obs": self._obs_summary(),
                     # load digest for least-loaded replica routing
                     "load": self._load_summary(),
                     # per-tenant meters; scope_id lets the master
                     # dedup co-located nodes sharing one process
                     # accountant (docs/ACCOUNTING.md)
                     "usage": self._usage_summary()},
                    auth=self.master_auth,
                )
            except RpcError:
                continue
            try:
                self._reconcile_schema_fields(
                    resp.get("schema_fields") or {}
                )
                self._reconcile_field_indexes(
                    resp.get("field_indexes") or {}
                )
            except Exception:
                _log.exception("field-index reconcile failed")
            try:
                # per-space recall floors from Space.slo ride the
                # register response; replace-not-merge, so dropping a
                # floor from the space config clears it here too
                if "recall_floors" in resp:
                    self._quality.set_floors(
                        resp.get("recall_floors") or {})
            except Exception:
                _log.exception("recall-floor apply failed")

    def _reconcile_schema_fields(
        self, expect: dict[str, list]
    ) -> None:
        """Add scalar fields the master's schema has but this engine
        lacks (missed /ps/schema/field fan-out or a restart from a
        pre-addition local schema). Runs before the index reconcile so
        a brand-new indexed field gets its column first."""
        from vearch_tpu.engine.types import FieldSchema

        for pid_s, flds in expect.items():
            eng = self.engines.get(int(pid_s))
            if eng is None:
                continue
            names = {f.name for f in eng.schema.fields}
            for d in flds:
                if d["name"] not in names:
                    eng.add_schema_field(FieldSchema.from_dict(d))

    def _reconcile_field_indexes(
        self, expect: dict[str, dict[str, str]]
    ) -> None:
        """Converge each engine's scalar-index flags onto the master's
        expectations riding the heartbeat response. This is the repair
        path for replicas that missed a /field_index fan-out — an alive
        node that hit a transient RPC failure, or one that restarted
        from a local schema.json persisted before the change."""
        for pid_s, flags in expect.items():
            eng = self.engines.get(int(pid_s))
            if eng is None:
                continue
            for f in eng.schema.fields:
                if f.data_type is DataType.VECTOR:
                    continue
                desired = flags.get(f.name, "NONE")
                if f.scalar_index.value != desired:
                    eng.add_field_index(f.name, desired)

    # -- recovery (reference: partition_service.go:275 recoverPartitions:
    #    re-Build engine, gamma Load, rejoin raft) ---------------------------

    def _recover_partitions(self) -> None:
        # the master's metadata wins over the locally persisted
        # partition.json: leadership may have moved while we were down
        current: dict[int, dict] = {}
        if self.master_addr:
            try:
                for p in rpc.call(self.master_addr, "GET", "/partitions",
                                  auth=self.master_auth)["partitions"]:
                    current[int(p["id"])] = p
            except RpcError:
                pass
        import re as _re

        for name in sorted(os.listdir(self.data_dir)):
            pdir = os.path.join(self.data_dir, name)
            # a crashed restore leaves partition_<pid>.restore.* staging
            # dirs: reclaim them at startup or they accumulate shard-
            # sized garbage across crash/restore cycles
            if _re.fullmatch(r"partition_\d+\.restore\..*", name):
                shutil.rmtree(pdir, ignore_errors=True)
                continue
            if not (_re.fullmatch(r"partition_\d+", name)
                    and os.path.isdir(pdir)):
                continue
            pid = int(name.split("_")[1])
            try:
                with open(os.path.join(pdir, "partition.json")) as f:
                    part = Partition.from_dict(json.load(f))
                if pid in current:
                    part = Partition.from_dict(current[pid])
                    self._persist_partition_meta(part)
                eng = Engine.open(pdir)
                eng.start_refresh_loop()
                self._wire_engine(pid, eng)
                applied = 0
                ap = os.path.join(pdir, "applied.json")
                if os.path.exists(ap):
                    with open(ap) as f:
                        applied = int(json.load(f)["applied"])
                node = self._make_raft_node(part, pdir)
                # lock-fix note: applied is raft-lock-guarded state and
                # _flushed was written outside _lock — both race the
                # flush loop once earlier partitions started it
                with node._lock:
                    node.applied = applied
                with self._lock:
                    self._flushed[pid] = applied
                    self.engines[pid] = eng
                    self.partitions[pid] = part
                    self.raft_nodes[pid] = node
                # replay the committed tail into the engine; single-
                # member groups treat every fsync'd entry as committed
                node.recover_singleton_commit()
                node._apply_to_commit()
            except Exception as e:
                _log.error("ps %s: recover partition %s failed: %s: %s",
                           self.node_id, pid, type(e).__name__, e)

    # -- raft plumbing -------------------------------------------------------

    def _make_raft_node(self, part: Partition, pdir: str) -> RaftNode:
        pid = part.id
        members = part.replicas or [self.node_id or 0]
        node = RaftNode(
            pid=pid,
            node_id=self.node_id if self.node_id is not None else 0,
            wal_dir=os.path.join(pdir, "raft"),
            apply_fn=lambda op, _pid=pid: self._apply(_pid, op),
            send_fn=self._raft_send,
            members=members,
            # leader iff the metadata says so, or this node is the sole
            # member (a directly-created local partition). A node NOT in
            # the member list (e.g. removed while down) is never leader.
            is_leader=(part.leader == self.node_id
                       or members == [self.node_id]),
            snapshot_fn=lambda _pid=pid: self._take_snapshot(_pid),
            install_fn=lambda data, idx, _pid=pid: self._install_snapshot(
                _pid, data, idx),
            observer=self._raft_observer(pid),
            learners=list(getattr(part, "learners", []) or []),
        )
        node.wal.observer = self._wal_observer(pid)
        return node

    def _wal_observer(self, pid: int):
        """WAL event sink feeding the durability histograms: fsync
        latency tells you when the disk (not the quorum) is the write
        bottleneck; batch entries show whether group-commit batching is
        actually happening. Fires under the WAL lock — keep it cheap."""

        def observe(event: str, info: dict) -> None:
            if event == "append":
                self._wal_fsync_hist.observe(
                    float(info.get("fsync_seconds", 0.0)), str(pid))
                self._wal_batch_hist.observe(
                    float(info.get("entries", 0)), str(pid))
        return observe

    def _raft_observer(self, pid: int):
        """Raft event sink: latency events feed the /metrics histograms;
        rare state transitions (elections, leadership changes, snapshot
        transfers) become spans so they show up in /debug/traces next to
        the searches they disturbed. Must stay cheap + non-blocking —
        it can fire under raft locks."""

        def observe(event: str, info: dict) -> None:
            p = str(pid)
            if event == "commit":
                self._raft_commit_hist.observe(info["seconds"], p)
            elif event == "apply":
                self._raft_apply_hist.observe(info["seconds"], p)
            else:
                self.tracer.record(
                    f"raft.{event}",
                    tags={"partition": pid, "node": self.node_id, **info},
                )
        return observe

    def _apply(self, pid: int, op: dict) -> Any:
        """State-machine apply (reference: raft_state_machine.go:124
        innerApply -> gammacb writer). Deterministic: every replica
        applies identical ops in identical log order."""
        eng = self._engine(pid)
        t = op["type"]
        if t == "upsert":
            with self._stats_lock:
                self._mem_dirty = True  # cached memory accounting is stale
            try:
                return eng.upsert(op["documents"])
            except ValueError as e:
                # data-dependent rejection (e.g. a partial update whose
                # base row vanished between propose and apply). Applies
                # must NEVER raise: the entry is already committed, and
                # an exception here would wedge the apply loop retrying
                # it forever on every replica. Same state -> same error
                # marker on every replica, so determinism holds.
                return {"_rejected": str(e)}
        if t == "delete":
            with self._stats_lock:
                self._mem_dirty = True
            return eng.delete(op["keys"])
        raise RpcError(500, f"unknown log op {t!r}")

    def _peer_addr(self, peer: int) -> str:
        now = time.monotonic()  # cache TTL is a duration
        ts, cache = self._peer_cache
        if now - ts > 2.0 or peer not in cache:
            servers = rpc.call(self.master_addr, "GET", "/servers",
                               auth=self.master_auth)["servers"]
            cache = {s["node_id"]: s["rpc_addr"] for s in servers}
            # lock-fix note: concurrent refreshers raced the rebind;
            # last-writer-wins is fine but the write itself is guarded
            with self._stats_lock:
                self._peer_cache = (now, cache)
        if peer not in cache:
            raise RpcError(503, f"no address for node {peer}")
        return cache[peer]

    def _raft_send(self, peer: int, path: str, body: dict) -> dict:
        try:
            return rpc.call(self._peer_addr(peer), "POST", path, body,
                            timeout=30.0)
        except RpcError:
            # lock-fix note: unlocked += from concurrent sync threads
            # dropped increments (read-modify-write race)
            with self._stats_lock:
                self.replication_errors += 1
            raise

    def _node(self, pid: int) -> RaftNode:
        node = self.raft_nodes.get(int(pid))
        if node is None:
            raise RpcError(404, f"partition {pid} not on this node")
        return node

    def _h_raft_append(self, body: dict, _parts) -> dict:
        return self._node(body["pid"]).handle_append(body)

    def _h_raft_fence(self, body: dict, _parts) -> dict:
        return self._node(body["pid"]).handle_fence(int(body["term"]))

    def _h_raft_lead(self, body: dict, _parts) -> dict:
        pid = int(body["pid"])
        node = self._node(pid)
        out = node.become_leader(int(body["term"]), body["members"],
                                 learners=body.get("learners"))
        self._update_partition_meta(pid, leader=self.node_id,
                                    term=int(body["term"]),
                                    replicas=body["members"],
                                    learners=body.get("learners"))
        return out

    def _h_raft_members(self, body: dict, _parts) -> dict:
        pid = int(body["pid"])
        node = self._node(pid)
        out = node.set_members(int(body["term"]), body["members"],
                               learners=body.get("learners"))
        self._update_partition_meta(pid, term=int(body["term"]),
                                    replicas=body["members"],
                                    leader=body.get("leader"),
                                    learners=body.get("learners"))
        return out

    def _h_raft_snapshot(self, body: dict, _parts) -> dict:
        return self._node(body["pid"]).handle_install_snapshot(body)

    def _h_raft_state(self, body, parts) -> dict:
        if parts:
            return self._node(int(parts[0])).state()
        return {str(pid): n.state() for pid, n in self.raft_nodes.items()}

    def _update_partition_meta(self, pid: int, leader=None, term=None,
                               replicas=None, learners=None) -> None:
        part = self.partitions.get(pid)
        if part is None:
            return
        if leader is not None:
            part.leader = leader
        if term is not None:
            part.term = term
        if replicas is not None:
            part.replicas = list(replicas)
        if learners is not None:
            part.learners = [int(x) for x in learners]
        self._persist_partition_meta(part)

    def _persist_partition_meta(self, part: Partition) -> None:
        pdir = os.path.join(self.data_dir, f"partition_{part.id}")
        os.makedirs(pdir, exist_ok=True)
        tmp = os.path.join(pdir, "partition.json.tmp")
        with open(tmp, "w") as f:
            json.dump(part.to_dict(), f)
        os.replace(tmp, os.path.join(pdir, "partition.json"))

    # -- flush job (reference: store_raft_job.go:97 flush job records the
    #    applied SN; :40 truncate job trims the log behind it) --------------

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.flush_interval)
            for pid in list(self.raft_nodes):
                try:
                    node = self.raft_nodes.get(pid)
                    if node is None:
                        continue
                    if node.applied > self._flushed.get(pid, 0):
                        self.flush_partition(pid)
                except Exception as e:
                    # a silently failing flush would stop checkpointing
                    # AND WAL truncation — always loud
                    _log.error("ps %s: flush partition %s failed: %s: %s",
                               self.node_id, pid, type(e).__name__, e)

    def _flush_lock(self, pid: int):
        # lock-fix note: flush locks were minted via bare setdefault
        # from the flush loop, /ps/flush, snapshot sends and restore
        # concurrently — two callers could each get a DIFFERENT lock
        # for the same pid and checkpoint over each other. The dict
        # mutation now happens under _lock.
        with self._lock:
            return self._flush_locks.setdefault(
                pid, lockcheck.make_lock(f"ps.flush{pid}"))

    def flush_partition(self, pid: int) -> int:
        """Checkpoint the engine with its applied index, then truncate
        the WAL behind it (keeping a catch-up tail). Returns the flushed
        applied index."""
        node = self._node(pid)
        eng = self._engine(pid)
        pdir = os.path.join(self.data_dir, f"partition_{pid}")
        with self._flush_lock(pid):
            # capture under the apply mutex so the engine snapshot
            # matches node.applied exactly; disk writes happen outside
            # it (but inside the flush lock — one checkpoint at a time)
            with node._apply_lock:
                applied = node.applied
                snap = eng.snapshot_state()
            eng.write_snapshot(snap, pdir)
            tmp = os.path.join(pdir, "applied.json.tmp")
            with open(tmp, "w") as f:
                json.dump({"applied": applied, "term": node.term}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(pdir, "applied.json"))
            # lock-fix note: _flushed is read by the flush loop under
            # no lock at all; writes now consistently go through _lock
            with self._lock:
                self._flushed[pid] = applied
            node.wal.save_meta(fsync=True)
            node.wal.truncate_prefix(
                max(node.wal.first_index, applied - WAL_KEEP_ENTRIES + 1)
            )
        return applied

    def _raft_tick_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.raft_tick)
            for node in list(self.raft_nodes.values()):
                # also tick single-voter groups that carry learners:
                # the migration catch-up stream rides the tick
                if node.is_leader and (len(node.members) > 1
                                       or node.learners):
                    node.tick()

    # -- snapshot transfer (reference: gammacb/snapshot.go:26 streams the
    #    engine's on-disk files in chunks) ----------------------------------

    def _take_snapshot(self, pid: int) -> tuple[bytes, int]:
        applied = self.flush_partition(pid)
        pdir = os.path.join(self.data_dir, f"partition_{pid}")
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            for name in sorted(os.listdir(pdir)):
                # raft log + local membership are per-replica, not state
                if name in ("raft", "partition.json") or \
                        name.endswith(".tmp"):
                    continue
                tar.add(os.path.join(pdir, name), arcname=name)
        return buf.getvalue(), applied

    def _install_snapshot(self, pid: int, data: bytes, snap_index: int
                          ) -> None:
        pdir = os.path.join(self.data_dir, f"partition_{pid}")
        old = self.engines.get(pid)
        if old is not None:
            old.close()
        for name in list(os.listdir(pdir)):
            if name in ("raft", "partition.json"):
                continue
            p = os.path.join(pdir, name)
            shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
            tar.extractall(pdir, filter="data")
        eng = Engine.open(pdir)
        eng.start_refresh_loop()
        self._wire_engine(pid, eng)
        with self._lock:
            self.engines[pid] = eng
            self._flushed[pid] = snap_index
        with self._stats_lock:
            self._mem_dirty = True

    # -- handlers ------------------------------------------------------------

    def _engine(self, pid: int) -> Engine:
        eng = self.engines.get(int(pid))
        if eng is None:
            raise RpcError(404, f"partition {pid} not on this node")
        return eng

    def memory_used_bytes(self) -> int:
        """Total engine memory across partitions, from a short-TTL /
        dirty-flag cache: a clean read serves the cached sum for up to
        _mem_max_age seconds; applies mark it dirty, and a dirty read
        refreshes at most every _mem_min_interval seconds so a write
        burst pays one O(engines) walk per interval, not per request."""
        now = time.monotonic()  # cache age is a duration
        ts, val = self._mem_cache
        age = now - ts
        if (age > self._mem_max_age
                or (self._mem_dirty and age > self._mem_min_interval)):
            val = sum(
                e.memory_usage_bytes() for e in list(self.engines.values())
            )
            # the O(engines) walk stays outside the lock (concurrent
            # refreshers waste a walk, never corrupt); the cache rebind
            # + dirty-flag clear are what must be atomic
            with self._stats_lock:
                self._mem_cache = (now, val)
                self._mem_dirty = False
        return val

    def _wire_engine(self, pid: int, eng: Engine) -> None:
        """Attach the per-engine observability hooks every creation
        path (create / recover / snapshot install / restore) needs:
        terminal build states feed the build-duration histogram — this
        covers background auto-builds the request handlers never see."""
        def on_build_done(job: dict, _pid: int = pid) -> None:
            self._build_hist.observe(
                float(job.get("duration_seconds") or 0.0),
                str(_pid), str(job.get("op", "build")))
            if job.get("status") == "done":
                # a finished (re)build replaced the serving index: reset
                # the recall estimators and the train-time recon
                # baseline (staleness hook, lint VL105) — this covers
                # background auto-builds no request handler ever sees
                self._quality.note_index_mutation(
                    _pid, self._space_key(_pid),
                    op=str(job.get("op", "build")))
        eng.build_observer = on_build_done

    def _h_create_partition(self, body: dict, _parts) -> dict:
        part = Partition.from_dict(body["partition"])
        pid = part.id
        with self._lock:
            if pid in self.engines:
                raise RpcError(409, f"partition {pid} already exists")
            schema = TableSchema.from_dict(body["schema"])
            pdir = os.path.join(self.data_dir, f"partition_{pid}")
            with self.flight_recorder.warmup():
                eng = Engine(schema, data_dir=pdir)
                eng.dump()  # schema on disk immediately: crash-openable
            eng.start_refresh_loop()
            self._wire_engine(pid, eng)
            self.engines[pid] = eng
            self.partitions[pid] = part
            self._persist_partition_meta(part)
            node = self._make_raft_node(part, pdir)
            if part.term > node.wal.term:
                node.wal.term = part.term
                node.wal.save_meta()
            self.raft_nodes[pid] = node
        return {"partition_id": pid}

    def _h_delete_partition(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        space = self._space_key(pid)  # before the registry pop below
        # an active split ends here: for a committed split this IS the
        # normal finalization (the master deletes the parent last); the
        # teardown drains the mirror queue while the engine still lives
        self._split_teardown(pid)
        with self._lock:
            node = self.raft_nodes.pop(pid, None)
            if node is not None:
                node.close()
            eng = self.engines.pop(pid, None)
            if eng is not None:
                eng.close()
            self.partitions.pop(pid, None)
            self._flushed.pop(pid, None)
        shutil.rmtree(
            os.path.join(self.data_dir, f"partition_{pid}"), ignore_errors=True
        )
        # drop quality state keyed by the gone partition (warm keys,
        # health, recall cells for its space — VL105 staleness hook)
        self._quality.note_index_mutation(pid, space, op="")
        return {"partition_id": pid}

    # -- writes: every mutation is a log proposal ---------------------------

    def _observed_write(self, body: dict, fn, parts) -> dict:
        """Write-op observability shim: inflight gauge + latency
        quantile sketch around the real handler (mirrors what the
        search path does inline)."""
        pid = int(body["partition_id"])
        t0 = time.monotonic()
        with self._stats_lock:
            self._op_inflight["write"] += 1
        # write-path H2D bytes (appends pushing rows to device) bill to
        # the owning space, not the _system bucket
        _space_token = accounting.set_space(self._space_key(pid))
        try:
            return fn(body, parts)
        finally:
            accounting.reset_space(_space_token)
            with self._stats_lock:
                self._op_inflight["write"] -= 1
            ms = (time.monotonic() - t0) * 1e3
            self.latency_quantiles.observe((pid, "write"), ms)
            self.latency_quantiles.observe(("_node", "write"), ms)

    def _h_upsert(self, body: dict, _parts) -> dict:
        return self._observed_write(body, self._h_upsert_inner, _parts)

    def _h_upsert_inner(self, body: dict, _parts) -> dict:
        import uuid

        from vearch_tpu.cluster.tracing import NULL_SPAN

        pid = int(body["partition_id"])
        self._engine(pid)  # 404 before proposing
        if self.memory_limit_mb:
            # cached accounting: the old inline sum walked every engine
            # on EVERY upsert — O(partitions) per request
            used = self.memory_used_bytes() >> 20
            if used >= self.memory_limit_mb:
                raise RpcError(
                    403,
                    f"resource_exhausted: {used}MB >= "
                    f"limit {self.memory_limit_mb}MB (writes rejected, "
                    f"reads still served)",
                )
        # assign ids BEFORE the log so replicas apply identical ops.
        # NOTE on retries: propose may 503 while the entry later commits
        # (at-least-once); a retry is safe because the router assigns
        # _ids before fan-out, so the replayed upsert is an idempotent
        # update. Direct-PS callers should pass _id themselves — the
        # uuid fallback here makes a blind retry mint a second document.
        docs = [
            doc if "_id" in doc else {**doc, "_id": uuid.uuid4().hex}
            for doc in body["documents"]
        ]
        # partial updates (docs omitting vector fields) must reference an
        # existing row — reject BEFORE proposing so a bad request never
        # enters the replicated log (a rare post-propose race degrades to
        # a deterministic _rejected apply marker instead)
        eng = self._engine(pid)
        vf = [f.name for f in eng.schema.vector_fields()]
        batch_ids = set()
        for doc in docs:
            # None == omitted (a JSON null vector is the natural "keep
            # the stored one" idiom); an _id provided earlier in this
            # batch is a valid inheritance source too
            missing = [n for n in vf if doc.get(n) is None]
            if missing and str(doc["_id"]) not in batch_ids \
                    and eng.table.docid_of(str(doc["_id"])) is None:
                raise RpcError(
                    400,
                    f"document {doc['_id']!r} omits vector field(s) "
                    f"{missing} and does not exist yet",
                )
            if not missing:
                batch_ids.add(str(doc["_id"]))
        tctx = body.get("_trace_ctx")
        profile = bool(body.get("profile"))
        # write-side timing mirrors the search path: raft fills per-phase
        # windows (propose-wait / wal append+fsync / commit-wait / apply)
        # which become child spans and the profile:true breakdown
        timing: dict | None = {} if (profile or tctx) else None
        span = (
            self.tracer.span("ps.upsert", ctx=tctx,
                             tags={"partition": pid, "node": self.node_id,
                                   "docs": len(docs)})
            if tctx else NULL_SPAN
        )
        node = self._node(pid)
        with span:
            keys = node.propose(
                [{"type": "upsert", "documents": docs}], timing=timing)[0]
            if timing is not None:
                timing["doc_count"] = len(docs)
                self._replay_write_spans(span, timing, pid)
        if isinstance(keys, dict) and "_rejected" in keys:
            raise RpcError(400, keys["_rejected"])
        self._write_docs_total.inc(str(pid), "upsert", by=float(len(docs)))
        self._count_op(pid, "writes")
        # double-write mirror for an active split: in the sync window
        # this blocks until the children hold the write, so the ack the
        # client sees is as durable post-cutover as pre-cutover
        self._split_mirror(pid, "upsert",
                           [str(d["_id"]) for d in docs])
        # propose() returns only after the entry applied locally, so
        # this applied index covers the write just acknowledged — the
        # router bumps its version map from it, which is exactly what
        # keeps read-your-writes through the result cache
        out = {"keys": keys, "count": len(keys),
               "apply_version": int(node.applied),
               "map_version": self._map_version(pid)}
        if profile:
            out["profile"] = _write_profile_from_timing(timing or {})
        return out

    def _replay_write_spans(self, span, timing: dict, pid: int) -> None:
        """Replay raft's measured phase windows as child spans under the
        sampled ps.upsert/ps.delete span, and tag the parent with the
        flat `*_ms` breakdown (same contract as the search path)."""
        from vearch_tpu.cluster.tracing import NULL_SPAN

        pspans = timing.pop("_phase_spans", None) or []
        if span is NULL_SPAN:
            return
        sctx = span.ctx()
        for name, start_us, dur_us in pspans:
            self.tracer.record(name, ctx=sctx, start_us=start_us,
                               dur_us=dur_us, tags={"partition": pid})
        for phase, ms in timing.items():
            span.set_tag(phase, ms)

    def _h_delete(self, body: dict, _parts) -> dict:
        return self._observed_write(body, self._h_delete_inner, _parts)

    def _h_delete_inner(self, body: dict, _parts) -> dict:
        from vearch_tpu.cluster.tracing import NULL_SPAN

        pid = int(body["partition_id"])
        eng = self._engine(pid)
        node = self._node(pid)
        tctx = body.get("_trace_ctx")
        profile = bool(body.get("profile"))
        span = (
            self.tracer.span("ps.delete", ctx=tctx,
                             tags={"partition": pid, "node": self.node_id})
            if tctx else NULL_SPAN
        )
        if body.get("keys"):
            timing: dict | None = {} if (profile or tctx) else None
            with span:
                deleted = node.propose(
                    [{"type": "delete", "keys": body["keys"]}],
                    timing=timing)[0]
                if timing is not None:
                    self._replay_write_spans(span, timing, pid)
            self._write_docs_total.inc(str(pid), "delete",
                                       by=float(deleted or 0))
            self._count_op(pid, "writes")
            self._split_mirror(pid, "delete",
                               [str(k) for k in body["keys"]])
            out = {"deleted": deleted,
                   "apply_version": int(node.applied),
                   "map_version": self._map_version(pid)}
            if profile:
                out["profile"] = _write_profile_from_timing(timing or {})
            return out
        # delete-by-filter (reference: /document/delete with filters).
        # Drain in batches until no matches remain — a single capped
        # query would silently delete only the first 10k of a larger
        # match set (r1 VERDICT weak-8). An explicit client `limit`
        # still bounds the total.
        limit = int(body["limit"]) if body.get("limit") is not None else None
        batch = 10_000
        deleted = 0
        while True:
            want = batch if limit is None else min(batch, limit - deleted)
            if want <= 0:
                break
            docs = eng.query(body.get("filters"), limit=want,
                             include_fields=[], order_by_key=False)
            if not docs:
                break
            keys = [d["_id"] for d in docs]
            deleted += node.propose([{"type": "delete", "keys": keys}])[0]
            self._split_mirror(pid, "delete", [str(k) for k in keys])
            if len(docs) < want:
                break
        self._write_docs_total.inc(str(pid), "delete", by=float(deleted))
        self._count_op(pid, "writes")
        return {"deleted": deleted, "apply_version": int(node.applied),
                "map_version": self._map_version(pid)}

    def _h_get(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        return {"documents": eng.get(body["keys"], body.get("fields"),
                                      bool(body.get("vector_value", False)))}

    # -- kill switch / slow-request isolation (reference: Set/Delete
    #    KillStatus c_api + Rqueue, handler_document.go:96; slow-request
    #    killer, ps/schedule_job.go:252) ------------------------------------

    def _slow_killer_loop(self) -> None:
        while not self._stop.is_set():
            # tick fast enough to catch requests near the limit, but
            # never busier than 20Hz; re-read the limit AFTER sleeping
            # so a runtime config change takes effect within one tick
            time.sleep(max(0.05, min(0.5,
                                     (self.slow_request_ms or 2000) / 4000.0)))
            limit = self.slow_request_ms
            # monotonic, matching the request start stamps: a clock
            # step must not mass-kill (or never kill) in-flight work
            now = time.monotonic()
            with self._inflight_lock:
                for rid, info in self._inflight.items():
                    ctx = info["ctx"]
                    if ctx.killed:
                        continue
                    # per-request deadlines arm even when the slow-killer
                    # limit is off; ctx.check() also self-enforces them
                    # between dispatches, this loop just makes the kill
                    # prompt for requests parked off-device
                    dl = info.get("deadline")
                    if dl is not None and now > dl:
                        ctx.kill("deadline exceeded", code="deadline")
                        self.killed_requests += 1
                    elif limit and (now - info["start"]) * 1e3 > limit:
                        ctx.kill(
                            f"slow request killed after {limit}ms",
                            code="slow",
                        )
                        self.killed_requests += 1

    def _h_kill(self, body: dict, _parts) -> dict:
        """Kill in-flight request(s) by id (reference: SetKillStatus).
        A retried request may share its id with the original — kill
        every matching entry (the registry is keyed by a unique token
        so duplicates never shadow each other). An optional "attempt"
        narrows the kill to one hedged-scatter attempt: the rid is
        shared across a whole fan-out, so the router cancelling a
        hedge loser must not take out the sibling partitions' RPCs."""
        rid = str(body["request_id"])
        att = body.get("attempt")
        killed = 0
        with self._inflight_lock:
            for info in self._inflight.values():
                if info["rid"] != rid or info["ctx"].killed:
                    continue
                if att is not None and info.get("attempt") != att:
                    continue
                info["ctx"].kill("killed by operator", code="operator")
                killed += 1
            self.killed_requests += killed
        if not killed:
            raise RpcError(404, f"request {rid!r} not in flight")
        return {"request_id": rid, "killed": killed}

    def _h_requests(self, _body, _parts) -> dict:
        now = time.monotonic()  # elapsed_ms against monotonic starts
        with self._inflight_lock:
            return {"requests": [
                {"request_id": i["rid"],
                 "elapsed_ms": round((now - i["start"]) * 1e3, 1),
                 "killed": i["ctx"].killed}
                for i in self._inflight.values()
            ]}

    def _check_read_consistency(self, body: dict) -> None:
        """raft_consistent reads (reference: client honors the replica's
        raft_consistent lag status, client/client.go:1316): a follower
        serving a consistent read must have applied everything it knows
        to be committed; otherwise the router retries on the leader."""
        if not body.get("raft_consistent"):
            return
        node = self.raft_nodes.get(int(body.get("partition_id", -1)))
        if node is None:
            return
        st = node.state()
        if not st["is_leader"] and st["applied"] < st["commit"]:
            raise RpcError(
                421,
                f"partition {node.pid}: replica lags (applied "
                f"{st['applied']} < commit {st['commit']}) for a "
                f"raft_consistent read",
            )

    def _h_search(self, body: dict, _parts) -> dict:
        import uuid

        import numpy as np

        from vearch_tpu.engine.engine import RequestContext, RequestKilled

        eng = self._engine(body["partition_id"])
        self._check_read_consistency(body)
        vectors = {
            name: np.asarray(v, dtype=np.float32)  # lint: allow[host-sync] host-side input normalization of wire payloads, no device work exists yet
            for name, v in body["vectors"].items()
        }
        pid = int(body["partition_id"])
        self._count_op(pid, "searches")
        # tenant resolution happens before admission so even a shed 429
        # is attributable (docs/ACCOUNTING.md)
        space_key = self._space_key(pid)
        space_lbl = self._accountant.label(space_key)
        # the router marks its duplicate hedge attempt: device work it
        # causes bills honestly, but the logical request bills once
        hedge_extra = bool(body.get("_hedge_extra"))
        q0 = next(iter(vectors.values()))
        qrows = 1 if q0.ndim == 1 else int(q0.shape[0])
        # slow-channel routing: partitions with a slow recent history go
        # through the small slow gate; everyone else uses the fast gate
        slow = bool(
            self.slow_route_ms
            and self._search_ewma.get(pid, 0.0) > self.slow_route_ms
        )
        gate = self._slow_gate if slow else self._search_gate
        if slow:
            with self._stats_lock:
                self.slow_routed += 1
        # admission control: shed before joining a wait queue that is
        # already past the bound — the request does zero device work and
        # the 429 carries a Retry-After estimate for the SDK's backoff
        if not self._admission.try_admit(
                priority=int(body.get("priority") or 0)):
            self._shed_total.inc("search", space_lbl)
            self._accountant.charge("sheds", 1, space=space_key)
            raise RpcError(
                429,
                f"partition server shedding: admission queue full "
                f"(limit {self._admission.queue_limit})",
                retry_after=self._retry_after_s(),
            )
        t_gate = time.monotonic()
        with self._stats_lock:
            self._op_waiting["search"] += 1
        try:
            acquired = gate.acquire(timeout=30.0)
        finally:
            with self._stats_lock:
                self._op_waiting["search"] -= 1
            self._admission.leave()
        if not acquired:
            raise RpcError(
                429,
                "partition server %s queue full"
                % ("slow-search" if slow else "search"),
                retry_after=self._retry_after_s(),
            )
        with self._stats_lock:
            self._op_inflight["search"] += 1
        gate_wait_ms = round((time.monotonic() - t_gate) * 1e3, 3)
        self._accountant.charge("queue_wait_us", int(gate_wait_ms * 1e3),
                                space=space_key)
        rid = str(body.get("request_id") or uuid.uuid4().hex)
        token = uuid.uuid4().hex  # unique even when clients reuse rids
        # per-request deadline: the search option wins, else the PS-wide
        # config default; 0/absent leaves the request unbounded
        deadline_ms = float(
            body.get("deadline_ms") or self.request_deadline_ms or 0
        )
        t_start = time.monotonic()
        # wall anchor for span epochs; all measurement stays monotonic
        wall0 = time.time() - t_start  # lint: allow[wall-clock] span epoch anchor, correlates with collector time
        ctx = RequestContext(
            rid,
            deadline=(t_start + deadline_ms / 1e3) if deadline_ms else None,
        )
        with self._inflight_lock:
            self._inflight[token] = {"rid": rid, "start": t_start,
                                     "ctx": ctx, "slow": slow,
                                     "deadline": ctx.deadline,
                                     # hedged-scatter attempt id: lets
                                     # the router cancel one attempt of
                                     # a fan-out without killing the
                                     # sibling that shares the rid
                                     "attempt": body.get("_hedge_attempt")}
        from vearch_tpu.cluster.tracing import NULL_SPAN

        tctx = body.get("_trace_ctx")
        span = (
            self.tracer.span("ps.search", ctx=tctx,
                             tags={"partition": pid, "node": self.node_id,
                                   "slow_channel": slow})
            if tctx else NULL_SPAN
        )
        want_trace = bool(body.get("trace") or body.get("profile"))
        # slowlog/deadline observability needs the phase breakdown even
        # when the client didn't ask for one — force the engine trace on
        # so a killed or slow request can explain where its time went
        # (the dict is stripped from the response below unless asked for)
        trace: dict | None = (
            {} if (want_trace or ctx.deadline is not None
                   or self.slowlog.threshold_ms > 0) else None
        )
        # compile attribution: a serving-path compilation during this
        # request's dispatches lands in /debug/compiles carrying this id
        from vearch_tpu.obs import flight_recorder as _flightrec

        _trace_token = _flightrec.set_active_trace(span.trace_id or rid)
        # cost attribution: every dispatch / H2D byte / device slice the
        # engine produces for this request bills to this space (the
        # batch scheduler carries the binding across its thread hop)
        _space_token = accounting.set_space(space_key)
        try:
            with span:
                if self.debug_search_delay_ms:
                    # injected straggler (tests/bench): sleep in small
                    # chunks so a hedged loser's kill aborts it fast
                    end = t_start + float(self.debug_search_delay_ms) / 1e3
                    while True:
                        ctx.check()
                        rem = end - time.monotonic()
                        if rem <= 0:
                            break
                        # lint: allow[serving-blocking] env-gated test-only delay, sliced 5ms so ctx.check() keeps it killable
                        time.sleep(min(0.005, rem))
                # apply version captured BEFORE the search runs: a
                # write landing mid-search makes the resulting cache
                # entry *older*-labeled, so it can never serve a state
                # the writer was already acknowledged for
                rnode = self.raft_nodes.get(pid)
                applied = (int(rnode.applied) if rnode is not None
                           else int(eng.data_version))
                out, cache_status, timing = self._cached_search(
                    eng, pid, applied, body, vectors, ctx, trace
                )
                # every response carries the partition's apply version
                # — the router's entry-validation signal
                out["apply_version"] = applied
                # ... and the partition-map epoch, so a router holding a
                # stale map learns of a split cutover from any response
                out["map_version"] = self._map_version(pid)
                span.set_tag("cache", cache_status)
                if cache_status in ("hit", "coalesced"):
                    # served from memo: billed to the hitting space at
                    # zero device cost (no engine work ran for it)
                    self._accountant.charge("cache_hits", 1,
                                            space=space_key)
                if timing is not None:
                    timing["gate_wait_ms"] = gate_wait_ms
                    # engine phase windows -> real child spans under
                    # ps.search (gate wait included), so /debug/traces
                    # shows where the partition's time went
                    pspans = timing.pop("_phase_spans", None) or []
                    if span is not NULL_SPAN:
                        sctx = span.ctx()
                        self.tracer.record(
                            "ps.gate_wait", ctx=sctx,
                            start_us=int((wall0 + t_gate) * 1e6),
                            dur_us=int(gate_wait_ms * 1e3),
                            tags={"partition": pid},
                        )
                        for name, start_us, dur_us in pspans:
                            self.tracer.record(
                                name, ctx=sctx, start_us=start_us,
                                dur_us=dur_us, tags={"partition": pid},
                            )
                    for phase, ms in timing.items():
                        span.set_tag(phase, ms)
                if body.get("profile"):
                    prof = _profile_from_timing(timing or {})
                    prof["cache"] = cache_status
                    if timing is None and cache_status in (
                            "hit", "coalesced"):
                        # no engine work happened for THIS response;
                        # the zero-dispatch claim is explicit, not an
                        # absence the reader must infer
                        prof["dispatches"]["path"] = "cache_hit"
                    out["profile"] = prof
                if want_trace and timing is not None:
                    # _cached_search detaches timing from the shared
                    # payload; re-attach only when the client asked
                    out["timing"] = timing
                return out
        except RequestKilled as e:
            reason = ctx.reason_code or "operator"
            self._killed_total.inc(reason, space_lbl)
            # force-sample killed requests: even an untraced request
            # leaves a span in /debug/traces explaining the abort
            if span is NULL_SPAN:
                self.tracer.record(
                    "ps.search",
                    start_us=int((wall0 + t_start) * 1e6),
                    dur_us=int((time.monotonic() - t_start) * 1e6),
                    tags={"partition": pid, "request_id": rid,
                          "kill_reason": reason},
                    status="error: RequestKilled",
                )
            # terminal abort code — the router must NOT retry this as a
            # failover (the kill exists to shed this exact work)
            raise RpcError(ERR_REQUEST_KILLED,
                           f"request_killed: request {rid}: {e}") from e
        finally:
            _flightrec.reset_active_trace(_trace_token)
            accounting.reset_space(_space_token)
            # per-tenant billing: one logical request (the router's
            # duplicate hedge attempt meters separately so a won hedge
            # bills once), its query rows, and any abort
            self._accountant.charge(
                "hedge_extras" if hedge_extra else "requests", 1,
                space=space_key)
            self._accountant.charge("rows", qrows, space=space_key)
            if ctx.killed:
                self._accountant.charge("kills", 1, space=space_key)
            with self._inflight_lock:
                self._inflight.pop(token, None)
            gate.release()
            with self._stats_lock:
                self._op_inflight["search"] -= 1
            ms = (time.monotonic() - t_start) * 1e3
            self.latency_quantiles.observe((pid, "search"), ms)
            self.latency_quantiles.observe(("_node", "search"), ms)
            # lock-fix note: the EWMA read-modify-write was documented
            # as benignly racy, but a torn read-modify-write pair can
            # resurrect a stale latency forever — _stats_lock is cheap
            with self._stats_lock:
                prev = self._search_ewma.get(pid, ms)
                self._search_ewma[pid] = 0.8 * prev + 0.2 * ms
            if self.slowlog.should_log(ms, killed=ctx.killed):
                t = trace or {}
                self.slowlog.add({
                    "request_id": rid, "partition": pid, "op": "search",
                    "space": space_key,
                    "elapsed_ms": round(ms, 3),
                    "killed": ctx.killed, "reason": ctx.reason,
                    "phases": {k[:-len("_ms")]: v for k, v in t.items()
                               if k.endswith("_ms")},
                    "dispatches": t.get("dispatches"),
                    "trace_id": span.trace_id or None,
                })

    def _cached_search(self, eng, pid, applied, body, vectors, ctx,
                       trace):
        """Result-cache + single-flight wrapper around _do_search.

        Returns ``(out, cache_status, timing)``: `out` is a fresh
        top-level dict per caller (hit/coalesced responses share the
        row payload but never the envelope, so later mutation of one
        response cannot leak into another), `cache_status` is one of
        hit/miss/coalesced/bypass, and `timing` is the engine trace of
        the request that actually computed (None for hit/coalesced —
        they did no engine work to explain). A coalesced follower also
        counts a `miss` (it did miss the cache) plus `coalesced`.
        """
        from vearch_tpu.cluster.querycache import canonical_query_key

        cacheable = (
            self.search_cache.max_entries > 0
            and body.get("cache", True) is not False
            and not body.get("raft_consistent")
            # trace:true promises a real phase/dispatch breakdown and
            # a replayed span tree — a hit has neither to offer;
            # profile:true is a measurement of the engine path, so
            # serving it a memoized envelope would be lying
            and not body.get("trace")
            and not body.get("profile")
        )
        if not cacheable:
            if body.get("cache", True) is False:
                self.search_cache.note("bypass")
            out = self._do_search(eng, body, vectors, ctx, trace)
            return out, "bypass", out.pop("timing", None)
        ckey = canonical_query_key(
            str(pid), vectors, int(body.get("k", 10)),
            {
                "filters": body.get("filters"),
                "include_fields": body.get("include_fields"),
                "columnar_wire": bool(body.get("columnar_wire")),
                "sort": body.get("sort"),
                "index_params": body.get("index_params"),
                "brute_force": bool(body.get("brute_force", False)),
                "score_bounds": body.get("score_bounds"),
                "field_weights": body.get("field_weights"),
            },
        )
        # raft apply index AND engine data version are part of the
        # key: any applied write bumps one of them, so every prior
        # entry for this partition becomes unreachable (exact
        # invalidation) and ages out of the LRU under pressure
        key = (pid, ckey, applied, eng.data_version)
        ent = self.search_cache.get(key)
        if ent is not None:
            return dict(ent), "hit", None

        def compute():
            out = self._do_search(eng, body, vectors, ctx, trace)
            timing = out.pop("timing", None)
            self.search_cache.put(key, out)
            return out, timing

        (out, timing), coalesced = self._search_flight.do(key, compute)
        if coalesced:
            self.search_cache.note("coalesced")
            return dict(out), "coalesced", None
        return dict(out), "miss", timing

    def _do_search(self, eng, body, vectors, ctx=None,
                   trace: dict | None = None) -> dict:
        columnar = bool(
            body.get("columnar_wire") and body.get("include_fields") == []
        )
        # raw_results skips the microbatcher, so only take the columnar
        # engine shape when the batch is big enough that per-item
        # shaping (not coalescing) is the cost that matters — small
        # concurrent queries keep micro-batching (review r5)
        first = next(iter(vectors.values())) if vectors else None
        rows = (first.shape[0] if first is not None and first.ndim > 1
                else 1)  # router ships [b, d]; a flat array is one query
        raw = columnar and rows >= 32
        req = SearchRequest(
            vectors=vectors,
            k=int(body.get("k", 10)),
            filters=body.get("filters"),
            include_fields=body.get("include_fields"),
            brute_force=bool(body.get("brute_force", False)),
            field_weights=body.get("field_weights") or {},
            index_params=body.get("index_params") or {},
            score_bounds={
                f: tuple(b) for f, b in body["score_bounds"].items()
            } if body.get("score_bounds") else None,
            sort=body.get("sort") or None,
            # columnar wire consumes the engine's columnar shape
            # directly — no per-item objects anywhere on the path
            raw_results=raw,
            trace=trace,
            ctx=ctx,
        )
        results = eng.search(req)
        # shadow recall sampling (docs/QUALITY.md): offer every served
        # row to the deterministic sampler BEFORE wire shaping, so what
        # gets scored is exactly what the client saw. Exact searches are
        # their own ground truth; sort reorders by non-score keys, so
        # recall-vs-score-truth would be meaningless for them. Hooked
        # here (not in _h_search) so cache hits/coalesced followers —
        # which re-serve an already-offered result — never double-count.
        if not req.brute_force and not body.get("sort"):
            try:
                from vearch_tpu.engine.types import ColumnarSearchResults

                pid_q = int(body["partition_id"])
                self._quality.observe_search(
                    pid_q, self._space_key(pid_q), vectors,
                    int(body.get("k", 10)),
                    (results.keys
                     if isinstance(results, ColumnarSearchResults)
                     else results),
                    int(eng.data_version),
                    index_params=body.get("index_params") or {},
                    filters=body.get("filters"),
                    field_weights=body.get("field_weights") or {},
                )
            except Exception as e:  # sampling must never fail a search
                internal_error("ps.quality_sample", e)
        metric = eng.indexes[next(iter(vectors))].metric.value
        if columnar:
            from vearch_tpu.engine.types import ColumnarSearchResults

            # fields-free searches ride columnar: keys as string lists,
            # scores as ONE ndarray over the binary tensor codec —
            # per-item JSON dicts for b=1024*k results were a measured
            # chunk of the e2e batch latency
            if isinstance(results, ColumnarSearchResults):
                out = {
                    "metric": metric,
                    "columnar": True,
                    "keys": results.keys,
                    "scores": np.asarray(results.scores, dtype=np.float32),  # lint: allow[host-sync] terminal result materialization for the wire codec
                }
            else:
                # engine fell back to the item shape (e.g. sort rode in)
                out = {
                    "metric": metric,
                    "columnar": True,
                    "keys": [[it.key for it in r.items] for r in results],
                    "scores": np.asarray(  # lint: allow[host-sync] terminal result materialization for the wire codec
                        [it.score for r in results for it in r.items],
                        dtype=np.float32,
                    ),
                }
        else:
            out = {
                "metric": metric,
                "results": [
                    [
                        {"_id": it.key, "_score": it.score,
                         **({"_sort": it.sort_values}
                            if it.sort_values is not None else {}),
                         **it.fields}
                        for it in r.items
                    ]
                    for r in results
                ],
            }
        if trace is not None:
            out["timing"] = trace
        return out

    def _h_query(self, body: dict, _parts) -> dict:
        eng = self._engine(body["partition_id"])
        self._check_read_consistency(body)
        vv = bool(body.get("vector_value", False))
        if body.get("document_ids"):
            docs = eng.get(body["document_ids"], body.get("fields"), vv)
        else:
            docs = eng.query(
                body.get("filters"),
                limit=int(body.get("limit", 50)),
                offset=int(body.get("offset", 0)),
                include_fields=body.get("fields"),
                vector_value=vv,
                sort=body.get("sort") or None,
            )
        return {"documents": docs}

    def _h_build(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        eng = self._engine(pid)
        if body.get("background"):
            # observable job mode: return immediately, progress and the
            # terminal state are readable at GET /ps/jobs
            threading.Thread(
                target=self._run_build, args=(pid, eng, False),
                daemon=True, name=f"build-p{pid}",
            ).start()
            return {"partition_id": pid, "status": int(eng.status),
                    "background": True}
        self._run_build(pid, eng, False)
        return {"status": int(eng.status)}

    def _run_build(self, pid: int, eng: Engine, rebuild: bool) -> None:
        """Run a build/rebuild and replay its phase windows (train /
        assign / publish / warmup) as spans, so /debug/traces shows the
        job next to the searches it competed with."""
        job = None
        try:
            # index (re)builds legitimately compile: train/assign/
            # publish kernels plus the post-publish warmup pass all
            # specialize here, none of it is a serving-path regression
            with self.flight_recorder.warmup():
                if rebuild:
                    eng.rebuild_index()
                else:
                    eng.build_index()
            # estimator staleness (lint VL105): the serving snapshot
            # just changed under any queued shadow samples
            self._quality.note_index_mutation(
                pid, self._space_key(pid),
                op="rebuild" if rebuild else "build")
        finally:
            job = eng.build_job
            if job is not None:
                op = str(job.get("op", "build"))
                for name, start_us, dur_us in job.get("_phase_spans") or []:
                    tags = {"partition": pid, "op": op}
                    if name == "build.train" and job.get("train_mesh"):
                        # mesh-sharded k-means ran: record the build-time
                        # mesh shape so traces tell sharded trains from
                        # single-device ones
                        tags["train_mesh"] = str(job["train_mesh"])
                    self.tracer.record(
                        name, start_us=start_us, dur_us=dur_us, tags=tags,
                    )

    def _h_jobs(self, _body, _parts) -> dict:
        """Background-job registry: index builds, partition splits, and
        synthesized learner-catchup entries (one per partition this node
        leads that is streaming a raft learner up to date). Internal
        keys (`_phase_spans`, the split mirror queue) are stripped."""
        jobs = []
        for pid, eng in sorted(self.engines.items()):
            job = eng.build_job
            if job is None:
                continue
            jobs.append({
                "partition_id": pid,
                **{k: v for k, v in job.items() if not k.startswith("_")},
            })
        with self._split_lock:
            for pid in sorted(self._split_jobs):
                jobs.append(self._split_public(self._split_jobs[pid]))
        # learner catch-up is raft state, not a registry entry — shape
        # it like a job so one /ps/jobs poll shows every phase of a
        # migration (reference: the master's job rollup reads this)
        for pid, node in sorted(self.raft_nodes.items()):
            if not node.is_leader or not node.learners:
                continue
            st = node.state()
            for learner in node.learners:
                info = st["peers"].get(str(learner))
                if info is None:
                    continue
                jobs.append({
                    "op": "learner_catchup", "partition_id": pid,
                    "status": "running" if info["lag"] else "caught_up",
                    "learner": learner, "lag": info["lag"],
                    "next": info["next"],
                })
        return {"jobs": jobs}

    def _h_slowlog(self, _body, _parts) -> dict:
        return {"threshold_ms": self.slowlog.threshold_ms,
                "entries": self.slowlog.entries()}

    def _h_compiles(self, _body, _parts) -> dict:
        """GET /debug/compiles — the compile-audit flight recorder's
        view: every post-warmup serving-path compilation with its shape
        signature, wall time, and originating trace id."""
        rec = self.flight_recorder
        return {
            "total": rec.total(),
            "counts": rec.counts(),
            "warmup_compiles": rec.warmup_compiles,
            "events": rec.events(),
        }

    def _h_compiles_reset(self, _body, _parts) -> dict:
        """POST /debug/compiles/reset — operator marks 'warmed now':
        after deliberate warmup traffic, zero the recorder so the
        doctor's post-warmup invariant measures only what follows."""
        before = self.flight_recorder.total()
        self.flight_recorder.reset()
        return {"reset": True, "dropped_events": before}

    def _model_device_bytes(self) -> int:
        """Footprint-model side of the drift gauge: modeled per-device
        resident bytes summed over hosted engines' indexes."""
        total = 0
        for eng in list(self.engines.values()):
            for idx in list(getattr(eng, "indexes", {}).values()):
                try:
                    total += int(idx.device_footprint_per_device_bytes())
                except Exception:
                    continue
        return total

    def _space_device_bytes(self) -> dict[str, int]:
        """Per-space split of :meth:`_model_device_bytes` — the same
        engines grouped by owning space, so the values sum to the node
        total exactly (partitions without a known space accrue to the
        `_system` bucket, keeping the conservation identity)."""
        out: dict[str, int] = {}
        for pid, eng in list(self.engines.items()):
            sp = self._space_key(pid)
            n = 0
            for idx in list(getattr(eng, "indexes", {}).values()):
                try:
                    n += int(idx.device_footprint_per_device_bytes())
                except Exception:
                    continue
            out[sp] = out.get(sp, 0) + n
        return out

    def _space_hbm_labelled(self) -> dict[tuple[str, ...], float]:
        """vearch_space_hbm_bytes callback: the per-space residency
        split collapsed under the accountant's top-K label policy."""
        out: dict[tuple[str, ...], float] = {}
        for sp, n in self._space_device_bytes().items():
            key = (self._accountant.label(sp),)
            out[key] = out.get(key, 0.0) + float(n)
        return out

    # -- online partition split (elastic data plane) -------------------------
    #
    # The master drives the lifecycle against the parent's leader:
    #   start -> poll progress until phase=cutover_ready -> flip the
    #   space's partition map (metastore) -> finish{commit} -> delete
    #   the parent everywhere (which finalizes the job here).
    #
    # Correctness contract: from the moment the job enters the sync
    # window, every write the parent acknowledges blocks until the
    # children hold it too (double-write), so cutover_ready means the
    # children are a superset-in-time of the parent. The parent KEEPS
    # sync-mirroring after commit until it is deleted — a router on a
    # stale map may still write through it during the flip window.

    def _count_op(self, pid: int, kind: str) -> None:
        with self._stats_lock:
            c = self._op_counts.setdefault(pid, {"searches": 0,
                                                 "writes": 0})
            c[kind] = c.get(kind, 0) + 1

    def _map_version(self, pid: int) -> int:
        part = self.partitions.get(int(pid))
        return int(getattr(part, "map_version", 0) or 0) \
            if part is not None else 0

    def _split_public(self, job: dict) -> dict:
        """Operator view of a split job: internal keys stripped, queue
        depth surfaced. Callers hold _split_lock."""
        out = {k: v for k, v in job.items() if not k.startswith("_")}
        out["queue"] = len(job["_queue"])
        return out

    def _h_split_start(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        self._engine(pid)
        node = self._node(pid)
        if not node.is_leader:
            raise RpcError(421, f"partition {pid}: split must start on "
                                f"the leader")
        children = [
            {"id": int(c["id"]), "slot_lo": int(c["slot_lo"]),
             "slot_hi": int(c["slot_hi"]), "leader": int(c["leader"])}
            for c in body["children"]
        ]
        if len(children) != 2:
            raise RpcError(400, "split takes exactly two children")
        with self._split_lock:
            existing = self._split_jobs.get(pid)
            if existing is not None and existing["status"] == "running":
                raise RpcError(
                    409, f"split already running for partition {pid}")
            job = {
                "op": "split", "status": "running", "phase": "copy",
                "partition_id": pid, "children": children,
                "docs_total": 0, "docs_done": 0, "mirrored": 0,
                "started": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
                "updated": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
                "phases_ms": {}, "error": None,
                "_queue": deque(), "_sync": False, "_finish": None,
                "_teardown": False,
            }
            self._split_jobs[pid] = job
        threading.Thread(target=self._run_split, args=(pid, job),
                         daemon=True, name=f"split-p{pid}").start()
        return {"partition_id": pid, "status": "running",
                "children": [c["id"] for c in children]}

    def _h_split_progress(self, body, _parts) -> dict:
        q = ((body or {}).get("_query") or {})
        pid = int(q.get("partition_id")
                  or (body or {}).get("partition_id"))
        with self._split_lock:
            job = self._split_jobs.get(pid)
            if job is None:
                raise RpcError(404, f"no split job for partition {pid}")
            return self._split_public(job)

    def _h_split_finish(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        commit = bool(body.get("commit", True))
        with self._split_lock:
            job = self._split_jobs.get(pid)
            if job is None:
                raise RpcError(404, f"no split job for partition {pid}")
            if job["status"] == "running" and job["_finish"] is None:
                if commit and job["phase"] != "cutover_ready":
                    raise RpcError(
                        409, f"split for partition {pid} is not "
                             f"cutover-ready (phase {job['phase']})")
                job["_finish"] = "commit" if commit else "abort"
                self._split_cv.notify_all()
        if commit:
            # cutover moves the space's rows to the children: the
            # parent's accumulated recall stream no longer describes
            # what the space serves (staleness hook, lint VL105)
            self._quality.note_index_mutation(
                pid, self._space_key(pid), op="split")
        # wait for the worker to acknowledge: commit -> phase
        # "committed" (mirror stays open until the parent is deleted);
        # abort -> terminal status
        deadline = time.monotonic() + 30.0  # bounded RPC, not a job clock
        while time.monotonic() < deadline:
            with self._split_lock:
                if ((commit and job["phase"] == "committed")
                        or job["status"] != "running"):
                    return self._split_public(job)
            time.sleep(0.02)
        with self._split_lock:
            return self._split_public(job)

    def _split_teardown(self, pid: int) -> None:
        """Called by partition delete BEFORE the engine goes away: tell
        the worker the parent is being removed and wait for it to drain
        the mirror queue (acked writes must reach the children while
        the parent engine can still be read)."""
        with self._split_lock:
            job = self._split_jobs.get(pid)
            if job is None or job["status"] != "running":
                return
            job["_teardown"] = True
            self._split_cv.notify_all()
        deadline = time.monotonic() + 15.0  # bounded wait, not a job clock
        while time.monotonic() < deadline:
            with self._split_lock:
                if job["status"] != "running":
                    return
            time.sleep(0.02)

    def _split_mirror(self, pid: int, kind: str,
                      keys: list[str]) -> None:
        """Hand a just-committed write's keys to the active split's
        mirror worker. Pre-sync phases enqueue asynchronously (the
        worker drains between copy batches); in the sync/cutover window
        the caller blocks until the entry is forwarded, so the ack the
        client sees implies the children hold the write."""
        ev = None
        with self._split_lock:
            job = self._split_jobs.get(pid)
            if job is None or job["status"] != "running":
                return
            if job["_sync"]:
                ev = threading.Event()
            job["_queue"].append((kind, list(keys), ev))
            self._split_cv.notify_all()
        if ev is not None and not ev.wait(timeout=30.0):
            raise RpcError(
                503, f"partition {pid}: split mirror stalled; write is "
                     f"committed here but not yet on the children — retry")

    def _run_split(self, pid: int, job: dict) -> None:
        t0 = time.monotonic()
        # wall anchor for span epochs; measurement stays monotonic
        wall0 = time.time() - t0  # lint: allow[wall-clock] span epoch anchor, correlates with collector time
        state = {"phase": "copy", "t": t0}

        def enter_phase(name: str) -> None:
            now = time.monotonic()
            prev, t_prev = state["phase"], state["t"]
            self.tracer.record(
                f"split.{prev}",
                start_us=int((wall0 + t_prev) * 1e6),
                dur_us=int((now - t_prev) * 1e6),
                tags={"partition": pid},
            )
            with self._split_lock:
                job["phases_ms"][prev] = round((now - t_prev) * 1e3, 3)
                if name is not None:
                    job["phase"] = name
                job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            state["phase"], state["t"] = name, now

        err: str | None = None
        try:
            eng = self._engine(pid)
            node = self._node(pid)
            # copy: one key snapshot, then batched re-read + forward.
            # Keys only — the docs are re-read at forward time, so a
            # doc updated after the snapshot forwards its LATEST state
            keys = [d["_id"] for d in eng.query(
                None, limit=max(eng.doc_count * 2, 1024),
                include_fields=[], order_by_key=False)]
            with self._split_lock:
                job["docs_total"] = len(keys)
            for i in range(0, len(keys), SPLIT_COPY_BATCH):
                self._split_check_live(pid, job, node)
                self._split_forward(pid, job, "copy",
                                    keys[i:i + SPLIT_COPY_BATCH])
                with self._split_lock:
                    job["docs_done"] = min(i + SPLIT_COPY_BATCH,
                                           len(keys))
                    job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
                # drain concurrent-write mirror entries between batches
                # so the queue stays bounded during a long copy; bounded
                # by the backlog at entry — steady writers refill the
                # queue as fast as we forward, so drain-to-empty would
                # never return (only the sync window's per-write
                # blocking can actually beat a sustained write rate)
                self._split_drain(pid, job, node, block_s=0.0,
                                  max_n=self._split_backlog(job))
            enter_phase("catchup")
            self._split_drain(pid, job, node, block_s=0.0,
                              max_n=self._split_backlog(job))
            # sync window opens: from here every acked write blocks on
            # its own mirror forward; draining the backlog once more
            # makes the children a superset-in-time of the parent
            with self._split_lock:
                job["_sync"] = True
            enter_phase("sync")
            self._split_drain(pid, job, node, block_s=0.0)
            enter_phase("cutover_ready")
            # hold the double-write open until the master commits (the
            # parent's deletion finalizes the job) or aborts (children
            # are garbage-collected by the master)
            while True:
                with self._split_lock:
                    fin = job["_finish"]
                    teardown = job["_teardown"]
                if fin == "abort":
                    raise _SplitAborted("aborted by master")
                if fin == "commit" and state["phase"] == "cutover_ready":
                    enter_phase("committed")
                if teardown or self.engines.get(pid) is None:
                    self._split_drain(pid, job, node, block_s=0.0)
                    if state["phase"] == "committed":
                        break  # normal finalization: parent retired
                    raise _SplitAborted("parent partition removed")
                if self._stop.is_set():
                    raise _SplitAborted("partition server stopping")
                if not node.is_leader:
                    raise _SplitAborted("lost leadership")
                self._split_drain(pid, job, node, block_s=0.25)
        except _SplitAborted as e:
            err = str(e)
        except RpcError as e:
            err = f"rpc {e.code}: {e}"
        except Exception as e:  # job must land terminal, never wedge
            internal_error("ps.split", e)
            err = f"{type(e).__name__}: {e}"
        finally:
            enter_phase(None)  # close the last phase span/window
            with self._split_lock:
                job["status"] = "done" if err is None else "error"
                job["error"] = err
                job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
                # wake every writer still blocked on a sync mirror:
                # their entries are committed on the parent; on abort
                # the children are garbage-collected anyway
                for _, _, ev in job["_queue"]:
                    if ev is not None:
                        ev.set()
                job["_queue"].clear()
                self._split_cv.notify_all()

    def _split_check_live(self, pid: int, job: dict, node) -> None:
        if self._stop.is_set():
            raise _SplitAborted("partition server stopping")
        if self.engines.get(pid) is None:
            raise _SplitAborted("parent partition removed")
        if not node.is_leader:
            raise _SplitAborted("lost leadership")
        with self._split_lock:
            if job["_finish"] == "abort":
                raise _SplitAborted("aborted by master")

    def _split_backlog(self, job: dict) -> int:
        with self._split_lock:
            return len(job["_queue"])

    def _split_drain(self, pid: int, job: dict, node,
                     block_s: float, max_n: int | None = None) -> int:
        """Forward queued mirror entries FIFO. With block_s > 0, waits
        up to that long for a first entry (cutover idle loop); with 0,
        drains whatever is queued and returns. `max_n` bounds the pass
        (pre-sync callers: sustained writers refill as fast as we
        forward, so drain-to-empty would not terminate — once _sync is
        on, writers block per entry and the queue drains for real).
        Entries are popped under _split_lock but forwarded outside it —
        a slow child RPC must not block the write handlers enqueueing
        behind us."""
        n = 0
        while max_n is None or n < max_n:
            with self._split_lock:
                if not job["_queue"] and n == 0 and block_s > 0:
                    self._split_cv.wait(timeout=block_s)
                if not job["_queue"]:
                    return n
                kind, keys, ev = job["_queue"].popleft()
            try:
                self._split_forward(pid, job, kind, keys)
            finally:
                if ev is not None:
                    ev.set()
            with self._split_lock:
                job["mirrored"] += 1
                job["updated"] = time.time()  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            n += 1

    def _split_forward(self, pid: int, job: dict, kind: str,
                       keys: list[str]) -> None:
        """Route keys to their child by hash slot and forward. Upserts
        RE-READ the parent engine at forward time rather than carrying
        a payload from enqueue: the queue is FIFO per key, so the last
        forward for any key ships the parent's current row (or, for a
        key deleted meanwhile, skips it and lets the queued delete do
        the removal) — re-reading makes reordering impossible by
        construction."""
        from vearch_tpu.cluster.hashing import key_slot

        children = job["children"]

        def child_of(key: str) -> dict:
            slot = key_slot(str(key))
            for c in children:
                if c["slot_lo"] <= slot < c["slot_hi"]:
                    return c
            # the two ranges partition the parent's range; a slot
            # outside both means the caller routed a foreign key here
            raise RpcError(
                500, f"split: key {key!r} (slot {slot}) outside both "
                     f"child ranges of partition {pid}")

        if kind == "delete":
            by_child: dict[int, list[str]] = {}
            for k in keys:
                by_child.setdefault(child_of(k)["id"], []).append(k)
            for c in children:
                ks = by_child.get(c["id"])
                if ks:
                    self._split_rpc(c, "/ps/doc/delete",
                                    {"partition_id": c["id"],
                                     "keys": ks})
            return
        eng = self._engine(pid)
        docs = eng.get(keys, None, vector_value=True)
        by_pid: dict[int, list[dict]] = {}
        for d in docs:
            by_pid.setdefault(child_of(str(d["_id"]))["id"], []).append(d)
        for c in children:
            ds = by_pid.get(c["id"])
            if ds:
                self._split_rpc(c, "/ps/doc/upsert",
                                {"partition_id": c["id"],
                                 "documents": ds})

    def _split_rpc(self, child: dict, path: str, body: dict) -> dict:
        """Forward to a child's leader with bounded retries. 400/404
        are structural (bad payload / child gone — the chaos case) and
        fail fast so the master can garbage-collect; transient codes
        retry with a fresh address in case the child's PS moved."""
        last: RpcError | None = None
        for attempt in range(3):
            try:
                addr = (self.addr if child["leader"] == self.node_id
                        else self._peer_addr(child["leader"]))
                return rpc.call(addr, "POST", path, body, timeout=30.0)
            except RpcError as e:
                last = e
                if e.code in (400, 404):
                    break
                time.sleep(0.2 * (attempt + 1))
        raise RpcError(
            503, f"split forward to child {child['id']} failed: {last}")

    def _h_field_index(self, body: dict, _parts) -> dict:
        """Master fan-out target for online scalar field-index add/remove
        (reference: gammacb/gamma.go:538,591 — the PS seam that hands
        AddFieldIndex/RemoveFieldIndex to the engine)."""
        eng = self._engine(body["partition_id"])
        itype = str(body.get("index_type", "INVERTED")).upper()
        if itype == "NONE":
            eng.remove_field_index(body["field"])
        else:
            eng.add_field_index(
                body["field"], itype,
                background=bool(body.get("background", True)),
            )
        return {"field": body["field"], "index_type": itype}

    def _h_schema_field(self, body: dict, _parts) -> dict:
        """Master fan-out target for online scalar-field addition
        (reference: updateSpaceFields -> engine schema update)."""
        from vearch_tpu.engine.types import FieldSchema

        eng = self._engine(body["partition_id"])
        added = []
        for d in body.get("fields", []):
            f = FieldSchema.from_dict(d)
            try:
                eng.add_schema_field(f)
            except ValueError as e:
                raise RpcError(400, str(e)) from None
            added.append(f.name)
        return {"added": added}

    def _h_rebuild(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        eng = self._engine(pid)
        if body.get("background"):
            threading.Thread(
                target=self._run_build, args=(pid, eng, True),
                daemon=True, name=f"rebuild-p{pid}",
            ).start()
            return {"partition_id": pid, "status": int(eng.status),
                    "background": True}
        self._run_build(pid, eng, True)
        return {"status": int(eng.status)}

    def _h_flush(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        applied = self.flush_partition(pid)
        return {"doc_count": self._engine(pid).doc_count,
                "applied": applied}

    def _h_engine_config(self, body: dict, _parts) -> dict:
        cfg = body.get("config") or {}
        if "log_level" in cfg:
            # validate before mutating ANY key — a bad level must not
            # leave the handler half-applied
            try:
                log.parse_level(str(cfg["log_level"]))
            except ValueError as e:
                raise RpcError(400, str(e)) from None
        if "memory_limit_mb" in cfg:
            self.memory_limit_mb = int(cfg["memory_limit_mb"])
        if "slow_request_ms" in cfg:
            # reference: slow_search_time runtime config -> slow killer
            self.slow_request_ms = int(cfg["slow_request_ms"])
        if "slow_route_ms" in cfg:
            # reference: slow-channel isolation threshold (ps/server.go:95)
            self.slow_route_ms = int(cfg["slow_route_ms"])
        if "slow_log_ms" in cfg:
            # slow-query log capture threshold (<=0 disables); killed
            # requests are force-logged regardless
            self.slowlog.threshold_ms = float(cfg["slow_log_ms"])
        if "request_deadline_ms" in cfg:
            # default per-request deadline; a search's own deadline_ms
            # option overrides it per request
            self.request_deadline_ms = int(cfg["request_deadline_ms"])
        if "search_cache_entries" in cfg:
            # runtime-resizable result cache; 0 disables AND drops the
            # live entries (an operator turning the cache off expects
            # no further hits, not a slow drain)
            n = int(cfg["search_cache_entries"])
            self.search_cache.max_entries = n
            if n <= 0:
                self.search_cache.clear()
        if "admission_queue_limit" in cfg:
            # runtime-tunable shed bound; 0 disables shedding
            n = int(cfg["admission_queue_limit"])
            if n < 0:
                raise RpcError(400,
                               "admission_queue_limit must be >= 0")
            self._admission.queue_limit = n
        if "debug_search_delay_ms" in cfg:
            # fault injection (tail-latency tests/bench): per-search
            # killable sleep before any engine work
            self.debug_search_delay_ms = int(cfg["debug_search_delay_ms"])
        if "quality" in cfg:
            # shadow-sampling knobs (docs/QUALITY.md): sample_rate,
            # decay, min_samples, health cadence + drift thresholds
            q = dict(cfg["quality"] or {})
            if "sample_rate" in q and not (
                    0.0 <= float(q["sample_rate"]) <= 1.0):
                raise RpcError(400,
                               "quality.sample_rate must be in [0, 1]")
            self._quality.configure(**q)
        if "log_level" in cfg:
            # runtime log-level flip, fanned out by the master's /config
            # (reference: log-level runtime config in pkg/log)
            log.set_level(str(cfg["log_level"]))
        eng = self._engine(body["partition_id"])
        return eng.apply_config(cfg)

    # -- backup/restore (reference: ps/backup/ps_backup_service.go:77
    #    PSShardManager — shard dump streamed to object storage) -------------

    def _backup_store(self, body: dict):
        """Resolve the object store from the request: legacy store_root
        strings stay local-filesystem; a `store` spec may select s3
        (reference: minio client configured from master config). The
        operator allowlists gate BOTH destination types."""
        from vearch_tpu.cluster.objectstore import is_within, make_object_store

        confined = (self.backup_roots is not None
                    or self.backup_endpoints is not None)
        spec = body.get("store") or body["store_root"]
        if isinstance(spec, str) or spec.get("type", "local") == "local":
            root = spec if isinstance(spec, str) else spec["root"]
            if confined and not any(
                is_within(allowed, root)
                for allowed in (self.backup_roots or [])
            ):
                raise RpcError(403, f"store_root {root!r} not in the "
                                    f"operator backup_roots allowlist")
        else:
            from vearch_tpu.cluster.objectstore import s3_endpoint_host

            host = s3_endpoint_host(str(spec.get("endpoint", "")))
            allowed = {s3_endpoint_host(e)
                       for e in (self.backup_endpoints or [])}
            if confined and host not in allowed:
                raise RpcError(
                    403, f"s3 endpoint {host!r} not in the operator "
                         f"backup_endpoints allowlist"
                )
        return make_object_store(spec)

    def _h_backup(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        self._engine(pid)  # partition must exist before we accept a job
        store = self._backup_store(body)
        job_id = body.get("job_id")
        if job_id is None:
            # synchronous shard backup (original path; the master's
            # async create passes a job_id instead)
            return self._run_shard_backup(pid, store, body, None)
        # async shard backup with progress (reference: PSShardManager
        # jobs, ps/backup/ps_backup_service.go:77,113 — the shard
        # manager tracks per-shard state the progress route reports)
        job = {"job_id": job_id, "partition_id": pid, "status": "dumping",
               "files_done": 0, "files_total": None,
               "started": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
               "updated": time.time(),  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
               "result": None, "error": None}
        from vearch_tpu.utils import prune_job_registry

        with self._backup_jobs_lock:
            jobs = self._backup_jobs
            if job_id in jobs and jobs[job_id]["status"] in (
                    "dumping", "uploading"):
                raise RpcError(409, f"backup job {job_id} already running")
            jobs[job_id] = job
            prune_job_registry(jobs)

        def run():
            try:
                out = self._run_shard_backup(pid, store, body, job)
                job.update(status="done", result=out,
                           updated=time.time())  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally
            except Exception as e:
                job.update(status="error", error=f"{type(e).__name__}: {e}",
                           updated=time.time())  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally

        threading.Thread(target=run, daemon=True,
                         name=f"backup-{job_id}").start()
        return {"partition_id": pid, "job_id": job_id, "status": "dumping"}

    def _run_shard_backup(self, pid: int, store, body: dict,
                          job: dict | None) -> dict:
        import tempfile

        eng = self._engine(pid)

        def progress(done_files: int, total: int) -> None:
            if job is not None:
                job.update(status="uploading", files_done=done_files,
                           files_total=total,
                           updated=time.time())  # lint: allow[wall-clock] operator-facing job timestamp, ordering-only internally

        with tempfile.TemporaryDirectory() as tmp:
            eng.dump(tmp)
            if body.get("pool_prefix"):
                # content-addressed dedup across versions (reference:
                # ref_count_manager.go ref-counted shard files)
                out = store.put_tree_dedup(
                    body["key_prefix"], tmp, body["pool_prefix"],
                    progress=progress,
                )
                return {"partition_id": pid, **out}
            n = store.put_tree(body["key_prefix"], tmp, progress=progress)
        return {"partition_id": pid, "files": n}

    def _h_backup_progress(self, body: dict, _parts) -> dict:
        """Per-shard job state (reference: PS backup progress route,
        ps_backup_service.go:180)."""
        job_id = ((body or {}).get("_query") or {}).get("job_id") \
            or (body or {}).get("job_id")
        with self._backup_jobs_lock:
            if job_id:
                job = self._backup_jobs.get(str(job_id))
                if job is None:
                    raise RpcError(404, f"no backup job {job_id}")
                return dict(job)
            return {"jobs": [dict(j) for j in self._backup_jobs.values()]}

    def _h_restore(self, body: dict, _parts) -> dict:
        pid = int(body["partition_id"])
        eng = self._engine(pid)  # partition must exist (space created first)
        node = self._node(pid)
        store = self._backup_store(body)
        import tempfile

        data_dir = os.path.join(self.data_dir, f"partition_{pid}")
        # download + CRC-verify into a staging dir FIRST: a network
        # failure or integrity error must leave the live partition
        # untouched, not bricked with a wiped directory. Unique staging
        # per call + the flush lock serialise concurrent restores (and
        # keep the flush job from interleaving writes during the swap).
        stage = tempfile.mkdtemp(prefix=f"partition_{pid}.restore.",
                                 dir=self.data_dir)
        try:
            if body.get("pool_prefix"):
                n = store.get_tree_dedup(
                    body["key_prefix"], stage, body["pool_prefix"]
                )
            else:
                n = store.get_tree(body["key_prefix"], stage)
            with self._flush_lock(pid), \
                    node._apply_lock:
                old_version = int(eng.data_version)
                eng.close()
                for name in list(os.listdir(data_dir)):
                    if name in ("raft", "partition.json"):
                        continue
                    p = os.path.join(data_dir, name)
                    shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
                for name in os.listdir(stage):
                    os.replace(os.path.join(stage, name),
                               os.path.join(data_dir, name))
                with self.flight_recorder.warmup():
                    restored = Engine.open(data_dir)
                # restore is a data rewrite the version counters must
                # not hide: a fresh Engine.open restarts data_version
                # at/below the pre-restore value, which would leave
                # version-exact cache keys (PS search cache) and the
                # router's apply-version validity maps believing their
                # pre-restore entries still describe this partition.
                # Force it strictly past everything ever served.
                restored.data_version = (
                    max(int(restored.data_version), old_version) + 1
                )
                restored.start_refresh_loop()
                self._wire_engine(pid, restored)
                with self._lock:
                    self.engines[pid] = restored
                with self._stats_lock:
                    self._mem_dirty = True
                # the restore rewrote the corpus AND the quantizers:
                # reset recall estimators + the train-time recon
                # baseline (staleness hook, lint VL105)
                self._quality.note_index_mutation(
                    pid, self._space_key(pid), op="restore")
                # restored state supersedes the log: reset it at the
                # current applied horizon (a point-in-time rewind).
                # last_term is the term AT last_index, so the horizon
                # stays term-verifiable for subsequent appends
                horizon_term = node.wal.term_at(node.wal.last_index)
                node.wal.reset(node.wal.last_index + 1,
                               horizon_term=horizon_term)
                # lock-fix note: applied is raft-lock-guarded; the old
                # bare write raced the apply loop's applied+1 read
                with node._lock:
                    node.applied = node.wal.last_index
                    node.wal.commit_index = node.wal.last_index
                node.wal.save_meta(fsync=True)
        finally:
            shutil.rmtree(stage, ignore_errors=True)
        return {"partition_id": pid, "files": n,
                "doc_count": restored.doc_count}

    def _h_stats(self, _body, _parts) -> dict:
        with self._stats_lock:
            op_load = {
                "queue_depth": dict(self._op_waiting),
                "inflight": dict(self._op_inflight),
            }
        return {
            "node_id": self.node_id,
            "replication_errors": self.replication_errors,
            "killed_requests": self.killed_requests,
            "slow_routed": self.slow_routed,
            "search_cache": {
                "entries": len(self.search_cache),
                **self.search_cache.stats,
            },
            # runtime truth: last device sample (live HBM, h2d bytes,
            # compiled-program count, footprint-model drift verdict)
            "device_sampler": self.device_sampler.snapshot(),
            # per-(partition, op) streaming tail quantiles; "_node" is
            # the node-level sketch the Prometheus gauge renders
            "latency_quantiles": {
                f"{key[0]}/{key[1]}": rec
                for key, rec in self.latency_quantiles.snapshot().items()
            },
            "op_load": op_load,
            # admission-control counters (sheds, waiters, limit) — the
            # doctor's shed-rate check reads these
            "admission": self._admission.snapshot(),
            # search-quality truth layer: shadow-sampling counters,
            # per-space recall/RBO estimators + floors, index-health
            # drift — the doctor's search_quality check reads this
            "quality": self._quality.stats(),
            # per-tenant cost meters (exact keys, never label-collapsed)
            # + this node's per-space HBM residency split — the same
            # block the heartbeat carries (docs/ACCOUNTING.md)
            "usage": self._usage_summary(),
            # snapshot under no lock: stale reads are fine for stats
            "search_ewma_ms": {
                str(pid): round(ms, 2)
                for pid, ms in dict(self._search_ewma).items()
            },
            "partitions": {
                str(pid): {
                    "doc_count": eng.doc_count,
                    "status": int(eng.status),
                    "memory_bytes": eng.memory_usage_bytes(),
                    "micro_batches": (
                        mb.batches if (mb := eng._microbatcher) is not None
                        else 0
                    ),
                    "micro_batched_requests": (
                        mb.batched_requests if mb is not None else 0
                    ),
                    # continuous-batching scheduler: bucket occupancy,
                    # dispatch mix, padding waste — the doctor's
                    # batch_padding_waste check reads this block
                    "scheduler": self._scheduler_info_safe(eng),
                    "raft": self.raft_nodes[pid].state()
                    if pid in self.raft_nodes else None,
                    "mesh": self._mesh_info_safe(eng),
                    # tiered storage (HBM slab cache / host-RAM tiers /
                    # prefetch) — the doctor's prefetch-effectiveness
                    # check reads these blocks
                    "tiering": self._tiering_info_safe(eng),
                }
                for pid, eng in self.engines.items()
            },
        }

    @staticmethod
    def _mesh_info_safe(eng) -> dict | None:
        try:
            return eng.mesh_info()
        except Exception:
            return None

    @staticmethod
    def _tiering_info_safe(eng) -> dict | None:
        try:
            return eng.tiering_info()
        except Exception:
            return None

    @staticmethod
    def _scheduler_info_safe(eng) -> dict | None:
        try:
            mb = eng._microbatcher
            if mb is None:
                return None
            info = mb.stats()
            real = int(getattr(eng, "pad_real_rows", 0))
            padded = int(getattr(eng, "pad_padded_rows", 0))
            info["pad_real_rows"] = real
            info["pad_padded_rows"] = padded
            info["pad_waste_bytes"] = int(getattr(eng, "pad_waste_bytes", 0))
            info["padding_waste_pct"] = round(
                100.0 * max(padded - real, 0) / max(padded, 1), 2
            )
            return info
        except Exception:
            return None

    # fixed (tier, event) label universe for vearch_ps_tier_events_total
    # — rendered zero-filled every scrape so the cardinality soak sees
    # no series growth as disk tiers warm up
    _TIER_EVENT_KEYS = (
        ("hbm", "hit"), ("hbm", "miss"), ("hbm", "eviction"),
        ("hbm", "pin_hit"), ("hbm", "prefetch_hit"), ("hbm", "prefetched"),
        ("ram", "hit"), ("ram", "miss"), ("ram", "eviction"),
        ("ram", "admitted"), ("ram", "rejected"),
        ("row", "hit"), ("row", "miss"), ("row", "eviction"),
        ("row", "admitted"), ("row", "rejected"),
        ("prefetch", "submitted"), ("prefetch", "completed"),
        ("prefetch", "dropped"), ("prefetch", "error"),
    )
    _CACHE_EVENT_MAP = (
        ("hits", "hit"), ("misses", "miss"), ("evictions", "eviction"),
        ("admitted", "admitted"), ("rejected", "rejected"),
    )

    def _tier_snapshot(self) -> tuple[dict, dict]:
        """(events, resident-bytes) label maps for the tier metrics
        callbacks, summed across hosted engines."""
        events = {k: 0.0 for k in self._TIER_EVENT_KEYS}
        resident = {("hbm",): 0.0, ("ram",): 0.0, ("row",): 0.0}

        def bump(tier: str, stats: dict, mapping) -> None:
            for src, dst in mapping:
                events[(tier, dst)] += float(stats.get(src, 0))

        for eng in list(self.engines.values()):
            info = self._tiering_info_safe(eng)
            if not info:
                continue
            for f in (info.get("fields") or {}).values():
                hbm = f.get("hbm") or {}
                bump("hbm", hbm, (
                    ("hits", "hit"), ("misses", "miss"),
                    ("evictions", "eviction"), ("pin_hits", "pin_hit"),
                    ("prefetch_hits", "prefetch_hit"),
                    ("prefetched", "prefetched"),
                ))
                resident[("hbm",)] += float(hbm.get("resident_bytes", 0))
                ram = f.get("ram") or {}
                bump("ram", ram, self._CACHE_EVENT_MAP)
                resident[("ram",)] += float(ram.get("resident_bytes", 0))
                row = f.get("row_cache") or {}
                bump("row", row, self._CACHE_EVENT_MAP)
                resident[("row",)] += float(row.get("resident_bytes", 0))
                pf = f.get("prefetch") or {}
                bump("prefetch", pf, (
                    ("submitted", "submitted"), ("completed", "completed"),
                    ("dropped", "dropped"), ("errors", "error"),
                ))
        return events, resident

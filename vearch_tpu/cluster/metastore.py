"""Cluster metadata store: KV + watches + leases + sequences.

TPU-native stand-in for the reference's embedded etcd (reference:
internal/master/server.go:89 embedded etcd; client/master_cache.go watch
-driven caches; master/store/distlock.go). Same primitives the reference
leans on — prefix watch, lease-with-TTL liveness, atomic sequences,
mutex.

Replication: every mutation funnels through `_mutate`, which either
applies directly (single-master mode) or hands the op to a `proposer`
(the master's metadata raft group — the analogue of etcd's raft).
`apply_op` is the deterministic state machine executed on every master
replica in log order; watches fire on every replica so watch-driven
caches stay fresh cluster-wide. Leases and locks are deliberately
leader-local (like etcd, lease keepalive is leader state; a new leader
re-grants leases for persisted keys).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable


class MetaStore:
    def __init__(self, persist_path: str | None = None):
        self._kv: dict[str, Any] = {}
        self._lock = threading.RLock()
        self._watches: list[tuple[str, Callable[[str, str, Any], None]]] = []
        self._leases: dict[int, tuple[float, list[str]]] = {}  # id -> (expiry, keys)
        self._next_lease = 1
        self._locks: dict[str, dict] = {}  # leader-local mutex table
        self._persist_path = persist_path
        # when set, mutations are proposed to the metadata log instead
        # of applied locally; the log's apply calls apply_op everywhere
        self.proposer: Callable[[dict], Any] | None = None
        self.applied_index = 0  # maintained by the replicated master
        if persist_path:
            os.makedirs(os.path.dirname(persist_path) or ".", exist_ok=True)
            if os.path.exists(persist_path):
                with open(persist_path) as f:
                    snap = json.load(f)
                # legacy snapshots are the bare kv dict
                if "kv" in snap and isinstance(snap.get("kv"), dict):
                    self._kv = snap["kv"]
                    self.applied_index = int(snap.get("applied", 0))
                else:
                    self._kv = snap

    # -- mutation funnel ------------------------------------------------------

    def _mutate(self, op: dict) -> Any:
        if self.proposer is not None:
            return self.proposer(op)
        return self.apply_op(op)

    def apply_op(self, op: dict) -> Any:
        """Deterministic state machine (runs on every master replica)."""
        t = op.get("t") or op.get("type")  # raft election no-ops use "type"
        if t == "noop":
            return None
        if t == "put":
            return self._do_put(op["key"], op["value"])
        if t == "delete":
            return self._do_delete(op["key"])
        if t == "next_id":
            with self._lock:
                nxt = int(self._kv.get(op["key"], 0)) + 1
                self._kv[op["key"]] = nxt
                self._persist()
                return nxt
        if t == "cas":
            with self._lock:
                if self._kv.get(op["key"]) != op["expect"]:
                    return False
                self._kv[op["key"]] = op["value"]
                self._persist()
                return True
        raise ValueError(f"unknown metastore op {t!r}")

    def _do_put(self, key: str, value: Any) -> None:
        with self._lock:
            self._kv[key] = value
            self._persist()
            watchers = [(p, cb) for p, cb in self._watches
                        if key.startswith(p)]
        for _, cb in watchers:
            cb("PUT", key, value)

    def _do_delete(self, key: str) -> bool:
        with self._lock:
            existed = key in self._kv
            self._kv.pop(key, None)
            self._persist()
            watchers = [(p, cb) for p, cb in self._watches
                        if key.startswith(p)]
        if existed:
            for _, cb in watchers:
                cb("DELETE", key, None)
        return existed

    # -- KV ------------------------------------------------------------------

    def put(self, key: str, value: Any, lease: int | None = None) -> None:
        self._mutate({"t": "put", "key": key, "value": value})
        if lease is not None:
            with self._lock:
                if lease in self._leases:
                    self._leases[lease][1].append(key)

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._kv.get(key, default)

    def delete(self, key: str) -> bool:
        return bool(self._mutate({"t": "delete", "key": key}))

    def prefix(self, prefix: str) -> dict[str, Any]:
        with self._lock:
            return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    def cas(self, key: str, expect: Any, value: Any) -> bool:
        """Compare-and-swap (reference: etcd STM transactions)."""
        return bool(self._mutate(
            {"t": "cas", "key": key, "expect": expect, "value": value}
        ))

    # -- watches (reference: client/master_cache.go:414) ---------------------

    def watch_prefix(self, prefix: str, cb: Callable[[str, str, Any], None]) -> None:
        with self._lock:
            self._watches.append((prefix, cb))

    # -- sequences (reference: etcd sequence for space/partition/node ids) ---

    def next_id(self, seq_key: str) -> int:
        return int(self._mutate({"t": "next_id", "key": seq_key}))

    # -- leases (leader-local; reference: etcd leases are leader state) ------

    def grant_lease(self, ttl_s: float) -> int:
        with self._lock:
            lease = self._next_lease
            self._next_lease += 1
            self._leases[lease] = (time.monotonic() + ttl_s, [])
            return lease

    def revoke_lease(self, lease: int) -> None:
        """Drop a lease WITHOUT deleting its keys (used when a new lease
        supersedes it — e.g. re-adoption after a leader change; letting
        the stale lease expire would delete keys the new lease owns)."""
        with self._lock:
            self._leases.pop(lease, None)

    def keepalive(self, lease: int, ttl_s: float) -> bool:
        with self._lock:
            if lease not in self._leases:
                return False
            self._leases[lease] = (time.monotonic() + ttl_s, self._leases[lease][1])
            return True

    def expire_leases(self) -> list[str]:
        """Drop expired leases; returns the keys deleted (the master's
        failure-detection tick — reference: lease expiry fires the
        server-watch DELETE, master_cache.go:963). The deletions
        replicate through the log like any other mutation."""
        now = time.monotonic()
        with self._lock:
            dead = [lid for lid, (exp, _) in self._leases.items() if exp < now]
            doomed: list[str] = []
            for lid in dead:
                doomed.extend(self._leases.pop(lid)[1])
        for key in doomed:
            self.delete(key)
        return doomed

    # -- distributed lock (leader-local: only the leader executes
    #    mutating handlers; reference: master/store/distlock.go) ------------

    def try_lock(self, name: str, owner: str, ttl_s: float = 30.0) -> bool:
        with self._lock:
            cur = self._locks.get(name)
            if cur is not None and cur["expiry"] > time.monotonic() \
                    and cur["owner"] != owner:
                return False
            self._locks[name] = {"owner": owner,
                                 "expiry": time.monotonic() + ttl_s}
            return True

    def unlock(self, name: str, owner: str) -> None:
        with self._lock:
            cur = self._locks.get(name)
            if cur is not None and cur["owner"] == owner:
                self._locks.pop(name, None)

    def clean_expired_locks(self) -> tuple[list[str], list[str]]:
        """(cleaned, still-held) lock names. Runs under the store lock so
        the sweep cannot race a concurrent try_lock re-acquiring a name
        it just judged expired."""
        with self._lock:
            now = time.monotonic()
            cleaned = [n for n, c in self._locks.items()
                       if c["expiry"] <= now]
            for n in cleaned:
                self._locks.pop(n, None)
            return cleaned, sorted(self._locks)

    # -- snapshots (replicated mode: checkpoint + log truncation) ------------

    def snapshot_bytes(self) -> bytes:
        with self._lock:
            return json.dumps(
                {"kv": self._kv, "applied": self.applied_index}
            ).encode()

    def install_snapshot(self, data: bytes) -> None:
        snap = json.loads(data)
        with self._lock:
            self._kv = snap["kv"]
            self.applied_index = int(snap.get("applied", 0))
            self._persist()

    def _persist(self) -> None:
        if self._persist_path:
            tmp = self._persist_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"kv": self._kv, "applied": self.applied_index}, f)
            os.replace(tmp, self._persist_path)

"""Cluster metadata store: KV + watches + leases + sequences.

TPU-native stand-in for the reference's embedded etcd (reference:
internal/master/server.go:89 embedded etcd; client/master_cache.go watch
-driven caches; master/store/distlock.go). Same primitives the reference
leans on — prefix watch, lease-with-TTL liveness, atomic sequences,
mutex — implemented in-process for the master role. Multi-master
replication of the metastore itself is a later-round concern (the
reference delegates it to etcd raft); the interface is shaped so a raft
log can slide underneath without touching callers.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable


class MetaStore:
    def __init__(self, persist_path: str | None = None):
        self._kv: dict[str, Any] = {}
        self._lock = threading.RLock()
        self._watches: list[tuple[str, Callable[[str, str, Any], None]]] = []
        self._leases: dict[int, tuple[float, list[str]]] = {}  # id -> (expiry, keys)
        self._next_lease = 1
        self._persist_path = persist_path
        if persist_path:
            os.makedirs(os.path.dirname(persist_path) or ".", exist_ok=True)
            if os.path.exists(persist_path):
                with open(persist_path) as f:
                    self._kv = json.load(f)

    # -- KV ------------------------------------------------------------------

    def put(self, key: str, value: Any, lease: int | None = None) -> None:
        with self._lock:
            self._kv[key] = value
            if lease is not None and lease in self._leases:
                self._leases[lease][1].append(key)
            self._persist()
            watchers = [(p, cb) for p, cb in self._watches if key.startswith(p)]
        for _, cb in watchers:
            cb("PUT", key, value)

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._kv.get(key, default)

    def delete(self, key: str) -> bool:
        with self._lock:
            existed = key in self._kv
            self._kv.pop(key, None)
            self._persist()
            watchers = [(p, cb) for p, cb in self._watches if key.startswith(p)]
        if existed:
            for _, cb in watchers:
                cb("DELETE", key, None)
        return existed

    def prefix(self, prefix: str) -> dict[str, Any]:
        with self._lock:
            return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    def cas(self, key: str, expect: Any, value: Any) -> bool:
        """Compare-and-swap (reference: etcd STM transactions)."""
        with self._lock:
            if self._kv.get(key) != expect:
                return False
            self._kv[key] = value
            self._persist()
        return True

    # -- watches (reference: client/master_cache.go:414) ---------------------

    def watch_prefix(self, prefix: str, cb: Callable[[str, str, Any], None]) -> None:
        with self._lock:
            self._watches.append((prefix, cb))

    # -- sequences (reference: etcd sequence for space/partition/node ids) ---

    def next_id(self, seq_key: str) -> int:
        with self._lock:
            nxt = int(self._kv.get(seq_key, 0)) + 1
            self._kv[seq_key] = nxt
            self._persist()
            return nxt

    # -- leases (reference: PS registration lease, server.go:228) ------------

    def grant_lease(self, ttl_s: float) -> int:
        with self._lock:
            lease = self._next_lease
            self._next_lease += 1
            self._leases[lease] = (time.time() + ttl_s, [])
            return lease

    def keepalive(self, lease: int, ttl_s: float) -> bool:
        with self._lock:
            if lease not in self._leases:
                return False
            self._leases[lease] = (time.time() + ttl_s, self._leases[lease][1])
            return True

    def expire_leases(self) -> list[str]:
        """Drop expired leases; returns the keys deleted (the master's
        failure-detection tick — reference: lease expiry fires the
        server-watch DELETE, master_cache.go:963)."""
        now = time.time()
        with self._lock:
            dead = [lid for lid, (exp, _) in self._leases.items() if exp < now]
            doomed: list[str] = []
            for lid in dead:
                doomed.extend(self._leases.pop(lid)[1])
        for key in doomed:
            self.delete(key)
        return doomed

    # -- distributed lock (reference: master/store/distlock.go) --------------

    def try_lock(self, name: str, owner: str, ttl_s: float = 30.0) -> bool:
        key = f"/lock/{name}"
        with self._lock:
            cur = self._kv.get(key)
            if cur is not None and cur["expiry"] > time.time() and cur["owner"] != owner:
                return False
            self._kv[key] = {"owner": owner, "expiry": time.time() + ttl_s}
            return True

    def unlock(self, name: str, owner: str) -> None:
        key = f"/lock/{name}"
        with self._lock:
            cur = self._kv.get(key)
            if cur is not None and cur["owner"] == owner:
                self._kv.pop(key, None)

    def _persist(self) -> None:
        if self._persist_path:
            tmp = self._persist_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._kv, f)
            os.replace(tmp, self._persist_path)

"""Murmur3-32 and slot-range partitioning.

Byte-compatible with the reference's doc routing (reference:
internal/client/client.go:245 `murmur3.Sum32WithSeed([]byte(doc.PKey), 0)`
and entity/space.go:153 `Space.PartitionId` binary search over partition
slot starts carved as i * (MaxUint32 / partition_num),
master/services/space_service.go:158).
"""

from __future__ import annotations

MAX_UINT32 = 0xFFFFFFFF


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86 32-bit (same algorithm as spaolacci/murmur3 Sum32)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & MAX_UINT32
    length = len(data)
    rounded = length - (length % 4)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & MAX_UINT32
        k = ((k << 15) | (k >> 17)) & MAX_UINT32
        k = (k * c2) & MAX_UINT32
        h ^= k
        h = ((h << 13) | (h >> 19)) & MAX_UINT32
        h = (h * 5 + 0xE6546B64) & MAX_UINT32
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & MAX_UINT32
        k = ((k << 15) | (k >> 17)) & MAX_UINT32
        k = (k * c2) & MAX_UINT32
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & MAX_UINT32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & MAX_UINT32
    h ^= h >> 16
    return h


def key_slot(key: str) -> int:
    return murmur3_32(key.encode("utf-8"), 0)


def carve_slots(partition_num: int) -> list[int]:
    """Slot start per partition (reference: space_service.go:158)."""
    width = MAX_UINT32 // partition_num
    return [i * width for i in range(partition_num)]


def partition_for_slot(slot_starts: list[int], slot: int) -> int:
    """Index of the partition owning `slot` (binary search over starts —
    reference: entity/space.go:153)."""
    if len(slot_starts) == 1:
        return 0
    lo, hi = 0, len(slot_starts) - 1
    while lo <= hi:
        mid = (lo + hi) >> 1
        v = slot_starts[mid]
        if v > slot:
            hi = mid - 1
        elif v < slot:
            lo = mid + 1
        else:
            return mid
    return lo - 1
